"""SLO-class analytics over a serving RunLog: THE one reader.

Everything downstream of a serving run's RunLog — `tools_serving_report.py`
(the dedicated CLI), `tools_obs_report.py`'s serving section, and the
chaos harness's serving recovery report — parses ``serve`` events and
``span`` records through this module, so there is exactly one place that
knows the record schemas (no second RunLog parser, the PR 10
one-tokenizer discipline applied to serving telemetry).

The report answers the questions aggregate histograms cannot:

* **per-class percentiles** — TTFT / e2e / queue wait / mean token gap
  split by `SLOClass` (serving/request.py),
* **SLO attainment** — the fraction of each class's finished requests
  that met their TTFT and token-gap targets (a dimension without a
  target is vacuously attained; the default class attains 1.0),
* **goodput** — tokens/s counted only from requests that finished
  within their class SLO (the Hetis-style metric: violating traffic
  produces load, not goodput),
* **stall attribution** — how queue time divides across the
  scheduler's reserve-on-admit reasons (``no_slot`` / ``no_pages`` /
  ``preempted`` / ``quota_exceeded``, read from the queued spans),
* **per-tenant accounting** — the class table re-grouped by
  ``Request.tenant`` (attainment/goodput per tenant) plus the cost
  ledger's per-tenant ``cost_*`` sums (serving/costs.py) when the run
  priced requests,
* **reconciliation** — per request, queued + prefill + decode + pause
  span durations vs the recorded ``e2e_s`` (the acceptance property:
  within one engine-step quantum; exact by the tracer's tiling
  construction),
* **critical path** — the stitched fleet traces (`FleetTrace.stitch`)
  decomposed into exclusive latency segments (obs/critpath.py) and
  rolled up per SLO class and tenant: where TTFT and e2e actually
  went, summing to the recorded latencies with zero residual
  (docs/observability.md, Distributed tracing),
* **fault accounting** — the failover / deadline / brownout sections
  (docs/fault_tolerance.md): replica deaths and per-class retry counts
  (HETU_TPU_SERVE_RETRY), deadline expiries and the tokens they
  discarded (HETU_TPU_SERVE_DEADLINE), and brownout sheds per class
  (HETU_TPU_SERVE_BROWNOUT),
* **disaggregated serving** — the ``disagg`` section
  (HETU_TPU_SERVE_DISAGG): KV shipments/resends on the prefill->decode
  wire, re-prefills per class and degraded-mode (colocated-fallback)
  seconds; and the ``frontend`` section: replica down/drain/rejoin
  transitions plus hedged re-dispatches and hedge wins
  (HETU_TPU_SERVE_HEDGE).

Span-derived fields degrade gracefully: with ``HETU_TPU_SERVE_TRACE``
unset there are no span records, and the report still renders the
per-class percentile/attainment tables from the ``done`` events alone
(token-gap attainment then uses e2e-derived mean gaps).

Sampled RunLogs (``HETU_TPU_RUNLOG_SERVE_SAMPLE`` > 1) stay unbiased:
each sampled done event carries ``sample_weight=N`` and every count/
token-sum/attainment fraction here re-weights by it — only the latency
percentiles stay unweighted (rid sampling is uniform, so the sampled
rows are already a uniform draw of the population).

Pure host-side record munging — no jax, no device contact.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from hetu_tpu.obs.metrics import percentile_of_sorted
from hetu_tpu.obs.spans import FleetTrace, RequestTrace, collect_traces
from hetu_tpu.serving.costs import COST_FIELDS, aggregate_costs

#: bump when the report dict shape changes incompatibly (pinned by the
#: CLI smoke tests)
REPORT_SCHEMA = 1


# ---------------------------------------------------------------------------
# the one reader
# ---------------------------------------------------------------------------

def collect(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Group a RunLog's serving records: ``serve`` events by kind plus
    the per-request span traces.  Every serving-report consumer starts
    here."""
    records = list(records)
    serves = [r for r in records if r.get("kind") == "serve"]
    return {
        "admits": [r for r in serves if r.get("event") == "admit"],
        "dones": [r for r in serves if r.get("event") == "done"],
        "reshards": [r for r in serves if r.get("event") == "reshard"],
        "reports": [r for r in serves if r.get("event") == "report"],
        "preempts": [r for r in serves if r.get("event") == "preempt"],
        # the fault-tolerance layer's events (docs/fault_tolerance.md):
        # engine failovers, per-request replica-loss requeues, and the
        # three fault terminations (retry_exhausted rides `evict`,
        # deadline_exceeded rides `expired`, brownout_shed rides `shed`)
        "failovers": [r for r in serves if r.get("event") == "failover"],
        "retries": [r for r in serves if r.get("event") == "retry"],
        "faults": [r for r in serves
                   if r.get("event") in ("evict", "expired", "shed")],
        # the disaggregated-serving layer (serving/disagg.py): KV
        # shipments on the prefill->decode wire and the degraded-mode
        # (colocated-fallback) enter/exit transitions
        "ships": [r for r in serves if r.get("event") == "ship"],
        "degraded": [r for r in serves if r.get("event") == "degraded"],
        # the multi-replica frontend (serving/frontend.py): replica
        # state changes and hedged re-dispatches
        "replicas": [r for r in serves if r.get("event") == "replica"],
        "hedges": [r for r in serves
                   if r.get("event") in ("hedge", "hedge_win")],
        "traces": collect_traces(records),
        # the stitched fleet DAG (obs/spans.py): EVERY (rid, trace) hop
        # — hedge losers and prefill-tier incarnations included — plus
        # the causal edges.  Raises ValueError on mixed clock bases:
        # a driver-clock and a wall-clock log cannot share a timeline.
        "stitched": FleetTrace.stitch(records),
        "anomalies": [r for r in records if r.get("kind") == "anomaly"],
    }


def request_rows(collected: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One row per finished request: the ``done`` event's SLO timeline
    joined with its span trace (when one was recorded).  ``*_ok``
    fields judge the class targets; ``residual_s`` is the
    span-vs-e2e reconciliation gap (None without spans)."""
    traces: Dict[int, RequestTrace] = collected["traces"]
    admits = {a.get("req"): a for a in collected["admits"]}
    rows = []
    for d in collected["dones"]:
        rid = d.get("req")
        ttft = d.get("ttft_s")
        e2e = d.get("e2e_s")
        tokens = d.get("tokens") or 0
        ttft_target = d.get("slo_ttft_s")
        gap_target = d.get("slo_token_gap_s")
        tr = traces.get(rid)
        row: Dict[str, Any] = {
            "rid": rid,
            "slo_class": str(d.get("slo_class", "default")),
            "tenant": str(d.get("tenant") or "default"),
            "ttft_s": ttft, "e2e_s": e2e, "tokens": tokens,
            "reason": d.get("reason"),
            "ttft_target_s": ttft_target, "token_gap_target_s": gap_target,
        }
        if d.get("sample_weight") is not None:
            # a sampled RunLog (HETU_TPU_RUNLOG_SERVE_SAMPLE): this row
            # stands for N requests — every count/sum below re-weights
            row["sample_weight"] = d["sample_weight"]
        for k in COST_FIELDS:
            # per-request cost ledger fields (serving/costs.py) ride the
            # done event when the engine ran with a CostModel
            if d.get(k) is not None:
                row[k] = d[k]
        if tr is not None and tr.terminal is not None:
            row["queued_s"] = tr.duration_s("queued")
            row["prefill_s"] = tr.duration_s("prefill")
            row["decode_s"] = tr.duration_s("decode")
            row["pause_s"] = tr.duration_s("reshard_pause")
            row["stall_reason"] = tr.stall_reason
            row["segments"] = len(tr.by_kind("decode"))
            row["residual_s"] = tr.reconcile(e2e)
            # mean USER-VISIBLE gap: pauses count (a reshard freeze is
            # latency the user sits through), so the traced number
            # equals the spanless fallback's (e2e-ttft)/(n-1) and
            # attainment cannot change with the tracing flag
            row["token_gap_s"] = ((row["decode_s"] + row["pause_s"])
                                  / (tokens - 1) if tokens > 1 else None)
        else:
            admit = admits.get(rid, {})
            row["queued_s"] = admit.get("queue_wait_s")
            row["stall_reason"] = None
            row["residual_s"] = None
            row["token_gap_s"] = ((e2e - ttft) / (tokens - 1)
                                  if (e2e is not None and ttft is not None
                                      and tokens > 1) else None)
        row["ttft_ok"] = (ttft_target is None or
                          (ttft is not None and ttft <= ttft_target))
        # no measurable gap (single-token request, or a spanless log
        # missing the timeline) is vacuous attainment, not a miss —
        # there is no inter-token gap to violate
        row["gap_ok"] = (gap_target is None
                         or row["token_gap_s"] is None
                         or row["token_gap_s"] <= gap_target)
        row["slo_ok"] = row["ttft_ok"] and row["gap_ok"]
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def _pcts(vals: List[float]) -> Optional[Dict[str, float]]:
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    return {"p50": percentile_of_sorted(vals, 50),
            "p95": percentile_of_sorted(vals, 95),
            "max": vals[-1]}


def _elapsed_s(collected: Dict[str, Any],
               rows: List[Dict[str, Any]]) -> Optional[float]:
    """The run's driver-clock span: the final ``report`` event when the
    run wrote one, else [earliest arrival, latest done] from the done
    events' ``now`` stamps."""
    if collected["reports"]:
        v = collected["reports"][-1].get("elapsed_s")
        if v:
            return float(v)
    ends = [d.get("now") for d in collected["dones"]
            if d.get("now") is not None]
    starts = [d["now"] - d["e2e_s"] for d in collected["dones"]
              if d.get("now") is not None and d.get("e2e_s") is not None]
    if not ends or not starts:
        return None
    return max(1e-9, max(ends) - min(starts))


def _weight(r: Dict[str, Any]) -> float:
    """How many requests this row stands for (sample_weight on sampled
    RunLogs, 1 otherwise)."""
    return float(r.get("sample_weight") or 1.0)


def _int_if_whole(v: float):
    """Weighted counts render as ints when they are whole (every
    unsampled log), so pre-sampling report consumers see no shape
    change."""
    return int(v) if float(v).is_integer() else v


def _group_section(rs: List[Dict[str, Any]], elapsed_s: Optional[float],
                   *, targets: bool) -> Dict[str, Any]:
    """One aggregate table section over a row group (a class or a
    tenant): weighted counts/attainment/goodput, unweighted latency
    percentiles (rid-sampling is uniform, so the sampled rows ARE a
    uniform draw — re-weighting would not change the order
    statistics)."""
    n_w = sum(_weight(r) for r in rs)
    tokens = sum(r["tokens"] * _weight(r) for r in rs)
    good_tokens = sum(r["tokens"] * _weight(r) for r in rs if r["slo_ok"])
    sec: Dict[str, Any] = {
        "requests": _int_if_whole(n_w),
        "tokens_out": _int_if_whole(tokens),
        "ttft_s": _pcts([r["ttft_s"] for r in rs]),
        "e2e_s": _pcts([r["e2e_s"] for r in rs]),
        "queue_wait_s": _pcts([r.get("queued_s") for r in rs]),
        "token_gap_s": _pcts([r.get("token_gap_s") for r in rs]),
        "attainment": {
            "ttft": sum(_weight(r) for r in rs if r["ttft_ok"]) / n_w,
            "token_gap": sum(_weight(r) for r in rs if r["gap_ok"]) / n_w,
            "slo": sum(_weight(r) for r in rs if r["slo_ok"]) / n_w,
        },
        "goodput_tokens": _int_if_whole(good_tokens),
    }
    if targets:
        sec["targets"] = {"ttft_s": rs[0]["ttft_target_s"],
                          "token_gap_s": rs[0]["token_gap_target_s"]}
    if elapsed_s:
        sec["goodput_tokens_per_s"] = good_tokens / elapsed_s
        sec["tokens_per_s"] = tokens / elapsed_s
    return sec


def class_report(rows: List[Dict[str, Any]],
                 elapsed_s: Optional[float]) -> Dict[str, Dict[str, Any]]:
    """Per-class table: counts, latency percentiles, attainment
    fractions, goodput."""
    by_cls: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        by_cls.setdefault(row["slo_class"], []).append(row)
    return {cls: _group_section(by_cls[cls], elapsed_s, targets=True)
            for cls in sorted(by_cls)}


def tenant_report(rows: List[Dict[str, Any]],
                  elapsed_s: Optional[float]
                  ) -> Optional[Dict[str, Dict[str, Any]]]:
    """Per-tenant table (same shape as the class table, minus targets —
    a tenant may mix classes).  None when every request is the default
    tenant: tenant-free logs keep their report shape."""
    if all(r["tenant"] == "default" for r in rows):
        return None
    by_t: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        by_t.setdefault(row["tenant"], []).append(row)
    return {t: _group_section(by_t[t], elapsed_s, targets=False)
            for t in sorted(by_t)}


def spec_decode_report(collected: Dict[str, Any]
                       ) -> Optional[Dict[str, Any]]:
    """Speculative-decoding acceptance over a run (from the ``done``
    events' spec counters): drafts proposed/accepted, the measured
    per-draft acceptance rate, and the mean tokens emitted per verify
    step it implies — the number the analytic roofline in
    bench.py detail.serving prices.  None when the run never drafted."""
    dones = [d for d in collected["dones"] if d.get("spec_proposed")]
    if not dones:
        return None
    proposed = sum(int(d.get("spec_proposed") or 0) for d in dones)
    accepted = sum(int(d.get("spec_accepted") or 0) for d in dones)
    return {
        "requests": len(dones),
        "drafts_proposed": proposed,
        "drafts_accepted": accepted,
        "acceptance_rate": accepted / proposed if proposed else 0.0,
    }


def prefix_cache_report(collected: Dict[str, Any]
                        ) -> Optional[Dict[str, Any]]:
    """Radix-cache effectiveness (from the ``admit`` events):
    admissions that hit, prompt tokens admitted already-resident, and
    the prefill-token fraction the cache eliminated.  None when no
    admit event carries the field (pre-cache logs degrade
    gracefully)."""
    admits = [a for a in collected["admits"]
              if a.get("shared_tokens") is not None]
    if not admits:
        return None
    shared = sum(int(a.get("shared_tokens") or 0) for a in admits)
    prompt = sum(int(a.get("prompt_len") or 0) for a in admits)
    hits = sum(1 for a in admits if (a.get("shared_tokens") or 0) > 0)
    return {
        "admits": len(admits),
        "hits": hits,
        "hit_rate": hits / len(admits),
        "shared_tokens": shared,
        "prompt_tokens": prompt,
        "prefill_tokens_saved_frac": shared / prompt if prompt else 0.0,
    }


def preemption_report(collected: Dict[str, Any]
                      ) -> Optional[Dict[str, Any]]:
    """Who preempted whom (from the ``preempt`` events): counts per
    victim class and per preemptor class."""
    pre = collected["preempts"]
    if not pre:
        return None
    victims: Dict[str, int] = {}
    by: Dict[str, int] = {}
    for p in pre:
        victims[str(p.get("slo_class", "default"))] = \
            victims.get(str(p.get("slo_class", "default")), 0) + 1
        by[str(p.get("by_class", "default"))] = \
            by.get(str(p.get("by_class", "default")), 0) + 1
    return {"preemptions": len(pre), "victim_classes": victims,
            "preemptor_classes": by}


def failover_report(collected: Dict[str, Any]
                    ) -> Optional[Dict[str, Any]]:
    """Replica-death recovery accounting (from the ``failover`` and
    ``retry`` events plus the ``done`` events' folded retry counts):
    engine failovers, requests requeued under HETU_TPU_SERVE_RETRY,
    budget exhaustions, and which classes paid the retries.  None when
    the run never failed over."""
    fo = collected["failovers"]
    retries = collected["retries"]
    if not fo and not retries:
        return None
    by_cls: Dict[str, float] = {}
    for r in retries:
        k = str(r.get("slo_class", "default"))
        by_cls[k] = by_cls.get(k, 0) + _weight(r)
    finished_retried = sum(
        _weight(d) for d in collected["dones"] if d.get("retries"))
    exhausted = [f for f in collected["faults"]
                 if f.get("reason") == "retry_exhausted"]
    return {
        "failovers": len(fo),
        "requeued": sum(int(f.get("requeued") or 0) for f in fo),
        "retry_exhausted": _int_if_whole(
            sum(_weight(f) for f in exhausted)),
        "retried_by_class": {k: _int_if_whole(v)
                             for k, v in sorted(by_cls.items())},
        "finished_after_retry": _int_if_whole(finished_retried),
    }


def deadline_report(collected: Dict[str, Any]
                    ) -> Optional[Dict[str, Any]]:
    """Deadline enforcement (HETU_TPU_SERVE_DEADLINE, from the
    ``expired`` events): requests expired per class and the decode
    tokens discarded with them.  None when nothing expired."""
    exp = [f for f in collected["faults"] if f.get("event") == "expired"]
    if not exp:
        return None
    by_cls: Dict[str, float] = {}
    for f in exp:
        k = str(f.get("slo_class", "default"))
        by_cls[k] = by_cls.get(k, 0) + _weight(f)
    return {
        "expired": _int_if_whole(sum(_weight(f) for f in exp)),
        "by_class": {k: _int_if_whole(v)
                     for k, v in sorted(by_cls.items())},
        "tokens_discarded": _int_if_whole(
            sum((f.get("tokens") or 0) * _weight(f) for f in exp)),
    }


def brownout_report(collected: Dict[str, Any]
                    ) -> Optional[Dict[str, Any]]:
    """Brownout shedding (HETU_TPU_SERVE_BROWNOUT, from the ``shed``
    events): queued requests shed per class — always the
    lowest-priority band present at each firing.  None when the policy
    never fired."""
    shed = [f for f in collected["faults"] if f.get("event") == "shed"]
    if not shed:
        return None
    by_cls: Dict[str, float] = {}
    for f in shed:
        k = str(f.get("slo_class", "default"))
        by_cls[k] = by_cls.get(k, 0) + _weight(f)
    return {
        "shed": _int_if_whole(sum(_weight(f) for f in shed)),
        "by_class": {k: _int_if_whole(v)
                     for k, v in sorted(by_cls.items())},
    }


def disagg_report(collected: Dict[str, Any]
                  ) -> Optional[Dict[str, Any]]:
    """Disaggregated prefill/decode accounting (serving/disagg.py, from
    the ``ship``/``degraded``/``retry`` events): KV shipments over the
    acked wire with their resend tally, re-prefills billed to the retry
    budget (``retry`` events carrying ``ship=True``) per class, and the
    degraded-mode (colocated-fallback) entries with their metered
    seconds.  None when the run never shipped or degraded — colocated
    logs keep their report shape."""
    ships = collected["ships"]
    degraded = collected["degraded"]
    if not ships and not degraded:
        return None
    reprefills = [r for r in collected["retries"] if r.get("ship")]
    by_cls: Dict[str, float] = {}
    for r in reprefills:
        k = str(r.get("slo_class", "default"))
        by_cls[k] = by_cls.get(k, 0) + _weight(r)
    entries = sum(1 for d in degraded if d.get("state") == "enter")
    degraded_s = sum(float(d.get("degraded_s") or 0.0)
                     for d in degraded if d.get("state") == "exit")
    return {
        "shipments": len(ships),
        "resends": sum(1 for s in ships if s.get("resend")),
        "reprefills": _int_if_whole(
            sum(_weight(r) for r in reprefills)),
        "reprefills_by_class": {k: _int_if_whole(v)
                                for k, v in sorted(by_cls.items())},
        "degraded_entries": entries,
        "degraded_s": degraded_s,
    }


def frontend_report(collected: Dict[str, Any]
                    ) -> Optional[Dict[str, Any]]:
    """Multi-replica frontend accounting (serving/frontend.py, from the
    ``replica``/``hedge``/``hedge_win`` events): replica health
    transitions (down / drain / rejoin) and hedged re-dispatches with
    how many the hedge copy actually won.  None when the log carries no
    frontend events — single-replica logs keep their report shape."""
    replicas = collected["replicas"]
    hedges = collected["hedges"]
    if not replicas and not hedges:
        return None
    states: Dict[str, int] = {}
    for r in replicas:
        k = str(r.get("state", "unknown"))
        states[k] = states.get(k, 0) + 1
    hedged = [h for h in hedges if h.get("event") == "hedge"]
    wins = [h for h in hedges if h.get("event") == "hedge_win"]
    return {
        "replica_events": dict(sorted(states.items())),
        "replicas_down": states.get("down", 0),
        "hedges": len(hedged),
        "hedge_wins": len(wins),
        "hedge_waited_steps": _pcts(
            [h.get("waited_steps") for h in hedged]),
    }


def stall_breakdown(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """How queued time attributes across the scheduler's stall reasons
    (span-traced runs only): request counts and total queued seconds per
    reason."""
    traced = [r for r in rows if r.get("stall_reason") is not None]
    if not traced:
        return None
    counts: Dict[str, float] = {}
    waited: Dict[str, float] = {}
    for r in traced:
        reason = r["stall_reason"]
        w = _weight(r)
        counts[reason] = counts.get(reason, 0) + w
        waited[reason] = (waited.get(reason, 0.0)
                          + (r.get("queued_s") or 0.0) * w)
    return {"requests": {k: _int_if_whole(v) for k, v in counts.items()},
            "queued_s": {k: round(v, 6) for k, v in waited.items()}}


def critpath_report(collected: Dict[str, Any],
                    rows: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Critical-path rollup (obs/critpath.py) over the stitched fleet
    traces: where each class's and tenant's latency went, decomposed
    into the exclusive frontend_queue / prefill / shipment_wait /
    decode_queue / decode / reshard_pause / replay segments that sum to
    e2e with zero residual.  None when the log has no stitchable spans
    (HETU_TPU_SERVE_TRACE unset degrades gracefully)."""
    from hetu_tpu.obs.critpath import critical_path, rollup
    fts = collected.get("stitched") or {}
    if not fts:
        return None
    tenants = {r["rid"]: r["tenant"] for r in rows}
    paths: List[Dict[str, Any]] = []
    for rid in sorted(fts):
        cp = critical_path(fts[rid])
        if cp is not None:
            paths.append(dict(cp, tenant=tenants.get(rid, "default")))
    if not paths:
        return None
    by_cls: Dict[str, List[Dict[str, Any]]] = {}
    by_tenant: Dict[str, List[Dict[str, Any]]] = {}
    for cp in paths:
        by_cls.setdefault(cp["slo_class"], []).append(cp)
        by_tenant.setdefault(cp["tenant"], []).append(cp)
    out: Dict[str, Any] = {
        "overall": rollup(paths),
        "by_class": {k: rollup(v) for k, v in sorted(by_cls.items())},
    }
    if any(t != "default" for t in by_tenant):
        out["by_tenant"] = {k: rollup(v)
                            for k, v in sorted(by_tenant.items())}
    return out


#: bump when the request_tree dict shape changes incompatibly (pinned
#: by the tools_serving_report --request --json smoke test)
REQUEST_TREE_SCHEMA = 1


def request_tree(collected: Dict[str, Any], rid: int
                 ) -> Optional[Dict[str, Any]]:
    """One rid's stitched hop tree (`tools_serving_report.py --request`):
    every fleet hop with its span timeline, the causal edges labelled by
    hop identity, the span-seconds/lifetime work ledger, and the
    critical-path decomposition (None while the request is still in
    flight).  None when the rid never recorded a span."""
    from hetu_tpu.obs.critpath import critical_path
    fts = collected.get("stitched") or {}
    ft = fts.get(rid)
    if ft is None:
        return None
    prim = ft.primary
    label = {h.trace: ft.hop_label(h) for h in ft.hops}
    hops = []
    for h in ft.hops:
        hops.append({
            "hop": ft.hop_label(h),
            "trace": h.trace,
            "tier": h.tier,
            "replica": h.replica,
            "primary": prim is not None and h.trace == prim.trace,
            "t0": h.spans[0].t0,
            "t1": h.spans[-1].t1,
            "lifetime_s": h.lifetime_s,
            "attempts": len(h.attempts()),
            "terminal": h.terminal.kind if h.terminal is not None
            else None,
            "spans": [{"kind": s.kind, "t0": s.t0, "t1": s.t1,
                       "attempt": s.attempt,
                       **({"reason": s.attrs["reason"]}
                          if s.attrs.get("reason") is not None else {})}
                      for s in h.spans],
        })
    edges = [dict(e, src=label.get(e.get("src"), e.get("src")),
                  dst=label.get(e.get("dst"), e.get("dst")))
             for e in ft.edges]
    return {
        "request_tree_schema": REQUEST_TREE_SCHEMA,
        "rid": ft.rid,
        "slo_class": ft.slo_class,
        "clock": ft.clock,
        "hops": hops,
        "edges": edges,
        "span_seconds": ft.span_seconds,
        "lifetime_seconds": ft.lifetime_seconds,
        "e2e_s": ft.e2e_s,
        "critical_path": critical_path(ft),
    }


def render_request_tree(tree: Dict[str, Any]) -> str:
    """The hop tree as text: hops indented with their span timelines
    (the primary hop starred), the causal edges, and the critical path
    with its dominant segment highlighted."""
    ln = [f"request {tree['rid']} ({tree['slo_class']}, "
          f"{tree['clock']} clock): {len(tree['hops'])} hop(s), "
          f"fleet work {tree['span_seconds']:.4g} span-s"
          + (f", e2e {tree['e2e_s']:.4g}s"
             if tree.get("e2e_s") is not None else " (in flight)")]
    for h in tree["hops"]:
        star = "*" if h["primary"] else " "
        ln.append(f" {star} {h['hop']:<12} "
                  f"[{h['t0']:.4f} -> {h['t1']:.4f}] "
                  f"{h['lifetime_s']:.4g}s, "
                  f"{h['attempts']} attempt(s) -> "
                  f"{h['terminal'] or 'OPEN'}")
        for s in h["spans"]:
            att = f" attempt={s['attempt']}" if s["attempt"] > 1 else ""
            why = f" ({s['reason']})" if s.get("reason") else ""
            ln.append(f"      {s['kind']:<16} "
                      f"{s['t0']:.4f} -> {s['t1']:.4f} "
                      f"({s['t1'] - s['t0']:.4g}s){att}{why}")
    if tree["edges"]:
        ln.append("  edges:")
        for e in tree["edges"]:
            ln.append(f"      {e['src']} --{e['kind']}--> {e['dst']} "
                      f"@{e['t']:.4f}")
    cp = tree.get("critical_path")
    if cp is not None:
        top = max(cp["segments"], key=lambda s: cp["segments"][s])
        ln.append(f"  critical path (e2e {cp['e2e_s']:.4g}s"
                  + (f", ttft {cp['ttft_s']:.4g}s"
                     if cp.get("ttft_s") is not None else "")
                  + f", residual {cp['residual_s']:.3g}s):")
        for piece in cp["path"]:
            mark = " <-- dominant" if piece["segment"] == top else ""
            ln.append(f"      {piece['segment']:<16} "
                      f"{piece['t0']:.4f} -> {piece['t1']:.4f} "
                      f"({piece['t1'] - piece['t0']:.4g}s){mark}")
    return "\n".join(ln)


def reconciliation(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The acceptance property's summary: span tiling vs recorded e2e
    across every traced request."""
    residuals = [r["residual_s"] for r in rows
                 if r.get("residual_s") is not None]
    if not residuals:
        return None
    return {"requests": len(residuals),
            "max_residual_s": max(residuals),
            "mean_residual_s": sum(residuals) / len(residuals)}


def serving_report(records: Iterable[Dict[str, Any]], *,
                   per_request: bool = False,
                   collected: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """The full SLO-class report over a RunLog's records.  Pass a
    pre-built ``collected`` (from :func:`collect`) to skip re-scanning
    the records — callers that already grouped them (tools_obs_report)
    must not pay the span-grouping walk twice."""
    if collected is None:
        collected = collect(records)
    rows = request_rows(collected)
    elapsed = _elapsed_s(collected, rows)
    n_w = sum(_weight(r) for r in rows)
    tokens = sum(r["tokens"] * _weight(r) for r in rows)
    good = sum(r["tokens"] * _weight(r) for r in rows if r["slo_ok"])
    out: Dict[str, Any] = {
        "report_schema": REPORT_SCHEMA,
        "requests": _int_if_whole(n_w),
        "tokens_out": _int_if_whole(tokens),
        "elapsed_s": elapsed,
        "classes": class_report(rows, elapsed),
        "slo_attainment": (sum(_weight(r) for r in rows if r["slo_ok"])
                           / n_w if rows else None),
        "goodput_tokens": _int_if_whole(good),
        "spans_recorded": sum(len(t.spans)
                              for t in collected["traces"].values()),
        "reshards": len(collected["reshards"]),
    }
    if elapsed:
        out["tokens_per_s"] = tokens / elapsed
        out["goodput_tokens_per_s"] = good / elapsed
    tenants = tenant_report(rows, elapsed)
    if tenants is not None:
        out["tenants"] = tenants
    costs = aggregate_costs(rows)
    if costs is not None:
        out["costs"] = costs
    stalls = stall_breakdown(rows)
    if stalls is not None:
        out["stall_breakdown"] = stalls
    rec = reconciliation(rows)
    if rec is not None:
        out["reconciliation"] = rec
    cp = critpath_report(collected, rows)
    if cp is not None:
        out["critical_path"] = cp
    spec = spec_decode_report(collected)
    if spec is not None:
        out["spec_decode"] = spec
    cache = prefix_cache_report(collected)
    if cache is not None:
        out["prefix_cache"] = cache
    pre = preemption_report(collected)
    if pre is not None:
        out["preemptions"] = pre
    fo = failover_report(collected)
    if fo is not None:
        out["failover"] = fo
    dl = deadline_report(collected)
    if dl is not None:
        out["deadline"] = dl
    bo = brownout_report(collected)
    if bo is not None:
        out["brownout"] = bo
    dg = disagg_report(collected)
    if dg is not None:
        out["disagg"] = dg
    fe = frontend_report(collected)
    if fe is not None:
        out["frontend"] = fe
    if collected["anomalies"]:
        by_kind: Dict[str, int] = {}
        for a in collected["anomalies"]:
            k = str(a.get("anomaly", "unknown"))
            by_kind[k] = by_kind.get(k, 0) + 1
        out["anomalies"] = by_kind
    if per_request:
        out["per_request"] = rows
    return out


# ---------------------------------------------------------------------------
# text rendering
# ---------------------------------------------------------------------------

def _fmt(v, scale=1.0, digits=4) -> str:
    if v is None:
        return "-"
    return f"{v * scale:.{digits}g}"


def render_text(report: Dict[str, Any]) -> str:
    """The report as a fixed-width table (tools_serving_report.py's
    default output)."""
    lines = [
        f"serving report: {report['requests']} requests, "
        f"{report['tokens_out']} tokens"
        + (f", {report['tokens_per_s']:.1f} tok/s"
           if report.get("tokens_per_s") else "")
        + (f", goodput {report['goodput_tokens_per_s']:.1f} tok/s"
           if report.get("goodput_tokens_per_s") is not None else "")]
    hdr = (f"{'class':>10} {'reqs':>5} {'ttft p50':>9} {'ttft p95':>9} "
           f"{'e2e p95':>9} {'gap p95':>9} {'attain':>7} {'goodput':>8}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for cls, sec in report.get("classes", {}).items():
        def pct(key, p):
            d = sec.get(key)
            return _fmt(d.get(p) if d else None)
        lines.append(
            f"{cls:>10} {sec['requests']:>5} "
            f"{pct('ttft_s', 'p50'):>9} {pct('ttft_s', 'p95'):>9} "
            f"{pct('e2e_s', 'p95'):>9} {pct('token_gap_s', 'p95'):>9} "
            f"{sec['attainment']['slo']:>7.0%} "
            f"{_fmt(sec.get('goodput_tokens_per_s'), digits=3):>8}")
    tenants = report.get("tenants")
    if tenants:
        thdr = (f"{'tenant':>10} {'reqs':>7} {'tokens':>8} "
                f"{'ttft p95':>9} {'e2e p95':>9} {'attain':>7} "
                f"{'goodput':>8}")
        lines.append(thdr)
        lines.append("-" * len(thdr))
        for t, sec in tenants.items():
            def tpct(key, p):
                d = sec.get(key)
                return _fmt(d.get(p) if d else None)
            lines.append(
                f"{t:>10} {_fmt(sec['requests'], digits=6):>7} "
                f"{_fmt(sec['tokens_out'], digits=6):>8} "
                f"{tpct('ttft_s', 'p95'):>9} {tpct('e2e_s', 'p95'):>9} "
                f"{sec['attainment']['slo']:>7.0%} "
                f"{_fmt(sec.get('goodput_tokens_per_s'), digits=3):>8}")
    costs = report.get("costs")
    if costs:
        for t, c in costs["by_tenant"].items():
            lines.append(
                f"cost[{t}]: prefill {_fmt(c['cost_prefill_flops'], digits=3)} "
                f"+ decode {_fmt(c['cost_decode_flops'], digits=3)} FLOPs, "
                f"{_fmt(c['cost_page_s'], digits=3)} page-s, "
                f"{_fmt(c['cost_kv_byte_s'], digits=3)} KV byte-s, "
                f"{_fmt(c['cost_wire_bytes'], digits=3)} wire B")
        tot = costs["total"]
        lines.append(
            f"cost[total]: prefill {_fmt(tot['cost_prefill_flops'], digits=3)} "
            f"+ decode {_fmt(tot['cost_decode_flops'], digits=3)} FLOPs, "
            f"{_fmt(tot['cost_page_s'], digits=3)} page-s, "
            f"{_fmt(tot['cost_kv_byte_s'], digits=3)} KV byte-s, "
            f"{_fmt(tot['cost_wire_bytes'], digits=3)} wire B")
    stalls = report.get("stall_breakdown")
    if stalls:
        parts = [f"{k}={v}" for k, v in sorted(stalls["requests"].items())]
        lines.append("stall attribution (requests): " + ", ".join(parts))
        parts = [f"{k}={v:.4g}s" for k, v in sorted(stalls["queued_s"].items())]
        lines.append("stall attribution (queued time): " + ", ".join(parts))
    rec = report.get("reconciliation")
    if rec:
        lines.append(
            f"span reconciliation: {rec['requests']} traced requests, "
            f"max |spans - e2e| = {rec['max_residual_s']:.3g}s")
    cpr = report.get("critical_path")
    if cpr:
        tot = cpr["overall"]
        parts = [f"{seg}={tot['mean_s'][seg]:.4g}s"
                 for seg in tot["mean_s"] if tot["total_s"][seg] > 0]
        lines.append(
            f"critical path ({tot['requests']} stitched, mean s/req): "
            + ", ".join(parts)
            + f"; max residual {tot['max_residual_s']:.3g}s")
        for cls, sec in cpr["by_class"].items():
            top = max(sec["mean_s"], key=lambda s: sec["mean_s"][s])
            lines.append(
                f"  {cls}: dominant segment {top} "
                f"({sec['mean_s'][top]:.4g}s mean of "
                f"{sec['requests']} requests)")
    spec = report.get("spec_decode")
    if spec:
        lines.append(
            f"spec decode: {spec['drafts_accepted']}/"
            f"{spec['drafts_proposed']} drafts accepted "
            f"(acceptance {spec['acceptance_rate']:.0%} over "
            f"{spec['requests']} requests)")
    cache = report.get("prefix_cache")
    if cache:
        lines.append(
            f"prefix cache: {cache['hits']}/{cache['admits']} admissions "
            f"hit ({cache['hit_rate']:.0%}); {cache['shared_tokens']}/"
            f"{cache['prompt_tokens']} prompt tokens resident "
            f"({cache['prefill_tokens_saved_frac']:.0%} of prefill "
            "eliminated)")
    pre = report.get("preemptions")
    if pre:
        victims = ", ".join(f"{k}={v}" for k, v in
                            sorted(pre["victim_classes"].items()))
        lines.append(f"preemptions: {pre['preemptions']} "
                     f"(victims by class: {victims})")
    fo = report.get("failover")
    if fo:
        retried = ", ".join(f"{k}={v}" for k, v in
                            fo["retried_by_class"].items())
        lines.append(
            f"failover: {fo['failovers']} replica deaths, "
            f"{fo['requeued']} requests requeued"
            + (f" ({retried})" if retried else "")
            + f", {fo['retry_exhausted']} over budget, "
            f"{fo['finished_after_retry']} finished after retry")
    dl = report.get("deadline")
    if dl:
        by = ", ".join(f"{k}={v}" for k, v in dl["by_class"].items())
        lines.append(f"deadlines: {dl['expired']} expired ({by}); "
                     f"{dl['tokens_discarded']} tokens discarded")
    bo = report.get("brownout")
    if bo:
        by = ", ".join(f"{k}={v}" for k, v in bo["by_class"].items())
        lines.append(f"brownout: {bo['shed']} queued requests shed ({by})")
    dg = report.get("disagg")
    if dg:
        by = ", ".join(f"{k}={v}" for k, v in
                       dg["reprefills_by_class"].items())
        lines.append(
            f"disagg: {dg['shipments']} KV shipments "
            f"({dg['resends']} resent), {dg['reprefills']} re-prefills"
            + (f" ({by})" if by else "")
            + f"; degraded {dg['degraded_entries']}x for "
            f"{dg['degraded_s']:.3g}s")
    fe = report.get("frontend")
    if fe:
        ev = ", ".join(f"{k}={v}" for k, v in
                       fe["replica_events"].items())
        lines.append(
            f"frontend: replica events [{ev}], {fe['hedges']} hedges, "
            f"{fe['hedge_wins']} hedge wins")
    if report.get("anomalies"):
        lines.append("anomalies: " + ", ".join(
            f"{k}={n}" for k, n in sorted(report["anomalies"].items())))
    if report.get("reshards"):
        lines.append(f"reshards: {report['reshards']}")
    return "\n".join(lines)
