"""In-graph token sampling for the serving decode program.

The engine's decode (and spec-decode verify) programs call
:func:`sample_tokens` INSIDE the jitted step: per-slot temperature /
top-k / top-p vectors ride in as program inputs, and the PRNG key for
each sampled token is derived in-graph as

    key = fold_in(jax.random.key(seed[slot]), token_position)

— a pure function of the request's seed and the token's absolute
sequence position.  That derivation is the determinism contract: the
same request replays to the same tokens across engine restarts, slot
assignments, batch compositions AND speculative re-verification (the
spec-decode path samples the token at position p with exactly the key
the sequential path would have used, which is what makes the
sample-then-match acceptance rule distribution-exact).

Greedy stays greedy bit-for-bit: rows with temperature == 0 take the
plain ``argmax`` of the unfiltered logits (the filters never touch
them), so a mixed batch of greedy and sampling requests decodes the
greedy rows exactly like the sampling-free program.  The engine only
builds the sampling program at all under ``HETU_TPU_SERVE_SAMPLE`` —
unset, the decode program is byte-identical to the pre-sampling engine
(registered identity contract, enforced by the flag-identity sweep).

Filter semantics match ``models/generation.generate``'s sampler (HF
conventions): top-k first, nucleus over the renormalized top-k
distribution, the max-probability token always survives.  One
descending full-vocab sort serves both filters per row.

The DRAW is Gumbel-argmax over a counter-based hash of the key's raw
words (`ops/pallas/sample.hash_uniform` — shared verbatim with the
fused sampling kernel, so the in-kernel epilogue and this XLA path pick
identical tokens for identical (seed, position) keys).  `sample_hidden`
is the fused entry: it takes last-layer HIDDEN rows plus the lm_head
slice and routes the whole matmul+filter+draw to the Pallas kernel when
enabled, never materializing the [rows, vocab] logits in HBM.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from hetu_tpu.ops.pallas.sample import gumbel

#: the filter mask value (matches generate()'s sampler)
_NEG = -1e30


def slot_keys(seeds, positions):
    """[S] per-slot typed PRNG keys: ``fold_in(key(seed), position)``.
    ``positions`` are the ABSOLUTE sequence positions of the tokens
    being sampled (prompt + generated index), not engine step counts —
    the restart-determinism contract."""
    def one(seed, pos):
        return jax.random.fold_in(jax.random.key(seed), pos)
    return jax.vmap(one)(seeds.astype(jnp.uint32),
                         positions.astype(jnp.uint32))


def key_words(seeds, positions):
    """[S, 2] uint32 — the raw key data of `slot_keys`, the form the
    hash-based draw (and the fused sampling kernel) consumes."""
    return jax.random.key_data(slot_keys(seeds, positions)) \
        .astype(jnp.uint32)


def filtered_logits(logits, temps, top_ks, top_ps):
    """Apply per-row temperature + top-k + top-p filtering.

    logits: [S, V] f32; temps: [S] f32 (0 = greedy row — returned
    unfiltered, the caller argmaxes it); top_ks: [S] int32 (0 =
    disabled); top_ps: [S] f32 (0 or >= 1 = disabled).  Returns the
    filtered, temperature-scaled logits [S, V]."""
    V = logits.shape[-1]
    temps = temps.astype(jnp.float32)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    scaled = logits.astype(jnp.float32) / safe_t[:, None]

    # ONE descending sort per row serves both filters
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]

    # top-k: mask everything below the per-row kth value (k=0 -> V)
    k_eff = jnp.where(top_ks > 0, top_ks, V).astype(jnp.int32)
    kth = jnp.take_along_axis(
        desc, jnp.clip(k_eff[:, None] - 1, 0, V - 1), axis=-1)
    out = jnp.where(scaled < kth, _NEG, scaled)

    # nucleus over the renormalized top-k distribution (HF semantics):
    # the filtered descending view is the top-k prefix of `desc`
    p_on = (top_ps > 0.0) & (top_ps < 1.0)
    desc_f = jnp.where(jnp.arange(V)[None, :] < k_eff[:, None], desc, _NEG)
    probs = jax.nn.softmax(desc_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_ps[:, None]      # mass BEFORE this token
    cutoff = jnp.min(jnp.where(keep, desc_f, jnp.inf), axis=-1,
                     keepdims=True)
    out = jnp.where(p_on[:, None] & (out < cutoff), _NEG, out)
    return out


def sample_tokens(logits, seeds, positions, temps, top_ks, top_ps):
    """Sample (or argmax) one token per slot, in-graph.

    logits: [S, V]; seeds/positions/top_ks: [S] int; temps/top_ps: [S]
    f32.  ``positions`` are the sampled tokens' absolute sequence
    positions (the key-derivation input).  Rows with temperature 0 take
    ``argmax`` of the UNFILTERED logits — exactly the greedy program's
    token.  Returns [S] int32."""
    V = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filt = filtered_logits(logits, temps, top_ks, top_ps)
    words = key_words(seeds, positions)
    idx = jnp.arange(V, dtype=jnp.uint32)[None, :]
    g = gumbel(words[:, 0:1], words[:, 1:2], idx)
    sampled = jnp.argmax(filt + g, axis=-1)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy_tok)


def sample_token_grid(logits, seeds, positions, temps, top_ks, top_ps):
    """The spec-decode form: sample a [S, C] grid of tokens, one per
    verify position.  logits: [S, C, V]; positions: [S, C] absolute
    sequence positions of the tokens being sampled; per-slot params
    broadcast over C.  Each (slot, position) uses the same key the
    sequential path would — acceptance by sample-then-match is then the
    exact rejection rule for a deterministic drafter
    (serving/spec_decode.py)."""
    S, C, V = logits.shape
    flat = logits.reshape(S * C, V)
    rep = lambda x: jnp.repeat(x, C)  # noqa: E731 — [S] -> [S*C]
    toks = sample_tokens(flat, rep(seeds), positions.reshape(-1),
                         rep(temps), rep(top_ks), rep(top_ps))
    return toks.reshape(S, C)


def sample_hidden(hidden, w, seeds, positions, temps, top_ks, top_ps):
    """The fused last-layer epilogue: last-layer hidden rows [R, H] +
    lm_head slice w [H, V] -> one token per row, WITHOUT materializing
    the [R, V] logits in HBM when the Pallas `sample` kernel routes
    (ops/pallas/sample.py).  The XLA fallback computes the same math
    (matmul -> filtered_logits -> hash-Gumbel argmax), so the routed
    and unrouted paths pick identical tokens — the flag only moves
    bytes, never the distribution."""
    from hetu_tpu.ops import pallas as _pl
    from hetu_tpu.ops.pallas import sample as _ps
    if _pl.resolve_route("sample", _ps.compatible(hidden.shape, w.shape)):
        words = key_words(seeds, positions)
        with jax.named_scope("pallas_fused_sample"):
            return _ps.fused_sample(hidden, w, words,
                                    temps.astype(jnp.float32),
                                    top_ks.astype(jnp.int32),
                                    top_ps.astype(jnp.float32))
    logits = hidden.astype(jnp.float32) @ w.astype(jnp.float32)
    return sample_tokens(logits, seeds, positions, temps, top_ks, top_ps)


def sample_hidden_grid(hidden, w, seeds, positions, temps, top_ks,
                       top_ps):
    """`sample_hidden` over the spec-decode verify grid: hidden
    [S, C, H], positions [S, C]; per-slot params broadcast over C.
    Returns [S, C] int32."""
    S, C, H = hidden.shape
    rep = lambda x: jnp.repeat(x, C)  # noqa: E731 — [S] -> [S*C]
    toks = sample_hidden(hidden.reshape(S * C, H), w, rep(seeds),
                         positions.reshape(-1), rep(temps), rep(top_ks),
                         rep(top_ps))
    return toks.reshape(S, C)
