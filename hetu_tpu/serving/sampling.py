"""In-graph token sampling for the serving decode program.

The engine's decode (and spec-decode verify) programs call
:func:`sample_tokens` INSIDE the jitted step: per-slot temperature /
top-k / top-p vectors ride in as program inputs, and the PRNG key for
each sampled token is derived in-graph as

    key = fold_in(jax.random.key(seed[slot]), token_position)

— a pure function of the request's seed and the token's absolute
sequence position.  That derivation is the determinism contract: the
same request replays to the same tokens across engine restarts, slot
assignments, batch compositions AND speculative re-verification (the
spec-decode path samples the token at position p with exactly the key
the sequential path would have used, which is what makes the
sample-then-match acceptance rule distribution-exact).

Greedy stays greedy bit-for-bit: rows with temperature == 0 take the
plain ``argmax`` of the unfiltered logits (the filters never touch
them), so a mixed batch of greedy and sampling requests decodes the
greedy rows exactly like the sampling-free program.  The engine only
builds the sampling program at all under ``HETU_TPU_SERVE_SAMPLE`` —
unset, the decode program is byte-identical to the pre-sampling engine
(registered identity contract, enforced by the flag-identity sweep).

Filter semantics match ``models/generation.generate``'s sampler (HF
conventions): top-k first, nucleus over the renormalized top-k
distribution, the max-probability token always survives.  One
descending full-vocab sort serves both filters per row.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: the filter mask value (matches generate()'s sampler)
_NEG = -1e30


def slot_keys(seeds, positions):
    """[S] per-slot typed PRNG keys: ``fold_in(key(seed), position)``.
    ``positions`` are the ABSOLUTE sequence positions of the tokens
    being sampled (prompt + generated index), not engine step counts —
    the restart-determinism contract."""
    def one(seed, pos):
        return jax.random.fold_in(jax.random.key(seed), pos)
    return jax.vmap(one)(seeds.astype(jnp.uint32),
                         positions.astype(jnp.uint32))


def filtered_logits(logits, temps, top_ks, top_ps):
    """Apply per-row temperature + top-k + top-p filtering.

    logits: [S, V] f32; temps: [S] f32 (0 = greedy row — returned
    unfiltered, the caller argmaxes it); top_ks: [S] int32 (0 =
    disabled); top_ps: [S] f32 (0 or >= 1 = disabled).  Returns the
    filtered, temperature-scaled logits [S, V]."""
    V = logits.shape[-1]
    temps = temps.astype(jnp.float32)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    scaled = logits.astype(jnp.float32) / safe_t[:, None]

    # ONE descending sort per row serves both filters
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]

    # top-k: mask everything below the per-row kth value (k=0 -> V)
    k_eff = jnp.where(top_ks > 0, top_ks, V).astype(jnp.int32)
    kth = jnp.take_along_axis(
        desc, jnp.clip(k_eff[:, None] - 1, 0, V - 1), axis=-1)
    out = jnp.where(scaled < kth, _NEG, scaled)

    # nucleus over the renormalized top-k distribution (HF semantics):
    # the filtered descending view is the top-k prefix of `desc`
    p_on = (top_ps > 0.0) & (top_ps < 1.0)
    desc_f = jnp.where(jnp.arange(V)[None, :] < k_eff[:, None], desc, _NEG)
    probs = jax.nn.softmax(desc_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_ps[:, None]      # mass BEFORE this token
    cutoff = jnp.min(jnp.where(keep, desc_f, jnp.inf), axis=-1,
                     keepdims=True)
    out = jnp.where(p_on[:, None] & (out < cutoff), _NEG, out)
    return out


def sample_tokens(logits, seeds, positions, temps, top_ks, top_ps):
    """Sample (or argmax) one token per slot, in-graph.

    logits: [S, V]; seeds/positions/top_ks: [S] int; temps/top_ps: [S]
    f32.  ``positions`` are the sampled tokens' absolute sequence
    positions (the key-derivation input).  Rows with temperature 0 take
    ``argmax`` of the UNFILTERED logits — exactly the greedy program's
    token.  Returns [S] int32."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filt = filtered_logits(logits, temps, top_ks, top_ps)
    keys = slot_keys(seeds, positions)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row))(keys, filt)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy_tok)


def sample_token_grid(logits, seeds, positions, temps, top_ks, top_ps):
    """The spec-decode form: sample a [S, C] grid of tokens, one per
    verify position.  logits: [S, C, V]; positions: [S, C] absolute
    sequence positions of the tokens being sampled; per-slot params
    broadcast over C.  Each (slot, position) uses the same key the
    sequential path would — acceptance by sample-then-match is then the
    exact rejection rule for a deterministic drafter
    (serving/spec_decode.py)."""
    S, C, V = logits.shape
    flat = logits.reshape(S * C, V)
    rep = lambda x: jnp.repeat(x, C)  # noqa: E731 — [S] -> [S*C]
    toks = sample_tokens(flat, rep(seeds), positions.reshape(-1),
                         rep(temps), rep(top_ks), rep(top_ps))
    return toks.reshape(S, C)
