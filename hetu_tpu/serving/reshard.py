"""Load-adaptive serving mesh: the hot-switch engine pointed at serving.

Hetis (PAPERS.md) serves heterogeneous clusters with fine-grained
DYNAMIC parallelism — the layout follows the load.  This repo already
owns that machinery for training (engine/hot_switch.py's per-strategy
plan pool over parallel/switch.py's device_put resharding engine), so
serving reuses it instead of forking it: a `LoadAdaptiveMesh` maps
queue-depth tiers to `ParallelStrategy` entries, and when the load
profile crosses a tier boundary the engine reshards its PARAMS onto the
tier's mesh with the same `switch_tree` ParamSlice program a training
hot-switch runs (params only — serving has no optimizer state; the
compiled decode/prefill programs re-specialize automatically because
jax.jit keys its plan cache on input shardings).

Hysteresis: a tier change needs `patience` consecutive observations on
the other side of the boundary, so one bursty step cannot thrash the
mesh back and forth (same strike discipline as the straggler hook).

KV re-paging (``HETU_TPU_SERVE_KV_REPAGE``, docs/serving.md): by
default only params move — the KV pool keeps its original placement
(the pre-existing behavior, and the identity contract of the flag).
With the flag set the engine also routes the pool arrays (fp or int8
payload + scales) through :meth:`LoadAdaptiveMesh.reshard_pool`, the
same device_put switch program, replicated onto the destination tier's
mesh — so in-flight requests survive a scale-up/down with their cache
intact and their token streams byte-identical.  Page tables are
host-side numpy, re-uploaded every step, so they migrate for free.

Chaos (`reshard_storm`): :meth:`LoadAdaptiveMesh.force_tier` lets the
fault-injection harness pin the next observation's outcome, bypassing
the hysteresis — a deterministic tier flip-flop that exercises the
re-paging path without shaping the workload around the thresholds.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from hetu_tpu.engine.hot_switch import param_handle
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.parallel.switch import StrategyHandle, switch_tree
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.reshard")


class LoadAdaptiveMesh:
    """Queue-depth -> strategy tier map with hysteresis.

    tiers: ascending [(min_queue_depth, strategy), ...]; tier 0 must
    start at depth 0 (the idle layout)."""

    def __init__(self, model_factory: Callable[[ParallelStrategy], object],
                 tiers: Sequence[Tuple[int, ParallelStrategy]],
                 *, patience: int = 2):
        if not tiers:
            raise ValueError("need at least one (threshold, strategy) tier")
        thresholds = [t for t, _ in tiers]
        if thresholds != sorted(thresholds) or thresholds[0] != 0:
            raise ValueError("tier thresholds must ascend from 0, got "
                             f"{thresholds}")
        self.model_factory = model_factory
        self.tiers = list(tiers)
        self.patience = max(1, patience)
        self.active_tier = 0
        self._handles: List[Optional[StrategyHandle]] = [None] * len(tiers)
        self._pending_tier: Optional[int] = None
        self._strikes = 0
        self._forced: Optional[int] = None
        self.reshards = 0
        self.pool_reshards = 0

    def handle(self, tier: int) -> StrategyHandle:
        h = self._handles[tier]
        if h is None:
            h = param_handle(self.model_factory, self.tiers[tier][1])
            self._handles[tier] = h
        return h

    def tier_for(self, queue_depth: int) -> int:
        tier = 0
        for i, (threshold, _) in enumerate(self.tiers):
            if queue_depth >= threshold:
                tier = i
        return tier

    def force_tier(self, tier: int):
        """Pin the NEXT observation's outcome to `tier`, bypassing the
        hysteresis — the chaos `reshard_storm` injection point.  A
        forced flip to the already-active tier is a no-op (observe
        still returns None: nothing to reshard)."""
        if not 0 <= tier < len(self.tiers):
            raise ValueError(f"tier {tier} out of range "
                             f"[0, {len(self.tiers)})")
        self._forced = tier

    def observe(self, queue_depth: int) -> Optional[int]:
        """Feed one load observation; returns the new tier id when the
        strike budget commits a change, else None."""
        if self._forced is not None:
            want, self._forced = self._forced, None
            self._pending_tier, self._strikes = None, 0
            if want == self.active_tier:
                return None
            self.active_tier = want
            return want
        want = self.tier_for(queue_depth)
        if want == self.active_tier:
            self._pending_tier, self._strikes = None, 0
            return None
        if want != self._pending_tier:
            self._pending_tier, self._strikes = want, 0
        self._strikes += 1
        if self._strikes < self.patience:
            return None
        self.active_tier = want
        self._pending_tier, self._strikes = None, 0
        return want

    def reshard(self, params, tier: int):
        """Move params onto tier's mesh (the hot-switch ParamSlice
        program, params-only mode).  donate=False: unlike the training
        switcher, the serving hook does NOT own the params pytree — the
        caller may share it with a trainer or later golden runs, and
        donating it would delete their buffers on backends that honor
        donation."""
        dst = self.handle(tier)
        new_params = switch_tree(params, dst.param_shardings, donate=False)
        self.reshards += 1
        logger.info(
            f"serving reshard -> tier {tier} "
            f"({self.tiers[tier][1].describe()})")
        return new_params

    def reshard_pool(self, pool_arrays, tier: int):
        """Migrate the paged KV pool onto tier's mesh
        (HETU_TPU_SERVE_KV_REPAGE): every pool leaf — fp payload, or
        int8 payload + f32 scales — is device_put replicated over the
        destination mesh through the same switch program the params
        ride.  Returns the migrated PoolArrays; the caller MUST commit
        it back (the decode program donates the pool tree, so the old
        arrays are dead after the next step either way).  Page tables
        never appear here: they are host-resident numpy, re-uploaded
        each step, so a tier change migrates them for free."""
        from hetu_tpu.serving.kv_pool import repage_arrays
        dst = self.handle(tier)
        migrated = repage_arrays(pool_arrays, dst.mesh)
        self.pool_reshards += 1
        logger.info(f"serving KV re-page -> tier {tier} "
                    f"({self.tiers[tier][1].describe()})")
        return migrated

    def describe(self, tier: Optional[int] = None) -> str:
        t = self.active_tier if tier is None else tier
        return self.tiers[t][1].describe()
