"""Load-adaptive serving mesh: the hot-switch engine pointed at serving.

Hetis (PAPERS.md) serves heterogeneous clusters with fine-grained
DYNAMIC parallelism — the layout follows the load.  This repo already
owns that machinery for training (engine/hot_switch.py's per-strategy
plan pool over parallel/switch.py's device_put resharding engine), so
serving reuses it instead of forking it: a `LoadAdaptiveMesh` maps
queue-depth tiers to `ParallelStrategy` entries, and when the load
profile crosses a tier boundary the engine reshards its PARAMS onto the
tier's mesh with the same `switch_tree` ParamSlice program a training
hot-switch runs (params only — serving has no optimizer state; the
compiled decode/prefill programs re-specialize automatically because
jax.jit keys its plan cache on input shardings).

Hysteresis: a tier change needs `patience` consecutive observations on
the other side of the boundary, so one bursty step cannot thrash the
mesh back and forth (same strike discipline as the straggler hook).

Known limit (docs/serving.md): the KV pool stays on its original
placement — only params move.  Re-paging the pool across meshes is the
natural next step once a multi-slice serving mesh exists to test on.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from hetu_tpu.engine.hot_switch import param_handle
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.parallel.switch import StrategyHandle, switch_tree
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.reshard")


class LoadAdaptiveMesh:
    """Queue-depth -> strategy tier map with hysteresis.

    tiers: ascending [(min_queue_depth, strategy), ...]; tier 0 must
    start at depth 0 (the idle layout)."""

    def __init__(self, model_factory: Callable[[ParallelStrategy], object],
                 tiers: Sequence[Tuple[int, ParallelStrategy]],
                 *, patience: int = 2):
        if not tiers:
            raise ValueError("need at least one (threshold, strategy) tier")
        thresholds = [t for t, _ in tiers]
        if thresholds != sorted(thresholds) or thresholds[0] != 0:
            raise ValueError("tier thresholds must ascend from 0, got "
                             f"{thresholds}")
        self.model_factory = model_factory
        self.tiers = list(tiers)
        self.patience = max(1, patience)
        self.active_tier = 0
        self._handles: List[Optional[StrategyHandle]] = [None] * len(tiers)
        self._pending_tier: Optional[int] = None
        self._strikes = 0
        self.reshards = 0

    def handle(self, tier: int) -> StrategyHandle:
        h = self._handles[tier]
        if h is None:
            h = param_handle(self.model_factory, self.tiers[tier][1])
            self._handles[tier] = h
        return h

    def tier_for(self, queue_depth: int) -> int:
        tier = 0
        for i, (threshold, _) in enumerate(self.tiers):
            if queue_depth >= threshold:
                tier = i
        return tier

    def observe(self, queue_depth: int) -> Optional[int]:
        """Feed one load observation; returns the new tier id when the
        strike budget commits a change, else None."""
        want = self.tier_for(queue_depth)
        if want == self.active_tier:
            self._pending_tier, self._strikes = None, 0
            return None
        if want != self._pending_tier:
            self._pending_tier, self._strikes = want, 0
        self._strikes += 1
        if self._strikes < self.patience:
            return None
        self.active_tier = want
        self._pending_tier, self._strikes = None, 0
        return want

    def reshard(self, params, tier: int):
        """Move params onto tier's mesh (the hot-switch ParamSlice
        program, params-only mode).  donate=False: unlike the training
        switcher, the serving hook does NOT own the params pytree — the
        caller may share it with a trainer or later golden runs, and
        donating it would delete their buffers on backends that honor
        donation."""
        dst = self.handle(tier)
        new_params = switch_tree(params, dst.param_shardings, donate=False)
        self.reshards += 1
        logger.info(
            f"serving reshard -> tier {tier} "
            f"({self.tiers[tier][1].describe()})")
        return new_params

    def describe(self, tier: Optional[int] = None) -> str:
        t = self.active_tier if tier is None else tier
        return self.tiers[t][1].describe()
