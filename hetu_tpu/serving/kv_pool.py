"""Paged KV cache: a block-pool allocator with per-sequence page tables.

The serving engine's cache is not one dense [b, max_len] buffer per
sequence (today's `init_cache` shape) but a POOL of fixed-size pages

    k_pool / v_pool : [L, num_pages, page_size, n_kv, hd]

plus, per decode slot, a page table row [max_pages] of page indices that
maps a sequence position p to (table[p // page_size], p % page_size).
Sequences of different lengths share the pool; a finished sequence's
pages go back on the free list and are recycled by the next admission —
the vLLM move, TPU-shaped: every device-side shape stays static (the
table is a fixed [slots, max_pages] int32 array; short sequences pad
with the null page).

Page 0 is the reserved NULL page: it is never allocated, unoccupied
table entries point at it, and inactive slots' token writes land in it.
It is never read either — gathers beyond a sequence's length are masked
by the position mask in `models/generation._attend_cached`, so null-page
garbage cannot reach attention.

Quantized page modes (``HETU_TPU_KV_QUANT=int8|int4``): pages store
blockwise values + one f32 absmax scale per head-vector (block =
head_dim).  int8 reuses `comm/compress.py`'s collective quantization
primitives; bytes per element drop 4 -> 1 + 4/hd (~3.88x smaller at
hd=128 vs fp32).  int4 packs two values per byte through the shared
`ops/quantization.pack_nibbles` storage layout (even index = LOW
nibble, +8 offset): 4 -> 0.5 + 4/hd (~7.53x smaller at hd=128), with
both paged Pallas kernels unpacking the nibbles in-VMEM.  The exact fp
path is the default and stores pages in the model's compute dtype —
byte-identical semantics to `init_cache`.

Host side (allocator, free list) is plain Python; device side
(gather/scatter) is pure-functional jax, jitted by the engine.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.comm.compress import dequantize_blockwise, quantize_blockwise

#: analytic bytes per element for each page mode (int8 carries one f32
#: scale per head-vector block of `head_dim` elements)
_ELEM_BYTES = {"fp32": 4.0, "bf16": 2.0, "fp16": 2.0}


def kv_bytes_per_token(num_layers: int, num_kv_heads: int, head_dim: int,
                       mode: str = "fp32") -> float:
    """Cache bytes one token position occupies (K and V, all layers) —
    the analytic model bench.py records (same pattern as comm/wire.py:
    provable without hardware)."""
    elems = 2.0 * num_layers * num_kv_heads * head_dim
    if mode == "int8":
        return elems * (1.0 + 4.0 / head_dim)
    if mode == "int4":
        return elems * (0.5 + 4.0 / head_dim)
    try:
        return elems * _ELEM_BYTES[mode]
    except KeyError:
        raise ValueError(f"unknown kv mode {mode!r}; "
                         f"known: {sorted(_ELEM_BYTES)} + ['int8', 'int4']")


def quantize_heads(x, bits: int = 8):
    """[..., hd] f32 -> (payload, scales f32 [...]): one absmax scale
    per head-vector (block = hd).  int8 payload is [..., hd] via the
    comm/compress blockwise primitives; ``bits=4`` packs nibbles to a
    [..., hd//2] uint8 payload via the shared `ops/quantization`
    storage layout."""
    hd = x.shape[-1]
    if bits == 4:
        from hetu_tpu.ops.quantization import quantize_int4
        q, s = quantize_int4(x, block_size=hd)
        return q.reshape(x.shape[:-1] + (hd // 2,)), s.reshape(x.shape[:-1])
    q, s = quantize_blockwise(x, block_size=hd)
    return q.reshape(x.shape), s.reshape(x.shape[:-1])


def dequantize_heads(q, s, bits: int = 8):
    """Inverse of `quantize_heads`."""
    if bits == 4:
        from hetu_tpu.ops.quantization import dequantize_int4
        hd = q.shape[-1] * 2
        shape = q.shape[:-1] + (hd,)
        return dequantize_int4(q.reshape(-1, q.shape[-1]),
                               s.reshape(-1), shape)
    return dequantize_blockwise(q.reshape(-1, q.shape[-1]),
                                s.reshape(-1)).reshape(q.shape)


def _tap_kv_snr(x32, q, s, bits: int = 8):
    """Numerics SNR tap at the quantized KV-page write site
    (obs/numerics.py, HETU_TPU_NUMERICS): the exact roundtrip error of
    the tokens just written.  Only traced when the serving engine
    installed a collector around the program build."""
    from hetu_tpu.obs import numerics as _numerics
    if _numerics.active():
        _numerics.tap_quant_error("kv_pages", x32,
                                  x32 - dequantize_heads(q, s, bits))


@dataclasses.dataclass
class PoolArrays:
    """The device-side pool state threaded through the engine's jitted
    step (a pytree: quant scales are None in the exact mode)."""
    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    def tree(self):
        if self.k_scale is None:
            return (self.k, self.v)
        return (self.k, self.v, self.k_scale, self.v_scale)

    @staticmethod
    def from_tree(t) -> "PoolArrays":
        return PoolArrays(*t) if len(t) == 4 else PoolArrays(t[0], t[1])


def repage_arrays(arrays: PoolArrays, mesh) -> PoolArrays:
    """Re-place a pool's device arrays onto `mesh`, replicated — the KV
    side of a LoadAdaptiveMesh tier change (HETU_TPU_SERVE_KV_REPAGE).

    Every leaf (fp payload, or int8 payload + f32 scales) rides the same
    `switch_tree` device_put program a params hot-switch uses; values
    are untouched, only placement moves, so decode after the migration
    is byte-identical to decode without it.  donate=True: the engine is
    the pool's only owner and commits the result straight back (the old
    buffers would be dead after the next donated decode step anyway),
    so the switch never holds two live copies of the cache."""
    from jax.sharding import NamedSharding, PartitionSpec

    from hetu_tpu.parallel.switch import switch_tree
    dst = NamedSharding(mesh, PartitionSpec())
    tree = arrays.tree()
    new = switch_tree(tree, tuple(dst for _ in tree), donate=True)
    return PoolArrays.from_tree(new)


class PagePool:
    """Host-side allocator + device-side page arrays.

    num_pages counts USABLE pages; one extra null page (index 0) is
    added on top, so the device arrays hold num_pages + 1 pages."""

    NULL_PAGE = 0

    def __init__(self, *, num_layers: int, num_pages: int, page_size: int,
                 num_kv_heads: int, head_dim: int,
                 dtype=jnp.float32, quant: str = "none",
                 device_arrays: bool = True):
        if quant not in ("none", "int8", "int4"):
            raise ValueError(f"kv quant mode {quant!r} invalid; "
                             "choices: ('none', 'int8', 'int4')")
        if quant == "int4" and head_dim % 2:
            raise ValueError(f"int4 pages need an even head_dim, "
                             f"got {head_dim}")
        if num_pages < 1:
            raise ValueError("need at least one usable page")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.quant = quant
        #: payload bit width of the stored pages (8 also covers fp modes)
        self.quant_bits = 4 if quant == "int4" else 8
        shape = (num_layers, num_pages + 1, page_size, num_kv_heads,
                 head_dim)
        if not device_arrays:
            # host-only pool (serving/fleet.py's discrete-event sim): the
            # allocator / refcount / page-table machinery is the real
            # thing, but no device memory is ever touched — a 10^6-page
            # pool costs one numpy array, not gigabytes of jnp.zeros
            self.arrays = None
        elif quant == "int4":
            pshape = shape[:-1] + (head_dim // 2,)
            self.arrays = PoolArrays(
                k=jnp.zeros(pshape, jnp.uint8),
                v=jnp.zeros(pshape, jnp.uint8),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32))
        elif quant == "int8":
            self.arrays = PoolArrays(
                k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32))
        else:
            self.arrays = PoolArrays(k=jnp.zeros(shape, dtype),
                                     v=jnp.zeros(shape, dtype))
        # LIFO free list: recently freed pages are reused first (their
        # garbage is overwritten by the next prefill/decode write before
        # any masked read can see it)
        self._free: List[int] = list(range(num_pages, 0, -1))
        # copy-on-write reference counts (serving/prefix_cache.py): a
        # freshly allocated page has one owner; the radix prefix cache
        # and every slot sharing the page each hold one more.  A page
        # returns to the free list when its LAST owner releases it —
        # `free()` is decref, not destroy.  Without sharing every count
        # stays 0/1 and the pre-COW semantics are unchanged.
        self.refcount = np.zeros(num_pages + 1, np.int64)
        self.allocs = 0
        self.frees = 0

    # ---------------------------------------------------------- allocator
    def pages_for(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.page_size))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def utilization(self) -> float:
        return self.used_count / self.num_pages

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n pages off the free list (refcount 1 each), or None
        (caller queues) when the pool cannot satisfy the reservation."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self.refcount[pages] = 1
        self.allocs += n
        return pages

    def incref(self, pages: List[int]):
        """Add one owner to each live page (prefix-cache sharing)."""
        for p in pages:
            if not (0 < p <= self.num_pages):
                raise ValueError(f"incref of invalid page id {p}")
            if self.refcount[p] < 1:
                raise ValueError(f"incref of free page {p}")
        for p in pages:     # per-element (fancy indexing drops dups)
            self.refcount[p] += 1

    def free(self, pages: List[int]):
        """Release one ownership of each page (decref); a page whose
        last owner released it returns to the free list."""
        for p in pages:
            if not (0 < p <= self.num_pages):
                raise ValueError(f"freeing invalid page id {p}")
            if self.refcount[p] < 1 or p in self._free:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)
                self.frees += 1

    # ------------------------------------------------------ device ops
    # Pure functions over PoolArrays trees (the engine jits them inside
    # its step programs; `self` only contributes static shape info).

    def gather(self, arrays_tree, table):
        """Dense per-slot cache views from the pool.  table: [S, mp]
        int32 -> (ck, cv) [L, S, mp*page_size, n_kv, hd] in the compute
        dtype (int8 pages dequantize here)."""
        a = PoolArrays.from_tree(arrays_tree)
        L = self.num_layers
        S, mp = table.shape
        M = mp * self.page_size

        def dense(pool, scale):
            g = pool[:, table]       # [L, S, mp, ps, n_kv, hd(/2)]
            if self.quant == "int4":
                from hetu_tpu.ops.quantization import unpack_nibbles
                g = unpack_nibbles(g, even_high=False).astype(jnp.int32) - 8
            g = g.reshape(L, S, M, self.num_kv_heads, self.head_dim)
            if scale is None:
                return g
            sc = scale[:, table].reshape(L, S, M, self.num_kv_heads)
            return (g.astype(jnp.float32) * sc[..., None]).astype(self.dtype)

        return (dense(a.k, a.k_scale), dense(a.v, a.v_scale))

    def write_token(self, arrays_tree, table, positions, k_toks, v_toks):
        """Scatter one decoded token's K/V into the pool.  positions:
        [S] absolute write positions; k_toks/v_toks: [L, S, n_kv, hd].
        Slots whose table entry is the null page (inactive) dump their
        write harmlessly into it."""
        a = PoolArrays.from_tree(arrays_tree)
        S = positions.shape[0]
        page = table[jnp.arange(S), positions // self.page_size]
        off = positions % self.page_size

        def put(pool, scale, toks):
            if scale is None:
                return pool.at[:, page, off].set(toks.astype(pool.dtype)), None
            x32 = toks.astype(jnp.float32)
            q, s = quantize_heads(x32, self.quant_bits)
            _tap_kv_snr(x32, q, s, self.quant_bits)
            return (pool.at[:, page, off].set(q),
                    scale.at[:, page, off].set(s))

        nk, nks = put(a.k, a.k_scale, k_toks)
        nv, nvs = put(a.v, a.v_scale, v_toks)
        return PoolArrays(nk, nv, nks, nvs).tree()

    def write_tokens(self, arrays_tree, table, positions, k_toks, v_toks):
        """Scatter a BLOCK of tokens' K/V into the pool — the
        spec-decode verify step's write (k+1 tokens per slot per step).
        positions: [S, C] absolute write positions; k_toks/v_toks:
        [L, S, C, n_kv, hd].  Positions beyond a slot's table row
        (possible only for inactive rows riding along) redirect to the
        null page instead of clamp-corrupting the row's last page."""
        a = PoolArrays.from_tree(arrays_tree)
        S, C = positions.shape
        mp = table.shape[1]
        pidx = positions // self.page_size                     # [S, C]
        valid = pidx < mp
        page = jnp.where(
            valid,
            table[jnp.arange(S)[:, None], jnp.clip(pidx, 0, mp - 1)],
            PagePool.NULL_PAGE)
        off = positions % self.page_size

        def put(pool, scale, toks):
            if scale is None:
                return pool.at[:, page, off].set(toks.astype(pool.dtype)), None
            x32 = toks.astype(jnp.float32)
            q, s = quantize_heads(x32, self.quant_bits)
            _tap_kv_snr(x32, q, s, self.quant_bits)
            return (pool.at[:, page, off].set(q),
                    scale.at[:, page, off].set(s))

        nk, nks = put(a.k, a.k_scale, k_toks)
        nv, nvs = put(a.v, a.v_scale, v_toks)
        return PoolArrays(nk, nv, nks, nvs).tree()

    def write_pages(self, arrays_tree, pages_row, ks, vs):
        """Bulk-write a prefilled sequence's K/V into its pages.
        pages_row: [mp] int32 page ids (pad unused tail entries with the
        null page — their garbage lands in page 0); ks/vs:
        [L, mp*page_size, n_kv, hd]."""
        a = PoolArrays.from_tree(arrays_tree)
        L = self.num_layers
        mp = pages_row.shape[0]
        paged_shape = (L, mp, self.page_size, self.num_kv_heads,
                       self.head_dim)

        def put(pool, scale, x):
            x = x.reshape(paged_shape)
            if scale is None:
                return pool.at[:, pages_row].set(x.astype(pool.dtype)), None
            x32 = x.astype(jnp.float32)
            q, s = quantize_heads(x32, self.quant_bits)
            _tap_kv_snr(x32, q, s, self.quant_bits)
            return (pool.at[:, pages_row].set(q),
                    scale.at[:, pages_row].set(s))

        nk, nks = put(a.k, a.k_scale, ks)
        nv, nvs = put(a.v, a.v_scale, vs)
        return PoolArrays(nk, nv, nks, nvs).tree()
