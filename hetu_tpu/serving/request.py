"""Serving request/result records and their SLO accounting.

A `Request` is one user sequence: prompt ids + a decode budget.  The
engine stamps the SLO-relevant timeline into `RequestStats` using the
DRIVER'S clock (virtual in tests, wall in tools_serving.py) so TTFT /
e2e latency percentiles are deterministic under a simulated timeline.

Every request belongs to an `SLOClass` — a named latency contract
(TTFT target + per-token-gap target).  The default single class carries
no targets, so class-free callers see exactly the old behavior; classed
traffic gets per-class labeled histograms, attainment and goodput in
`serving/slo_report.py` (docs/serving.md).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """A named latency contract.  Targets are optional: None means the
    dimension is uncontracted (always attained); the default class has
    no targets at all — classless traffic reports attainment 1.0 and
    its tokens all count toward goodput.

    ``priority`` orders classes for preemptive admission
    (HETU_TPU_SERVE_PREEMPT): under slot/page pressure a queued request
    of a STRICTLY higher priority may evict-and-requeue the
    lowest-priority live slot.  0 (default) = every class equal —
    preemption can never fire between default-priority classes.

    ``deadline_s`` is an end-to-end wall budget from ARRIVAL: when
    deadline enforcement is on (HETU_TPU_SERVE_DEADLINE) a request
    still unfinished ``deadline_s`` after it arrived terminates as
    ``deadline_exceeded`` — a real terminal span, costed in the
    ledger.  None (default) = no deadline; with the flag unset the
    engine never even inspects it."""
    name: str = "default"
    ttft_s: Optional[float] = None       # arrival -> first token target
    token_gap_s: Optional[float] = None  # mean inter-token gap target
    priority: int = 0
    deadline_s: Optional[float] = None   # arrival -> done hard budget

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO class needs a name")
        for fld in ("ttft_s", "token_gap_s", "deadline_s"):
            v = getattr(self, fld)
            if v is not None and v <= 0:
                raise ValueError(f"SLO class {self.name!r}: {fld} must "
                                 f"be positive, got {v}")

    def to_dict(self) -> dict:
        return {"name": self.name, "ttft_s": self.ttft_s,
                "token_gap_s": self.token_gap_s,
                "priority": self.priority,
                "deadline_s": self.deadline_s}

    @staticmethod
    def parse(spec: str) -> "SLOClass":
        """``name[:ttft_s[:token_gap_s[:priority[:deadline_s]]]]``
        (empty/'-' = no target) — the CLI surface:
        ``--slo-class gold:0.2:0.05:2:30``.  Extra fields and
        non-numeric targets are loud errors: a silently dropped field
        would run a different contract than the user typed."""
        parts = spec.split(":")
        if not parts[0] or len(parts) > 5:
            raise ValueError(
                f"bad SLO class spec {spec!r}; want "
                "name[:ttft_s[:token_gap_s[:priority[:deadline_s]]]]")

        def num(i, what, cast=float):
            if len(parts) <= i or parts[i] in ("", "-"):
                return None
            try:
                return cast(parts[i])
            except ValueError:
                raise ValueError(
                    f"bad SLO class spec {spec!r}: {what} "
                    f"{parts[i]!r} is not a number (use '-' for no "
                    "target)") from None
        prio = num(3, "priority", int)
        return SLOClass(parts[0], num(1, "ttft_s"),
                        num(2, "token_gap_s"),
                        prio if prio is not None else 0,
                        num(4, "deadline_s"))


DEFAULT_SLO = SLOClass()


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission quota (HETU_TPU_SERVE_QUOTAS).

    Caps how many decode slots and KV pages requests of one tenant may
    hold LIVE at once; the scheduler checks the cap at admission, before
    touching the pool, and stalls the queue head with the
    ``quota_exceeded`` reason when its tenant is over.  0 = unlimited in
    that dimension; a tenant with no quota registered is unlimited in
    both — quota-free deployments see exactly the old admission path."""
    tenant: str
    max_slots: int = 0
    max_pages: int = 0

    def __post_init__(self):
        if not self.tenant:
            raise ValueError("tenant quota needs a tenant name")
        if self.max_slots < 0 or self.max_pages < 0:
            raise ValueError(
                f"tenant {self.tenant!r}: quota caps must be >= 0, got "
                f"slots={self.max_slots} pages={self.max_pages}")

    def to_dict(self) -> dict:
        return {"tenant": self.tenant, "max_slots": self.max_slots,
                "max_pages": self.max_pages}

    @staticmethod
    def parse(spec: str) -> "TenantQuota":
        """``tenant[:max_slots[:max_pages]]`` (empty/'-'/0 = unlimited)
        — the CLI/flag surface: ``HETU_TPU_SERVE_QUOTAS=acme:2:16,free:1:4``."""
        parts = spec.split(":")
        if not parts[0] or len(parts) > 3:
            raise ValueError(f"bad tenant quota spec {spec!r}; want "
                             "tenant[:max_slots[:max_pages]]")

        def num(i, what):
            if len(parts) <= i or parts[i] in ("", "-"):
                return 0
            try:
                return int(parts[i])
            except ValueError:
                raise ValueError(
                    f"bad tenant quota spec {spec!r}: {what} "
                    f"{parts[i]!r} is not an integer (use '-' for "
                    "unlimited)") from None
        return TenantQuota(parts[0], num(1, "max_slots"),
                           num(2, "max_pages"))


def parse_quotas(spec: str) -> dict:
    """Comma-separated TenantQuota specs -> {tenant: TenantQuota}.
    Empty/blank spec = no quotas (the identity contract of
    HETU_TPU_SERVE_QUOTAS)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        q = TenantQuota.parse(part)
        if q.tenant in out:
            raise ValueError(f"duplicate tenant quota for {q.tenant!r}")
        out[q.tenant] = q
    return out


#: Fibonacci-hash multiplier (2^64 / phi) for rid_sampled's bit mixer
_SAMPLE_MIX = 0x9E3779B97F4A7C15


def rid_sampled(rid: int, n: int) -> bool:
    """Deterministic 1-in-`n` request sampling for RunLog serve events
    and spans (HETU_TPU_RUNLOG_SERVE_SAMPLE): hash the rid, keep the
    1/n bucket.  The multiplicative mix matters — a plain ``rid % n``
    aliases with anything else assigned round-robin by rid (tenants,
    SLO classes in the workload builders share the same stride), so a
    modulo sample of a 2-tenant trace could contain ONE tenant.  The
    hash is a pure function of (rid, n): the same request is sampled on
    every replay, so goldens stay byte-identical."""
    if n <= 1:
        return True
    return ((rid * _SAMPLE_MIX) & 0xFFFFFFFFFFFFFFFF) >> 32 < \
        (1 << 32) // n


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding parameters (serving/sampling.py).

    The defaults are GREEDY: temperature 0 makes the sampler an argmax
    regardless of the filters, so a default-constructed request decodes
    exactly like the pre-sampling engine.  ``seed`` keys a per-request
    PRNG stream: the key for the token at sequence position p is
    ``fold_in(key(seed), p)`` — a pure function of (seed, position), so
    the same request replays to the same tokens across engine restarts,
    slot assignments and batch compositions (the determinism golden in
    tests/test_serving_decode.py)."""
    temperature: float = 0.0
    top_k: int = 0                     # 0 = filter disabled
    top_p: float = 0.0                 # 0.0 (or >= 1.0) = disabled
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {self.top_p}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def to_dict(self) -> dict:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


GREEDY = SamplingParams()


@dataclasses.dataclass
class Request:
    """One generation request (greedy decode unless ``sampling`` says
    otherwise; per-request EOS)."""
    rid: int
    prompt: np.ndarray                 # [plen] int32 token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_t: float = 0.0
    slo: SLOClass = DEFAULT_SLO
    sampling: SamplingParams = GREEDY
    #: who this request bills to: per-tenant quotas gate admission
    #: (scheduler), and slo_report/costs aggregate per tenant.  The
    #: default tenant keeps tenant-free callers byte-identical.
    tenant: str = "default"

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be "
                             ">= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def total_len(self) -> int:
        """Worst-case cache footprint (prompt + full decode budget) —
        what the scheduler reserves pages for at admission."""
        return self.prompt_len + self.max_new_tokens


@dataclasses.dataclass
class RequestStats:
    """Per-request SLO timeline (driver-clock seconds) + the decoding
    subsystem's per-request accounting (spec-decode acceptance, prefix
    cache hits, preemptions — serving/slo_report.py aggregates these
    from the ``done`` events)."""
    arrival_t: float = 0.0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    prefill_chunks: int = 0
    #: speculative decoding (serving/spec_decode.py): draft tokens
    #: proposed / accepted over the request's verify steps
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: prompt tokens admitted with their KV pages already resident
    #: (serving/prefix_cache.py) — prefill skipped them entirely
    shared_prefix_tokens: int = 0
    #: times this request was evicted-and-requeued by a higher-priority
    #: admission (HETU_TPU_SERVE_PREEMPT)
    preemptions: int = 0
    #: times this request re-entered the queue after its serving
    #: replica died (chaos ``engine_kill``; budget HETU_TPU_SERVE_RETRY)
    retries: int = 0

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait counts: a user
        staring at a spinner does not care which side of the scheduler
        the time went)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.arrival_t


@dataclasses.dataclass
class RequestResult:
    """What the engine hands back when a request completes."""
    rid: int
    tokens: List[int]                  # generated ids (EOS included)
    #: "eos" | "length" on the happy path; fault terminations use
    #: "deadline_exceeded" (HETU_TPU_SERVE_DEADLINE), "brownout_shed"
    #: (HETU_TPU_SERVE_BROWNOUT) and "retry_exhausted" (an engine_kill
    #: past the HETU_TPU_SERVE_RETRY budget)
    finished_reason: str
    stats: RequestStats = dataclasses.field(default_factory=RequestStats)

    @property
    def tokens_per_s(self) -> Optional[float]:
        e2e = self.stats.e2e_s
        if not e2e or e2e <= 0:
            return None
        return len(self.tokens) / e2e
