"""Serving request/result records and their SLO accounting.

A `Request` is one user sequence: prompt ids + a decode budget.  The
engine stamps the SLO-relevant timeline into `RequestStats` using the
DRIVER'S clock (virtual in tests, wall in tools_serving.py) so TTFT /
e2e latency percentiles are deterministic under a simulated timeline.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request (greedy decode; per-request EOS)."""
    rid: int
    prompt: np.ndarray                 # [plen] int32 token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_t: float = 0.0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be "
                             ">= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def total_len(self) -> int:
        """Worst-case cache footprint (prompt + full decode budget) —
        what the scheduler reserves pages for at admission."""
        return self.prompt_len + self.max_new_tokens


@dataclasses.dataclass
class RequestStats:
    """Per-request SLO timeline (driver-clock seconds)."""
    arrival_t: float = 0.0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    prefill_chunks: int = 0

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait counts: a user
        staring at a spinner does not care which side of the scheduler
        the time went)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.arrival_t


@dataclasses.dataclass
class RequestResult:
    """What the engine hands back when a request completes."""
    rid: int
    tokens: List[int]                  # generated ids (EOS included)
    finished_reason: str               # "eos" | "length"
    stats: RequestStats = dataclasses.field(default_factory=RequestStats)

    @property
    def tokens_per_s(self) -> Optional[float]:
        e2e = self.stats.e2e_s
        if not e2e or e2e <= 0:
            return None
        return len(self.tokens) / e2e
