"""Serving request/result records and their SLO accounting.

A `Request` is one user sequence: prompt ids + a decode budget.  The
engine stamps the SLO-relevant timeline into `RequestStats` using the
DRIVER'S clock (virtual in tests, wall in tools_serving.py) so TTFT /
e2e latency percentiles are deterministic under a simulated timeline.

Every request belongs to an `SLOClass` — a named latency contract
(TTFT target + per-token-gap target).  The default single class carries
no targets, so class-free callers see exactly the old behavior; classed
traffic gets per-class labeled histograms, attainment and goodput in
`serving/slo_report.py` (docs/serving.md).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """A named latency contract.  Targets are optional: None means the
    dimension is uncontracted (always attained); the default class has
    no targets at all — classless traffic reports attainment 1.0 and
    its tokens all count toward goodput.

    ``priority`` orders classes for preemptive admission
    (HETU_TPU_SERVE_PREEMPT): under slot/page pressure a queued request
    of a STRICTLY higher priority may evict-and-requeue the
    lowest-priority live slot.  0 (default) = every class equal —
    preemption can never fire between default-priority classes."""
    name: str = "default"
    ttft_s: Optional[float] = None       # arrival -> first token target
    token_gap_s: Optional[float] = None  # mean inter-token gap target
    priority: int = 0

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO class needs a name")
        for fld in ("ttft_s", "token_gap_s"):
            v = getattr(self, fld)
            if v is not None and v <= 0:
                raise ValueError(f"SLO class {self.name!r}: {fld} must "
                                 f"be positive, got {v}")

    def to_dict(self) -> dict:
        return {"name": self.name, "ttft_s": self.ttft_s,
                "token_gap_s": self.token_gap_s,
                "priority": self.priority}

    @staticmethod
    def parse(spec: str) -> "SLOClass":
        """``name[:ttft_s[:token_gap_s[:priority]]]`` (empty/'-' = no
        target) — the CLI surface: ``--slo-class gold:0.2:0.05:2``.
        Extra fields and non-numeric targets are loud errors: a
        silently dropped field would run a different contract than the
        user typed."""
        parts = spec.split(":")
        if not parts[0] or len(parts) > 4:
            raise ValueError(f"bad SLO class spec {spec!r}; want "
                             "name[:ttft_s[:token_gap_s[:priority]]]")

        def num(i, what, cast=float):
            if len(parts) <= i or parts[i] in ("", "-"):
                return None
            try:
                return cast(parts[i])
            except ValueError:
                raise ValueError(
                    f"bad SLO class spec {spec!r}: {what} "
                    f"{parts[i]!r} is not a number (use '-' for no "
                    "target)") from None
        prio = num(3, "priority", int)
        return SLOClass(parts[0], num(1, "ttft_s"),
                        num(2, "token_gap_s"),
                        prio if prio is not None else 0)


DEFAULT_SLO = SLOClass()


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decoding parameters (serving/sampling.py).

    The defaults are GREEDY: temperature 0 makes the sampler an argmax
    regardless of the filters, so a default-constructed request decodes
    exactly like the pre-sampling engine.  ``seed`` keys a per-request
    PRNG stream: the key for the token at sequence position p is
    ``fold_in(key(seed), p)`` — a pure function of (seed, position), so
    the same request replays to the same tokens across engine restarts,
    slot assignments and batch compositions (the determinism golden in
    tests/test_serving_decode.py)."""
    temperature: float = 0.0
    top_k: int = 0                     # 0 = filter disabled
    top_p: float = 0.0                 # 0.0 (or >= 1.0) = disabled
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {self.top_p}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def to_dict(self) -> dict:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


GREEDY = SamplingParams()


@dataclasses.dataclass
class Request:
    """One generation request (greedy decode unless ``sampling`` says
    otherwise; per-request EOS)."""
    rid: int
    prompt: np.ndarray                 # [plen] int32 token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_t: float = 0.0
    slo: SLOClass = DEFAULT_SLO
    sampling: SamplingParams = GREEDY

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be "
                             ">= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def total_len(self) -> int:
        """Worst-case cache footprint (prompt + full decode budget) —
        what the scheduler reserves pages for at admission."""
        return self.prompt_len + self.max_new_tokens


@dataclasses.dataclass
class RequestStats:
    """Per-request SLO timeline (driver-clock seconds) + the decoding
    subsystem's per-request accounting (spec-decode acceptance, prefix
    cache hits, preemptions — serving/slo_report.py aggregates these
    from the ``done`` events)."""
    arrival_t: float = 0.0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    prefill_chunks: int = 0
    #: speculative decoding (serving/spec_decode.py): draft tokens
    #: proposed / accepted over the request's verify steps
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: prompt tokens admitted with their KV pages already resident
    #: (serving/prefix_cache.py) — prefill skipped them entirely
    shared_prefix_tokens: int = 0
    #: times this request was evicted-and-requeued by a higher-priority
    #: admission (HETU_TPU_SERVE_PREEMPT)
    preemptions: int = 0

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait counts: a user
        staring at a spinner does not care which side of the scheduler
        the time went)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.arrival_t


@dataclasses.dataclass
class RequestResult:
    """What the engine hands back when a request completes."""
    rid: int
    tokens: List[int]                  # generated ids (EOS included)
    finished_reason: str               # "eos" | "length"
    stats: RequestStats = dataclasses.field(default_factory=RequestStats)

    @property
    def tokens_per_s(self) -> Optional[float]:
        e2e = self.stats.e2e_s
        if not e2e or e2e <= 0:
            return None
        return len(self.tokens) / e2e
