"""Serving request/result records and their SLO accounting.

A `Request` is one user sequence: prompt ids + a decode budget.  The
engine stamps the SLO-relevant timeline into `RequestStats` using the
DRIVER'S clock (virtual in tests, wall in tools_serving.py) so TTFT /
e2e latency percentiles are deterministic under a simulated timeline.

Every request belongs to an `SLOClass` — a named latency contract
(TTFT target + per-token-gap target).  The default single class carries
no targets, so class-free callers see exactly the old behavior; classed
traffic gets per-class labeled histograms, attainment and goodput in
`serving/slo_report.py` (docs/serving.md).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """A named latency contract.  Targets are optional: None means the
    dimension is uncontracted (always attained); the default class has
    no targets at all — classless traffic reports attainment 1.0 and
    its tokens all count toward goodput."""
    name: str = "default"
    ttft_s: Optional[float] = None       # arrival -> first token target
    token_gap_s: Optional[float] = None  # mean inter-token gap target

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO class needs a name")
        for fld in ("ttft_s", "token_gap_s"):
            v = getattr(self, fld)
            if v is not None and v <= 0:
                raise ValueError(f"SLO class {self.name!r}: {fld} must "
                                 f"be positive, got {v}")

    def to_dict(self) -> dict:
        return {"name": self.name, "ttft_s": self.ttft_s,
                "token_gap_s": self.token_gap_s}

    @staticmethod
    def parse(spec: str) -> "SLOClass":
        """``name[:ttft_s[:token_gap_s]]`` (empty/'-' = no target) —
        the CLI surface: ``--slo-class gold:0.2:0.05``.  Extra fields
        and non-numeric targets are loud errors: a silently dropped
        field would run a different contract than the user typed."""
        parts = spec.split(":")
        if not parts[0] or len(parts) > 3:
            raise ValueError(f"bad SLO class spec {spec!r}; want "
                             "name[:ttft_s[:token_gap_s]]")

        def num(i, what):
            if len(parts) <= i or parts[i] in ("", "-"):
                return None
            try:
                return float(parts[i])
            except ValueError:
                raise ValueError(
                    f"bad SLO class spec {spec!r}: {what} "
                    f"{parts[i]!r} is not a number (use '-' for no "
                    "target)") from None
        return SLOClass(parts[0], num(1, "ttft_s"),
                        num(2, "token_gap_s"))


DEFAULT_SLO = SLOClass()


@dataclasses.dataclass
class Request:
    """One generation request (greedy decode; per-request EOS)."""
    rid: int
    prompt: np.ndarray                 # [plen] int32 token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_t: float = 0.0
    slo: SLOClass = DEFAULT_SLO

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be "
                             ">= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def total_len(self) -> int:
        """Worst-case cache footprint (prompt + full decode budget) —
        what the scheduler reserves pages for at admission."""
        return self.prompt_len + self.max_new_tokens


@dataclasses.dataclass
class RequestStats:
    """Per-request SLO timeline (driver-clock seconds)."""
    arrival_t: float = 0.0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    prefill_chunks: int = 0

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait counts: a user
        staring at a spinner does not care which side of the scheduler
        the time went)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.arrival_t


@dataclasses.dataclass
class RequestResult:
    """What the engine hands back when a request completes."""
    rid: int
    tokens: List[int]                  # generated ids (EOS included)
    finished_reason: str               # "eos" | "length"
    stats: RequestStats = dataclasses.field(default_factory=RequestStats)

    @property
    def tokens_per_s(self) -> Optional[float]:
        e2e = self.stats.e2e_s
        if not e2e or e2e <= 0:
            return None
        return len(self.tokens) / e2e
