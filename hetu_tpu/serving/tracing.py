"""Request-scoped span tracer: the serving engine's flight recorder.

`RequestTracer` turns the engine's host-side lifecycle callbacks into
the typed spans of `obs/spans.py`, recorded as schema-versioned ``span``
RunLog records.  Timestamps are the DRIVER's clock (the same virtual
clock `ServingEngine.run` advances), so a replayed trace is
deterministic and span durations reconcile with the SLO timeline in
`RequestStats` exactly.

Tiling contract: every span of a request opens where the previous one
closed —

    queued   [arrival_t, admit_t]                (reason: none|no_slot|no_pages)
    prefill  [prev_end, chunk_end]               one per chunk; last ends at TTFT
    decode   [prev_end, boundary]                split at evictions/reshard pauses
    reshard_pause [pause_t0, pause_t1]
    done/evicted/deadline_exceeded/hedge_withdrawn [t, t]
                                                 zero-duration terminal

so ``sum(durations) == done_t - arrival_t == e2e_s`` by construction
(`slo_report` property-tests the reconciliation).

A request may be RE-queued inside the same trace — by a preemption
(``preempted``) or by an engine failover (``replica_lost``,
HETU_TPU_SERVE_RETRY).  Each requeue bumps the per-request ``attempt``
index (first admission = attempt 1); spans emitted on later attempts
carry an ``attempt`` attr so readers reconcile per-attempt instead of
corrupting the first attempt's tiling.  Attempt-1 spans stay
byte-identical to the pre-failover schema (no attr stamped).

Gated by ``HETU_TPU_SERVE_TRACE`` (`maybe_tracer`): unset means the
engine holds no tracer and does zero per-step tracing work — a single
None check, the `maybe_health_monitor` discipline.  The tracer itself
never touches the device: enabling it cannot perturb any compiled
program (enforced by the flag's registered identity contract).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from hetu_tpu.obs.spans import (SPAN_SCHEMA, RequestTrace, Span,
                                new_trace_id)


class _Open:
    """Per-request open state between span boundaries."""

    __slots__ = ("rid", "trace", "slo_class", "slot", "phase", "last_t",
                 "stall_reason", "seg_tokens", "seg_index", "chunks",
                 "attempt")

    def __init__(self, rid: int, trace: str, slo_class: str,
                 arrival_t: float):
        self.rid = rid
        self.trace = trace
        self.slo_class = slo_class
        self.slot: Optional[int] = None
        self.phase = "queued"
        self.last_t = arrival_t          # where the next span opens
        self.stall_reason = "none"       # reserve-on-admit attribution
        self.seg_tokens = 0              # tokens in the open decode seg
        self.seg_index = 0
        self.chunks = 0
        self.attempt = 1                 # bumped on every requeue


class RequestTracer:
    """Records request lifecycle spans; one instance per engine.

    ``run_log`` receives one ``span`` record per closed span; with
    ``keep=True`` (the default when no run_log is given) completed
    traces are also held in memory (``traces``) for direct inspection —
    tests and the fuzz harness read them without a disk round-trip.
    """

    def __init__(self, run_log=None, registry=None,
                 keep: Optional[bool] = None, max_kept: int = 4096,
                 tier: Optional[str] = None,
                 replica: Optional[int] = None, clock: str = "driver"):
        self.run_log = run_log
        self.registry = registry
        self.keep = (run_log is None) if keep is None else keep
        self.max_kept = max_kept
        #: hop identity (the fleet trace context): ``tier`` names the
        #: pipeline stage this tracer records (prefill|decode; None =
        #: a single colocated engine), ``replica`` the engine index
        #: behind a routing frontend (the frontend stamps it), and
        #: ``clock`` the timestamp basis every span declares
        self.tier = tier
        self.replica = replica
        self.clock = clock
        self._open: Dict[int, _Open] = {}
        #: completed RequestTraces by rid (keep=True only; bounded to
        #: the newest ``max_kept`` so a long-lived runlog-less engine
        #: cannot grow without limit)
        self.traces: Dict[int, RequestTrace] = {}
        #: every completed trace in completion order (keep=True; same
        #: bound) — unlike ``traces`` a rid that carried SEVERAL hops
        #: on this tracer (prefill re-prefills) keeps them all, which
        #: is what `FleetTrace.stitch` wants
        self.completed: List[RequestTrace] = []
        self._kept: Dict[int, RequestTrace] = {}
        self.spans_emitted = 0

    # ------------------------------------------------------------- emit
    def _emit(self, st: _Open, kind: str, t0: float, t1: float,
              **attrs: Any):
        if st.attempt > 1:
            # attempt-1 spans keep the pre-failover record shape
            attrs.setdefault("attempt", st.attempt)
        span = Span(kind=kind, t0=t0, t1=t1, rid=st.rid, trace=st.trace,
                    slot=st.slot, slo_class=st.slo_class,
                    clock=self.clock, tier=self.tier,
                    replica=self.replica, attrs=attrs)
        self.spans_emitted += 1
        if self.registry is not None:
            self.registry.inc("serve.spans", span=kind)
        if self.run_log is not None:
            self.run_log.log("span", **span.record())
        if self.keep:
            tr = self._kept.get(st.rid)
            if tr is None or tr.trace != st.trace:
                tr = self._kept[st.rid] = RequestTrace(
                    rid=st.rid, trace=st.trace, slo_class=st.slo_class)
            tr.spans.append(span)

    # -------------------------------------------------------- lifecycle
    def on_submit(self, req, at: Optional[float] = None) -> str:
        """A request entered the queue; opens the queued span at its
        arrival time (or ``at`` — a prefill-tier hop opens at ROUTING
        time, not arrival, so sibling hops don't double-open the same
        wait).  Returns the assigned trace id."""
        trace = new_trace_id(req.rid)
        slo = getattr(req, "slo", None)
        self._open[req.rid] = _Open(
            req.rid, trace, slo.name if slo is not None else "default",
            float(req.arrival_t) if at is None else float(at))
        return trace

    def on_stall(self, rids: Iterable[int], reason: str):
        """The scheduler declined admission this step; stamp the
        reserve-on-admit reason on every still-queued request (the
        LAST observed reason wins — it names what the request was
        actually waiting on when it finally mattered).  A `preempted`,
        `replica_lost`, or `prefill_tier_down` stamp is sticky: the
        request is back in the queue BECAUSE it was evicted / its
        replica died / its prefill tier went down, and that attribution
        must survive later stalls."""
        for rid in rids:
            st = self._open.get(rid)
            if (st is not None and st.phase == "queued"
                    and st.stall_reason not in ("preempted",
                                                "replica_lost",
                                                "prefill_tier_down")):
                st.stall_reason = reason

    def on_admit(self, req, slot: int, now: float,
                 shared_tokens: int = 0):
        st = self._open.get(req.rid)
        if st is None:
            return
        st.slot = slot
        self._emit(st, "queued", st.last_t, now, reason=st.stall_reason,
                   **({"shared_tokens": shared_tokens}
                      if shared_tokens else {}))
        st.phase = "prefill"
        st.last_t = now

    def on_chunk(self, req, now: float, chunk: int):
        """A non-final prefill chunk landed; the span absorbs any
        inter-step wait since the previous boundary (tiling)."""
        st = self._open.get(req.rid)
        if st is None:
            return
        st.chunks = chunk
        self._emit(st, "prefill", st.last_t, now, chunk=chunk)
        st.last_t = now

    def on_first_token(self, req, slot: int, now: float, *, chunk: int):
        """The final prefill chunk landed and the first token was
        emitted (TTFT); closes prefill and opens the decode segment."""
        st = self._open.get(req.rid)
        if st is None:
            return
        st.slot = slot
        st.chunks = chunk
        self._emit(st, "prefill", st.last_t, now, chunk=chunk, last=True)
        st.phase = "decode"
        st.last_t = now
        st.seg_tokens = 0
        st.seg_index = 0

    def on_token(self, req, now: float):
        st = self._open.get(req.rid)
        if st is not None and st.phase == "decode":
            st.seg_tokens += 1

    def _close_segment(self, st: _Open, now: float, end: str):
        if st.phase != "decode":
            return
        if now > st.last_t or st.seg_tokens:
            self._emit(st, "decode", st.last_t, now,
                       tokens=st.seg_tokens, segment=st.seg_index,
                       end=end)
            st.seg_index += 1
        st.last_t = now
        st.seg_tokens = 0

    def on_split(self, rids: Iterable[int], now: float, why: str):
        """A batch-composition change (an eviction) at `now`: close the
        survivors' decode segments so the boundary is visible."""
        for rid in rids:
            st = self._open.get(rid)
            if st is not None:
                self._close_segment(st, now, end=why)

    def _requeue(self, req, slot: int, now: float, *, reason: str,
                 end: str):
        """Close the open decode segment (or the partial prefill) and
        re-enter the QUEUED phase inside the SAME trace with a sticky
        stall reason — the re-admission emits another queued span, so
        the tiling (and the span-vs-e2e reconciliation) stays exact
        across the requeue.  Bumps the ``attempt`` index: every span
        emitted from here on carries ``attempt`` so readers reconcile
        per-attempt."""
        st = self._open.get(req.rid)
        if st is None:
            return
        st.slot = slot
        if st.phase == "decode":
            self._close_segment(st, now, end=end)
        elif st.phase == "prefill" and now > st.last_t:
            self._emit(st, "prefill", st.last_t, now, chunk=st.chunks,
                       discarded=True)
            st.last_t = now
        st.phase = "queued"
        st.stall_reason = reason
        st.slot = None
        st.chunks = 0
        st.seg_tokens = 0
        st.seg_index = 0
        st.attempt += 1

    def on_preempt(self, req, slot: int, now: float, *,
                   by: Optional[int] = None):
        """A higher-priority admission evicted this request
        (HETU_TPU_SERVE_PREEMPT); stall reason ``preempted``."""
        self._requeue(req, slot, now, reason="preempted", end="preempt")

    def on_replica_lost(self, req, slot: int, now: float):
        """The engine (replica) serving this request died (chaos
        ``engine_kill``) and the request re-entered the queue under its
        retry budget (HETU_TPU_SERVE_RETRY); stall reason
        ``replica_lost``.  The warm radix prefix cache makes the
        re-prefill cheap and seeded sampling replays the exact token
        stream — the trace shows the failover as a requeue boundary,
        not a fresh trace."""
        self._requeue(req, slot, now, reason="replica_lost",
                      end="replica_lost")

    def on_pause(self, rids: Iterable[int], t0: float, t1: float,
                 **attrs: Any):
        """A reshard froze decode over [t0, t1]: split segments at t0,
        record the pause, and reopen at t1."""
        for rid in rids:
            st = self._open.get(rid)
            if st is None or st.phase != "decode":
                continue
            self._close_segment(st, t0, end="reshard")
            self._emit(st, "reshard_pause", t0, t1, **attrs)
            st.last_t = t1

    def _finalize(self, st: _Open, kind: str, now: float, **attrs: Any):
        """Emit the zero-duration terminal span and retire the trace."""
        self._emit(st, kind, now, now, **attrs)
        if self.keep and st.rid in self._kept:
            tr = self._kept.pop(st.rid)
            self.traces[st.rid] = tr
            self.completed.append(tr)
            while len(self.traces) > self.max_kept:
                # dicts iterate in insertion order: drop the oldest
                self.traces.pop(next(iter(self.traces)))
            if len(self.completed) > self.max_kept:
                del self.completed[: len(self.completed)
                                   - self.max_kept]

    def on_finish(self, req, slot: int, reason: str, now: float, *,
                  tokens: Optional[int] = None, e2e_s=None,
                  evicted: bool = False):
        """Terminal: close the open decode segment and emit the
        zero-duration ``done`` (or ``evicted``) span.  A mid-prefill
        eviction (a retry-exhausted failover) tiles its partial
        prefill as discarded so the trace still covers [arrival,
        terminal] exactly; a still-QUEUED finish (a disaggregated
        re-prefill that exhausted the retry budget before any
        admission) tiles the queued wait the same way on_expire
        does."""
        st = self._open.pop(req.rid, None)
        if st is None:
            return
        st.slot = slot
        if st.phase == "queued":
            self._emit(st, "queued", st.last_t, now,
                       reason=st.stall_reason)
            st.last_t = now
        elif st.phase == "prefill":
            if now > st.last_t:
                self._emit(st, "prefill", st.last_t, now,
                           chunk=st.chunks, discarded=True)
                st.last_t = now
        else:
            self._close_segment(st, now, end="finish")
        kind = "evicted" if evicted else "done"
        self._finalize(st, kind, now, reason=reason, tokens=tokens,
                       e2e_s=e2e_s, chunks=st.chunks)

    def on_expire(self, req, now: float, *, tokens: int = 0,
                  e2e_s=None):
        """The request's SLO deadline expired (HETU_TPU_SERVE_DEADLINE):
        tile the trace up to ``now`` from whatever phase it was in —
        the un-admitted queued wait, a discarded partial prefill, or
        the open decode segment — then emit the zero-duration
        ``deadline_exceeded`` terminal span."""
        st = self._open.pop(req.rid, None)
        if st is None:
            return
        if st.phase == "queued":
            self._emit(st, "queued", st.last_t, now,
                       reason=st.stall_reason)
        elif st.phase == "prefill" and now > st.last_t:
            self._emit(st, "prefill", st.last_t, now, chunk=st.chunks,
                       discarded=True)
        else:
            self._close_segment(st, now, end="expire")
        self._finalize(st, "deadline_exceeded", now,
                       reason="deadline_exceeded", tokens=tokens,
                       e2e_s=e2e_s, chunks=st.chunks)

    def on_withdraw(self, req, now: float, *,
                    reason: str = "hedge_loss"):
        """The frontend withdrew this copy of the request from this
        replica — the losing side of a hedged dispatch
        (``reason="hedge_loss"``), or a dead replica's queue being
        re-routed (``reason="rerouted"``).  Close whatever phase is
        open and emit the ``hedge_withdrawn`` terminal so fleet-wide
        span accounting includes the discarded wait/work: stitched
        span-seconds equal the sum of per-attempt lifetimes, losers
        included."""
        st = self._open.pop(req.rid, None)
        if st is None:
            return
        if st.phase == "queued":
            self._emit(st, "queued", st.last_t, now,
                       reason=st.stall_reason)
        elif st.phase == "prefill":
            if now > st.last_t:
                self._emit(st, "prefill", st.last_t, now,
                           chunk=st.chunks, discarded=True)
        else:
            self._close_segment(st, now, end="withdraw")
        self._finalize(st, "hedge_withdrawn", now, reason=reason,
                       tokens=st.seg_tokens, chunks=st.chunks)

    def on_shed(self, req, now: float):
        """The brownout policy shed this still-queued request
        (HETU_TPU_SERVE_BROWNOUT): close its queued span with the
        ``brownout_shed`` stall reason and emit the ``evicted``
        terminal carrying the same reason."""
        st = self._open.pop(req.rid, None)
        if st is None:
            return
        st.stall_reason = "brownout_shed"
        self._emit(st, "queued", st.last_t, now, reason="brownout_shed")
        self._finalize(st, "evicted", now, reason="brownout_shed",
                       tokens=0, e2e_s=now - float(req.arrival_t),
                       chunks=st.chunks)

    # ------------------------------------------------------------ debug
    def is_open(self, rid: int) -> bool:
        """True while rid has an open (un-terminated) hop here — the
        fleet sim's guard for idempotent hop closes."""
        return rid in self._open

    def open_requests(self) -> List[int]:
        return sorted(self._open)


def maybe_tracer(run_log=None, registry=None,
                 **kw) -> Optional[RequestTracer]:
    """A RequestTracer when HETU_TPU_SERVE_TRACE is set, else None —
    the one gate the engine uses, so 'flag unset' provably means zero
    per-request tracing work (a single None check)."""
    from hetu_tpu.utils import flags
    if not flags.bool_flag("HETU_TPU_SERVE_TRACE"):
        return None
    return RequestTracer(run_log=run_log, registry=registry, **kw)


__all__ = ["RequestTracer", "maybe_tracer", "SPAN_SCHEMA"]
