"""Fleet observatory: a seeded discrete-event simulator driving the REAL
serving state machines hardware-free.

ROADMAP item 1's proving ground: every fleet-scale policy question
(multi-tenant quotas, preemption fairness, prefix-cache sizing) is
answered by replaying 10^6+ requests through the SAME host-side state
machines the live engine runs — `Scheduler` (admission, reserve-on-
admit, preemption), `PagePool`/`RadixPrefixCache` (COW refcounts, LRU
eviction), `RequestTracer` spans and the SLO/priority policies — under a
virtual clock whose per-step service times come from a pluggable
analytic `ServiceModel` (the bench.py ``detail.serving`` roofline:
params read once per step, every slot reads its context KV), NOT from
running any jax program.  No jax math anywhere in the hot loop: a
million requests complete in seconds, and `check_invariants()` + span
reconciliation fuzz at a scale the jitted tests cannot reach.

What is simulated vs real:

* REAL: admission order, page allocation/eviction/refcounts, tenant
  quotas, preemption victims, span tiling, stall attribution — every
  policy decision is made by the production code path.
* MODELED: step durations (`ServiceModel` roofline) and token values
  (requests always finish by length; no logits exist).  A chaos
  `FaultPlan`'s ``slow_worker``/``decode_stall`` windows inflate the
  modeled step time exactly like the engine's on_step hook inflates
  the wall clock, and its ``engine_kill`` specs drive replica
  death/rejoin: at ``at_step`` every in-flight request is requeued
  under the retry budget (or terminated ``retry_exhausted``), and
  admissions stay suspended for the spec's ``count``-step down-window
  until the replica rejoins.  Deadlines and brownout shedding run the
  same policy code shape as the live engine (docs/fault_tolerance.md).

Accounting is EXACT regardless of RunLog sampling: per-(tenant, class)
aggregates (attainment, goodput, latency reservoirs, stall and cost
attribution) are accumulated in memory for every request, while serve
events / spans are emitted for a deterministic 1-in-N sample of
requests (``HETU_TPU_RUNLOG_SERVE_SAMPLE``) with ``sample_weight`` so
`slo_report.py` stays unbiased.  The report is derived ONLY from the
virtual clock — same seed + trace, byte-identical `tools_fleet.py
--json` output (docs/serving.md).
"""
from __future__ import annotations

import collections
import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

from hetu_tpu.obs.metrics import Histogram
from hetu_tpu.obs.spans import _EDGE_EVENTS, FleetTrace
from hetu_tpu.serving.costs import COST_FIELDS, CostLedger, CostModel
from hetu_tpu.serving.kv_pool import PagePool, kv_bytes_per_token
from hetu_tpu.serving.request import Request, TenantQuota, rid_sampled
from hetu_tpu.serving.scheduler import Scheduler
from hetu_tpu.serving.tracing import RequestTracer

#: bump when the `tools_fleet.py --json` report shape changes
#: (2: faults.tokens_discarded + the two-tier `disagg` section)
FLEET_SCHEMA = 2


@dataclasses.dataclass(frozen=True)
class ServiceModel:
    """Analytic per-step service times: the roofline bench.py's
    ``detail.serving`` record prices decode with (params read once per
    step, each slot reads its own context KV; FLOPs = 2N per token +
    4*L*hidden per cached position), turned into a pluggable clock for
    the simulator.  Frozen + pure arithmetic — deterministic and safe
    in the 10^6-request hot loop."""
    #: matmul FLOPs per computed token (2 * N_params)
    flops_per_token: float
    #: attention FLOPs per computed token per cached context position
    attn_flops_per_ctx: float
    #: parameter bytes streamed once per step (bf16 = 2 * N_params)
    param_bytes: float
    #: cache bytes per resident token position (kv_pool byte model)
    kv_bytes_per_token: float
    #: chip peak (obs/mfu hardware profile)
    peak_flops: float
    hbm_bytes_per_s: float
    #: fixed per-step host/dispatch overhead
    step_overhead_s: float = 50e-6

    @staticmethod
    def from_hardware_profile(*, num_params: float, num_layers: int,
                              hidden_size: int, num_kv_heads: int,
                              head_dim: int, kv_mode: str = "fp16",
                              hw: Optional[dict] = None,
                              step_overhead_s: float = 50e-6
                              ) -> "ServiceModel":
        """Calibrate from the profiled chip (obs/mfu
        `load_hardware_profile`) + model dimensions — the exact inputs
        bench.py's serving roofline uses, so simulated tokens/s and the
        BENCH record can never disagree on the formula."""
        if hw is None:
            from hetu_tpu.obs.mfu import load_hardware_profile
            hw = load_hardware_profile()
        return ServiceModel(
            flops_per_token=2.0 * float(num_params),
            attn_flops_per_ctx=4.0 * num_layers * hidden_size,
            param_bytes=2.0 * float(num_params),
            kv_bytes_per_token=kv_bytes_per_token(
                num_layers, num_kv_heads, head_dim, kv_mode),
            peak_flops=float(hw["bf16_tflops"]) * 1e12,
            hbm_bytes_per_s=float(hw["hbm_gbps"]) * 1e9,
            step_overhead_s=step_overhead_s)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def prefill_chunk_s(self, chunk: int, ctx: int) -> float:
        """One padded prefill chunk of `chunk` tokens starting at cache
        position `ctx` (static shapes: the PADDED chunk runs)."""
        flops = (self.flops_per_token * chunk
                 + self.attn_flops_per_ctx
                 * (ctx * chunk + chunk * (chunk - 1) / 2.0))
        bytes_ = (self.param_bytes
                  + (ctx + chunk) * self.kv_bytes_per_token)
        return max(flops / self.peak_flops,
                   bytes_ / self.hbm_bytes_per_s) + self.step_overhead_s

    def decode_step_s(self, slots: int, kv_tokens: int) -> float:
        """One batched decode step: `slots` active rows, `kv_tokens`
        total resident context positions read."""
        if slots <= 0:
            return 0.0
        flops = (self.flops_per_token * slots
                 + self.attn_flops_per_ctx * kv_tokens)
        bytes_ = self.param_bytes + kv_tokens * self.kv_bytes_per_token
        return max(flops / self.peak_flops,
                   bytes_ / self.hbm_bytes_per_s) + self.step_overhead_s


def analytic_models(*, num_params: float, num_layers: int,
                    hidden_size: int, num_kv_heads: int, head_dim: int,
                    page_size: int, kv_mode: str = "fp16",
                    hw: Optional[dict] = None
                    ) -> "tuple[ServiceModel, CostModel]":
    """The matched (ServiceModel, CostModel) pair for one model+chip:
    time and cost priced from the same dimensions, so a fleet report's
    latency and FLOPs columns describe the same machine."""
    svc = ServiceModel.from_hardware_profile(
        num_params=num_params, num_layers=num_layers,
        hidden_size=hidden_size, num_kv_heads=num_kv_heads,
        head_dim=head_dim, kv_mode=kv_mode, hw=hw)
    cost = CostModel.from_model_dims(
        num_params=num_params, num_layers=num_layers,
        hidden_size=hidden_size, num_kv_heads=num_kv_heads,
        head_dim=head_dim, page_size=page_size, kv_mode=kv_mode)
    return svc, cost


@dataclasses.dataclass
class FleetConfig:
    """Simulator shape — mirrors ServeConfig's host-side knobs (the sim
    has no device-side ones)."""
    num_slots: int = 64
    page_size: int = 16
    max_len: int = 512
    prefill_chunk: int = 64
    num_pages: int = 0            # 0 = full reservation for every slot
    prefix_cache: bool = False
    prefix_cache_pages: int = 0   # 0 = unbounded (insert-budget off)
    preempt: bool = False
    quotas: Dict[str, TenantQuota] = dataclasses.field(default_factory=dict)
    #: run check_invariants() every N sim steps (plus once at the end);
    #: 0 disables the periodic sweep (the final check still runs)
    invariant_every: int = 997
    #: serve-event/span sampling: 1-in-N requests reach the RunLog/
    #: tracer; 0 = read HETU_TPU_RUNLOG_SERVE_SAMPLE (default 1 = all)
    sample: int = 0
    # -- the fault-tolerance layer (same knobs as ServeConfig)
    #: replica-death requeues allowed per request before it terminates
    #: ``retry_exhausted`` (chaos engine_kill; 0 = no retries)
    retry_budget: int = 0
    #: enforce SLOClass.deadline_s (expired requests terminate
    #: ``deadline_exceeded``)
    deadline: bool = False
    #: sustained-pressure shedding of the lowest-priority queued band
    brownout: bool = False
    brownout_page_high: float = 0.95
    brownout_queue_min: int = 1
    brownout_streak: int = 4
    # -- disaggregated prefill/decode tiers (serving/disagg.py on the
    #    analytic clock: prompts prefill on a separate tier that runs
    #    CONCURRENTLY with decode, and finished KV ships over an acked
    #    at-least-once wire driven by the chaos shipment_* kinds)
    disagg: bool = False
    #: prefill-tier width (concurrent prefills); 0 = num_slots
    prefill_slots: int = 0
    #: modeled one-way wire latency per shipment delivery
    ship_latency_s: float = 500e-6
    #: virtual seconds before an un-acked shipment retransmits (and,
    #: past ``ship_retry`` resends, the request re-prefills under the
    #: retry budget)
    ship_timeout_s: float = 0.05
    ship_retry: int = 2
    #: dead prefill tier: True (default) degrades to colocated chunked
    #: prefill on the decode tier; False is the naive model — arrivals
    #: wait out the outage (the comparison baseline)
    fallback: bool = True


class _Bucket:
    """Exact per-(tenant, class) accumulator — every request lands here
    regardless of RunLog sampling."""

    __slots__ = ("requests", "tokens", "slo_ok", "goodput_tokens",
                 "preemptions", "retries", "faults", "stalls", "ttft",
                 "e2e", "queue_wait", "costs")

    def __init__(self):
        self.requests = 0
        self.tokens = 0
        self.slo_ok = 0
        self.goodput_tokens = 0
        self.preemptions = 0
        self.retries = 0
        self.faults: Dict[str, int] = {}
        self.stalls: Dict[str, int] = {}
        # seeded reservoirs: deterministic percentiles at any count
        self.ttft = Histogram()
        self.e2e = Histogram()
        self.queue_wait = Histogram()
        self.costs = {k: 0.0 for k in COST_FIELDS}


def _merge_hist(dst: Histogram, src: Histogram):
    """Fold `src`'s reservoir + exact running stats into `dst` (used to
    roll per-(tenant, class) buckets up to per-tenant / per-class rows).
    The merged reservoir is approximate but deterministic; count/total/
    min/max stay exact."""
    for v in src._sample:
        dst.observe(v)
    # the observes above counted only the reservoir; correct the running
    # stats to src's exact values
    dst.count += src.count - len(src._sample)
    dst.total += src.total - sum(src._sample)
    if src.vmin is not None:
        dst.vmin = (src.vmin if dst.vmin is None
                    else min(dst.vmin, src.vmin))
    if src.vmax is not None:
        dst.vmax = (src.vmax if dst.vmax is None
                    else max(dst.vmax, src.vmax))


def _hist_summary(h: Histogram) -> Optional[Dict[str, Any]]:
    if not h.count:
        return None
    return {"mean": h.total / h.count, "p50": h.percentile(50),
            "p95": h.percentile(95), "p99": h.percentile(99),
            "max": h.vmax}


class FleetSimulator:
    """Discrete-event replay of a request trace through the production
    scheduler/page-pool/prefix-cache/preemption machinery.

    One instance = one run: construct, `run(requests)`, read the
    returned report (or `report()` again later).  Wire a RunLog to get
    the sampled serve/span event stream every serving tool understands;
    wire a chaos `FaultPlan` to inflate service times through its
    ``slow_worker`` windows (`step_delay`)."""

    def __init__(self, service: ServiceModel, *,
                 config: Optional[FleetConfig] = None,
                 cost_model: Optional[CostModel] = None,
                 run_log=None, registry=None, fault_plan=None):
        cfg = config or FleetConfig()
        self.cfg = cfg
        self.service = service
        self.run_log = run_log
        self.registry = registry
        self.fault_plan = fault_plan
        pages = cfg.num_pages or cfg.num_slots * (cfg.max_len
                                                  // cfg.page_size)
        # the REAL pool/scheduler/cache — host-side only (no device
        # arrays): policy decisions come from the production code path
        self.pool = PagePool(num_layers=1, num_pages=pages,
                             page_size=cfg.page_size, num_kv_heads=1,
                             head_dim=1, device_arrays=False)
        self.prefix_cache = None
        if cfg.prefix_cache:
            from hetu_tpu.serving.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(
                self.pool, max_pages=cfg.prefix_cache_pages)
        self.sched = Scheduler(num_slots=cfg.num_slots, pool=self.pool,
                               max_len=cfg.max_len,
                               prefix_cache=self.prefix_cache,
                               quotas=cfg.quotas,
                               retry_budget=cfg.retry_budget)
        self.ledger = (CostLedger(cost_model)
                       if cost_model is not None else None)
        if cfg.sample:
            self.sample = cfg.sample
        else:
            from hetu_tpu.utils import flags
            self.sample = max(
                1, flags.int_flag("HETU_TPU_RUNLOG_SERVE_SAMPLE"))
        # the real flight recorder over the SAMPLED requests (keep=True:
        # the end-of-run reconciliation sweep reads the kept traces);
        # stamped with its hop identity so the kept spans stitch
        self.tracer = RequestTracer(run_log=run_log, keep=True,
                                    max_kept=1 << 20, tier="decode")
        #: a SECOND flight recorder for the prefill tier (cfg.disagg):
        #: a rid's prefill incarnations must be separate HOPS in the
        #: stitched fleet trace, not collide with its decode trace.
        #: In-memory only — the end-of-run stitch reads it directly;
        #: the runlog keeps its established record stream.
        self.pf_tracer = (RequestTracer(keep=True, max_kept=1 << 20,
                                        tier="prefill", replica=0)
                          if cfg.disagg else None)
        #: the frontend/shipment EDGE events (dispatch/ship/retry/
        #: admit), captured in memory so `FleetTrace.stitch` can build
        #: the causal DAG without a runlog round-trip
        self._events: List[Dict[str, Any]] = []
        # ---- exact accounting (per request, sampling-independent)
        self._buckets: Dict[tuple, _Bucket] = {}
        self._first_reason: Dict[int, str] = {}
        self._enter_seq: Dict[int, int] = {}
        self._preempt_counts: Dict[int, int] = {}
        #: sticky requeue attribution per rid (preempted/replica_lost) —
        #: the reason the next admission's queued span carries
        self._requeue_reason: Dict[int, str] = {}
        self._stall_seq = 0
        self._stall_reason = "none"
        self.stall_steps: Dict[str, int] = {}
        self.quota_peaks: Dict[str, Dict[str, int]] = {}
        self.submitted = 0
        self.completed = 0
        self.tokens_out = 0
        self.prefill_chunks = 0
        self.preemptions = 0
        # fault-layer accounting (chaos engine_kill / deadlines /
        # brownout): `faulted` counts every fault termination — the
        # run-loop progress check includes it, so a sweep that only
        # expires requests still counts as progress
        self.failovers = 0
        self.replica_requeues = 0
        self.retry_exhausted = 0
        self.expired = 0
        self.shed = 0
        self.faulted = 0
        self._brownout_hot = 0
        #: tokens emitted (counted in tokens_out) whose work was later
        #: discarded — a preemption or a replica-death requeue threw the
        #: partial stream away and the replay re-emits it.  The exact
        #: reconciliation: tokens_out == sum(bucket tokens) + this.
        self.tokens_discarded = 0
        # ---- disaggregated prefill tier (cfg.disagg)
        self._pf_slots = cfg.prefill_slots or cfg.num_slots
        self._pf_arrivals: List[Request] = []
        self._pf_queue: collections.deque = collections.deque()
        self._pf_live: Dict[int, list] = {}   # rid -> [req, chunks, att]
        self._pf_awaiting: Dict[int, dict] = {}
        #: rids with a FINITE deadline (shipped, or lost to a tier
        #: kill) — the per-step timeout scan walks only these, not the
        #: whole awaiting backlog (O(queue) x O(steps) at fleet scale);
        #: a dict, not a set, so iteration order is insertion order and
        #: the determinism golden holds
        self._pf_armed: Dict[int, None] = {}
        self._pf_wire: List[dict] = []        # in-flight shipments
        self._pf_finished: set = set()
        self._pf_seq = 0
        self._pf_degraded = False
        self._pf_degraded_t0 = 0.0
        self.tier_prefill_chunks = 0
        self.ship_sent = 0
        self.ship_dropped = 0
        self.ship_duped = 0
        self.ship_delayed = 0
        self.ship_dedups = 0
        self.ship_resends = 0
        self.adoptions = 0
        self.reprefills = 0
        self.colocated = 0
        self.prefill_kills = 0
        self.degraded_entries = 0
        self.degraded_steps = 0
        self.degraded_s = 0.0
        self.steps = 0
        self.invariant_checks = 0
        self._start = 0.0
        self._end = 0.0

    # ------------------------------------------------------------ utils
    def _sampled(self, rid: int) -> bool:
        return rid_sampled(rid, self.sample)

    def _weight_fields(self) -> Dict[str, Any]:
        return {"sample_weight": self.sample} if self.sample > 1 else {}

    def _bucket(self, tenant: str, cls: str) -> _Bucket:
        b = self._buckets.get((tenant, cls))
        if b is None:
            b = self._buckets[(tenant, cls)] = _Bucket()
        return b

    def _log(self, **fields):
        if fields.get("event") in _EDGE_EVENTS:
            # the stitcher's causal-edge vocabulary rides the same
            # serve events the runlog gets — captured unconditionally
            # so runlog-less sims still stitch
            self._events.append(dict(fields))
        if self.run_log is not None:
            self.run_log.log("serve", **fields)

    # -------------------------------------------------------- lifecycle
    def _submit(self, req: Request):
        if self.cfg.disagg:
            # two-tier intake: the request heads to the prefill tier
            # (or the colocation fallback) at the next sim step —
            # submission accounting and the queued span open here
            self._pf_arrivals.append(req)
        else:
            self.sched.submit(req)
        self.submitted += 1
        self._enter_seq[req.rid] = self._stall_seq
        if self._sampled(req.rid):
            self.tracer.on_submit(req)

    def _queued_reason(self, rid: int) -> str:
        """The stall-attribution reason the tracer would have stamped on
        this request — computed lazily at admission (O(1) per request)
        instead of walking the whole queue every stalled step: a stall
        event is global to the FIFO queue, so 'the last stall observed
        while this request was queued' is exactly 'the last global stall
        if any occurred after it entered'.  A requeue reason
        (``preempted`` / ``replica_lost``) is sticky — latest requeue
        wins — matching RequestTracer.on_stall."""
        requeue = self._requeue_reason.get(rid)
        if requeue is not None:
            return requeue
        if self._stall_seq > self._enter_seq.get(rid, self._stall_seq):
            return self._stall_reason
        return "none"

    def _on_admit(self, slot_idx: int, st, now: float):
        req = st.request
        rid = req.rid
        reason = self._queued_reason(rid)
        # stall attribution reported per request = the FIRST admission's
        # wait (what collect_traces' RequestTrace.stall_reason reads)
        self._first_reason.setdefault(rid, reason)
        self._enter_seq.pop(rid, None)
        st.prefilling = True
        if self.ledger is not None:
            self.ledger.on_admit(rid, len(st.pages), now)
        t = req.tenant
        peaks = self.quota_peaks.get(t)
        if peaks is None:
            peaks = self.quota_peaks[t] = {"slots": 0, "pages": 0}
        peaks["slots"] = max(peaks["slots"],
                             self.sched.tenant_slots.get(t, 0))
        peaks["pages"] = max(peaks["pages"],
                             self.sched.tenant_pages.get(t, 0))
        if self._sampled(rid):
            if reason != "none":
                self.tracer.on_stall([rid], reason)
            self.tracer.on_admit(req, slot_idx, now,
                                 shared_tokens=st.shared_tokens)

    def _try_preempt(self, now: float) -> bool:
        head = self.sched.queue[0]
        victim = self.sched.preempt_victim(head.slo.priority)
        if victim is None:
            return False
        st = self.sched.slots[victim]
        req = st.request
        rid = req.rid
        self._preempt_counts[rid] = self._preempt_counts.get(rid, 0) + 1
        self.preemptions += 1
        if self.ledger is not None:
            self.ledger.on_preempt(rid, now, ctx_start=st.shared_tokens,
                                   tokens_cached=st.pos)
        tokens_discarded = len(st.generated)
        self.tokens_discarded += tokens_discarded
        self.sched.preempt(victim)
        self._enter_seq[rid] = self._stall_seq
        self._requeue_reason[rid] = "preempted"
        b = self._bucket(req.tenant, req.slo.name)
        b.preemptions += 1
        if self._sampled(rid):
            self.tracer.on_preempt(req, victim, now, by=head.rid)
            self._log(event="preempt", req=rid, slot=victim,
                      by=head.rid, by_class=head.slo.name,
                      slo_class=req.slo.name, tenant=req.tenant, now=now,
                      tokens_discarded=tokens_discarded,
                      queue_depth=self.sched.queue_depth,
                      **self._weight_fields())
        return True

    def _advance_prefill(self, slot_idx: int, st, now: float) -> float:
        """One (padded) prefill chunk; on the final chunk the first
        token is emitted — same per-step contract as the engine."""
        req = st.request
        plen = req.prompt_len
        C = self.cfg.prefill_chunk
        base = st.shared_tokens
        s = base + st.chunks_done * C
        dt = self.service.prefill_chunk_s(C, s)
        st.chunks_done += 1
        st.stats.prefill_chunks += 1
        self.prefill_chunks += 1
        padded = base + math.ceil((plen - base) / C) * C
        if s + C < padded:
            if self._sampled(req.rid):
                self.tracer.on_chunk(req, now, st.chunks_done)
            return dt
        # final chunk: prompt fully cached — index it, emit TTFT token
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, st.pages, now)
        st.prefilling = False
        st.pos = plen
        st.generated.append(0)     # modeled token (no logits exist)
        self.tokens_out += 1
        st.stats.first_token_t = now
        rid = req.rid
        if self._sampled(rid):
            self.tracer.on_first_token(req, slot_idx, now,
                                       chunk=st.chunks_done)
            self._log(event="admit", req=rid, slot=slot_idx,
                      prompt_len=plen, chunks=st.stats.prefill_chunks,
                      ttft_s=st.stats.ttft_s,
                      queue_wait_s=st.stats.queue_wait_s, now=now,
                      slo_class=req.slo.name, tenant=req.tenant,
                      shared_tokens=st.shared_tokens,
                      queue_depth=self.sched.queue_depth,
                      page_util=self.pool.utilization,
                      **self._weight_fields())
        if len(st.generated) >= req.max_new_tokens:
            self._finish(slot_idx, st, now)
        return dt

    def _finish(self, slot_idx: int, st, now: float):
        req = st.request
        rid = req.rid
        st.stats.done_t = now
        tokens = len(st.generated)
        self.sched.release(slot_idx)
        if self.cfg.disagg:
            self._pf_finished.add(rid)
            self._pf_awaiting.pop(rid, None)
            self.sched.ship_forget(rid)
        st.stats.preemptions = self._preempt_counts.pop(rid, 0)
        st.stats.retries = self.sched.retries.pop(rid, 0)
        self._requeue_reason.pop(rid, None)
        reason_first = self._first_reason.pop(rid, "none")
        cost = None
        if self.ledger is not None:
            cost = self.ledger.finish(
                rid, now, prompt_len=req.prompt_len,
                shared_tokens=st.stats.shared_prefix_tokens,
                tokens_out=tokens)
        ttft = st.stats.ttft_s
        e2e = st.stats.e2e_s
        gap = ((e2e - ttft) / (tokens - 1)
               if (tokens > 1 and e2e is not None and ttft is not None)
               else 0.0)
        slo = req.slo
        ttft_ok = slo.ttft_s is None or (ttft is not None
                                         and ttft <= slo.ttft_s)
        gap_ok = slo.token_gap_s is None or gap <= slo.token_gap_s
        ok = ttft_ok and gap_ok
        b = self._bucket(req.tenant, slo.name)
        b.requests += 1
        b.tokens += tokens
        b.retries += st.stats.retries
        b.stalls[reason_first] = b.stalls.get(reason_first, 0) + 1
        if ok:
            b.slo_ok += 1
            b.goodput_tokens += tokens
        if ttft is not None:
            b.ttft.observe(ttft)
        if e2e is not None:
            b.e2e.observe(e2e)
        if st.stats.queue_wait_s is not None:
            b.queue_wait.observe(st.stats.queue_wait_s)
        if cost is not None:
            for k in COST_FIELDS:
                b.costs[k] += cost[k]
        self.completed += 1
        if self._sampled(rid):
            self.tracer.on_finish(req, slot_idx, "length", now,
                                  tokens=tokens, e2e_s=e2e)
            self._log(event="done", req=rid, slot=slot_idx,
                      reason="length", tokens=tokens, ttft_s=ttft,
                      e2e_s=e2e,
                      tokens_per_s=(tokens / e2e if e2e else None),
                      now=now, slo_class=slo.name, tenant=req.tenant,
                      slo_ttft_s=slo.ttft_s,
                      slo_token_gap_s=slo.token_gap_s,
                      shared_prefix_tokens=st.stats.shared_prefix_tokens,
                      prompt_len=req.prompt_len,
                      preemptions=st.stats.preemptions,
                      queue_depth=self.sched.queue_depth,
                      slot_occupancy=self.sched.occupancy,
                      page_util=self.pool.utilization,
                      **({"retries": st.stats.retries}
                         if st.stats.retries else {}),
                      **dict(cost or {}), **self._weight_fields())

    # ----------------------------------------------------------- faults
    def _terminate_fault(self, req, st, now: float, *, reason: str,
                         event: str, slot: Optional[int] = None):
        """Terminal fault accounting shared by retry exhaustion,
        deadline expiry and brownout shedding: the request counts in
        its bucket's ``requests`` with ``slo_ok`` unset — attainment
        degrades by construction — and its latencies stay out of the
        reservoirs (they summarize finished requests)."""
        rid = req.rid
        tokens = len(st.generated) if st is not None else 0
        cost = None
        if self.ledger is not None and st is not None:
            st.stats.done_t = now
            cost = self.ledger.finish(
                rid, now, prompt_len=req.prompt_len,
                shared_tokens=st.stats.shared_prefix_tokens,
                tokens_out=tokens)
        preempts = self._preempt_counts.pop(rid, 0)
        retries = self.sched.retries.pop(rid, 0)
        self._requeue_reason.pop(rid, None)
        self._first_reason.pop(rid, None)
        self._enter_seq.pop(rid, None)
        if self.cfg.disagg:
            self._pf_finished.add(rid)
            self._pf_awaiting.pop(rid, None)
            self.sched.ship_forget(rid)
        b = self._bucket(req.tenant, req.slo.name)
        b.requests += 1
        b.tokens += tokens
        b.retries += retries
        b.faults[reason] = b.faults.get(reason, 0) + 1
        self.faulted += 1
        if self._sampled(rid):
            self._log(event=event, req=rid, reason=reason,
                      tokens=tokens, e2e_s=now - req.arrival_t, now=now,
                      slo_class=req.slo.name, tenant=req.tenant,
                      retries=retries, preemptions=preempts,
                      queue_depth=self.sched.queue_depth,
                      **({"slot": slot} if slot is not None else {}),
                      **dict(cost or {}), **self._weight_fields())

    def _fail_over(self, now: float):
        """The replica serving every live slot died (chaos
        ``engine_kill``): requeue each in-flight request under its
        retry budget — the deterministic replay regenerates the same
        tokens — or terminate it ``retry_exhausted`` past the budget.
        Mirrors ServeEngine.fail_over on the analytic clock."""
        sched = self.sched
        self.failovers += 1
        requeued: List[int] = []
        exhausted: List[int] = []
        for i in list(sched.active_slots()):
            st = sched.slots[i]
            req = st.request
            rid = req.rid
            if sched.retries.get(rid, 0) < self.cfg.retry_budget:
                if self.ledger is not None:
                    self.ledger.on_preempt(rid, now,
                                           ctx_start=st.shared_tokens,
                                           tokens_cached=st.pos)
                tokens_discarded = len(st.generated)
                self.tokens_discarded += tokens_discarded
                sched.requeue_lost(i)
                self._enter_seq[rid] = self._stall_seq
                self._requeue_reason[rid] = "replica_lost"
                self.replica_requeues += 1
                requeued.append(rid)
                if self._sampled(rid):
                    self.tracer.on_replica_lost(req, i, now)
                    self._log(event="retry", req=rid, slot=i,
                              attempt=sched.retries[rid] + 1,
                              tokens_discarded=tokens_discarded,
                              slo_class=req.slo.name, tenant=req.tenant,
                              now=now,
                              queue_depth=sched.queue_depth,
                              **self._weight_fields())
            else:
                tokens = len(st.generated)
                if self._sampled(rid):
                    self.tracer.on_finish(req, i, "retry_exhausted",
                                          now, tokens=tokens,
                                          e2e_s=now - req.arrival_t,
                                          evicted=True)
                sched.release(i)
                self.retry_exhausted += 1
                exhausted.append(rid)
                self._terminate_fault(req, st, now,
                                      reason="retry_exhausted",
                                      event="evict", slot=i)
        self._log(event="failover", requeued=len(requeued),
                  exhausted=len(exhausted), now=now,
                  queue_depth=sched.queue_depth)

    def _expire_deadlines(self, now: float):
        """Terminate every request past its SLO deadline (queued and
        live) as ``deadline_exceeded`` — same sweep order as
        ServeEngine._expire_deadlines."""
        sched = self.sched
        for req in [r for r in sched.queue
                    if r.slo.deadline_s is not None
                    and now - r.arrival_t > r.slo.deadline_s]:
            if not sched.drop_queued(req):
                continue
            if self._sampled(req.rid):
                self.tracer.on_expire(req, now,
                                      e2e_s=now - req.arrival_t)
            self.expired += 1
            self._terminate_fault(req, None, now,
                                  reason="deadline_exceeded",
                                  event="expired")
        for i in list(sched.active_slots()):
            st = sched.slots[i]
            req = st.request
            d = req.slo.deadline_s
            if d is None or now - req.arrival_t <= d:
                continue
            if self._sampled(req.rid):
                self.tracer.on_expire(req, now,
                                      tokens=len(st.generated),
                                      e2e_s=now - req.arrival_t)
            sched.release(i)
            self.expired += 1
            self._terminate_fault(req, st, now,
                                  reason="deadline_exceeded",
                                  event="expired", slot=i)

    def _maybe_brownout(self, now: float):
        """Sustained page+queue pressure sheds the lowest-priority
        queued band (same policy shape as ServeEngine._maybe_brownout:
        ``brownout_streak`` consecutive hot steps arm it, one shed per
        trigger, streak resets after)."""
        cfg = self.cfg
        sched = self.sched
        hot = (self.pool.utilization >= cfg.brownout_page_high
               and sched.queue_depth >= cfg.brownout_queue_min)
        if not hot:
            self._brownout_hot = 0
            return
        self._brownout_hot += 1
        if self._brownout_hot < cfg.brownout_streak:
            return
        self._brownout_hot = 0
        min_pri = min(r.slo.priority for r in sched.queue)
        for req in [r for r in sched.queue
                    if r.slo.priority == min_pri]:
            if not sched.drop_queued(req):
                continue
            if self._sampled(req.rid):
                self.tracer.on_shed(req, now)
            self.shed += 1
            self._terminate_fault(req, None, now,
                                  reason="brownout_shed", event="shed")

    # ------------------------------------------- disaggregated tier
    def _pf_route(self, req: Request, attempt: int, now: float):
        """Queue `req` on the prefill tier.  The shipment deadline is
        armed only once a shipment exists (or a tier kill loses the
        prefill) — a healthy tier's queue wait is not a wire fault."""
        self._pf_queue.append((req, attempt))
        self._pf_awaiting[req.rid] = {
            "req": req, "attempt": attempt, "deadline": math.inf,
            "shipped": False, "seq": None, "resends": 0}
        if self.pf_tracer is not None and self._sampled(req.rid):
            # open the prefill-tier HOP at routing time (not arrival:
            # the decode hop's queued span already covers the wait)
            self.pf_tracer.on_submit(req, at=now)
            self._log(event="dispatch", req=req.rid, tier="prefill",
                      now=now,
                      **({"attempt": attempt} if attempt else {}))

    def _fallback_colocate(self, req: Request, now: float):
        """Colocated chunked prefill on the decode tier (graceful
        degradation): the request enters the REAL scheduler queue with
        the sticky ``prefill_tier_down`` stall stamp, and the normal
        admission path prefills it on the decode clock."""
        self._pf_awaiting.pop(req.rid, None)
        self.sched.submit(req)
        self.colocated += 1
        self._enter_seq.setdefault(req.rid, self._stall_seq)
        self._requeue_reason[req.rid] = "prefill_tier_down"
        if self._sampled(req.rid):
            self._log(event="dispatch", req=req.rid, tier="decode",
                      fallback=True, now=now)

    def _kill_prefill_tier(self, now: float):
        """Chaos ``prefill_kill``: every queued and in-flight prefill
        on the tier is lost; their pending entries' timeouts fire THIS
        step, so the recovery path (re-prefill under the retry budget,
        or colocation while degraded) runs immediately."""
        lost = ([(rid, ent[0]) for rid, ent in self._pf_live.items()]
                + [(r.rid, r) for r, _ in self._pf_queue])
        self._pf_live.clear()
        self._pf_queue.clear()
        self.prefill_kills += 1
        for rid, req in lost:
            p = self._pf_awaiting.get(rid)
            if p is not None and not p["shipped"]:
                p["deadline"] = now
                self._pf_armed[rid] = None
            self._pf_hop_evict(req, now, reason="prefill_kill")

    def _pf_hop_evict(self, req: Request, now: float, *, reason: str):
        """Close an OPEN prefill-tier hop ``evicted`` (a tier kill or a
        re-prefill turnaround): the tracer tiles whatever phase was
        open, so the discarded work still stitches and counts in the
        fleet-wide span ledger.  A no-op when the hop already closed
        (shipped) or the rid is unsampled."""
        tr = self.pf_tracer
        if tr is None or not self._sampled(req.rid) \
                or not tr.is_open(req.rid):
            return
        tr.on_finish(req, None, reason, now, tokens=0, evicted=True)

    def _pf_hop_ship(self, req: Request, now: float):
        """Close the prefill-tier hop at the ship: the final chunk
        boundary (the hop's ``last`` prefill span) plus the zero-token
        ``shipped`` terminal — the stitcher's ship edge source."""
        tr = self.pf_tracer
        if tr is None or not self._sampled(req.rid) \
                or not tr.is_open(req.rid):
            return
        C = self.cfg.prefill_chunk
        tr.on_first_token(req, None, now,
                          chunk=math.ceil(req.prompt_len / C))
        tr.on_finish(req, None, "shipped", now, tokens=0)

    def _pf_send(self, rid: int, p: dict, now: float):
        """Put (or re-put) rid's shipment on the modeled wire, driving
        the chaos shipment_* kinds exactly like the real channel."""
        self.ship_sent += 1
        if self._sampled(rid):
            self._log(event="ship", req=rid, seq=p["seq"],
                      attempt=p["attempt"], resend=p["resends"],
                      now=now, **self._weight_fields())
        plan = self.fault_plan
        spec = plan.shipment_fault("ship") if plan is not None else None
        due = now + self.cfg.ship_latency_s
        if spec is not None and spec.kind == "shipment_drop":
            self.ship_dropped += 1
            return                  # the timeout machinery recovers it
        if spec is not None and spec.kind == "shipment_delay":
            due += spec.delay_s
            self.ship_delayed += 1
        entry = {"due": due, "rid": rid, "seq": p["seq"],
                 "attempt": p["attempt"]}
        self._pf_wire.append(entry)
        if spec is not None and spec.kind == "shipment_dup":
            self._pf_wire.append(dict(entry))
            self.ship_duped += 1

    def _pf_reprefill(self, rid: int, p: dict, now: float):
        """Shipment unrecoverable (resends exhausted, or the tier died
        holding the prefill): re-prefill under the decode retry budget
        — the same `scheduler.retries` ledger replica failover bills —
        or terminate ``retry_exhausted`` past it."""
        req = p["req"]
        self._pf_hop_evict(req, now, reason="reprefill")
        retries = self.sched.retries.get(rid, 0)
        if retries >= self.cfg.retry_budget:
            self._pf_awaiting.pop(rid, None)
            self._pf_finished.add(rid)
            self.retry_exhausted += 1
            if self._sampled(rid):
                self.tracer.on_finish(req, -1, "retry_exhausted", now,
                                      tokens=0,
                                      e2e_s=now - req.arrival_t,
                                      evicted=True)
            self._terminate_fault(req, None, now,
                                  reason="retry_exhausted",
                                  event="evict")
            return
        self.sched.retries[rid] = retries + 1
        self.reprefills += 1
        self._requeue_reason[rid] = "shipment_wait"
        if self._sampled(rid):
            self._log(event="retry", req=rid, attempt=retries + 1,
                      ship=True, tokens_discarded=0, now=now,
                      slo_class=req.slo.name, tenant=req.tenant,
                      **self._weight_fields())
        if self._pf_degraded and self.cfg.fallback:
            self._pf_awaiting.pop(rid, None)
            self._fallback_colocate(req, now)
        else:
            self._pf_awaiting.pop(rid, None)
            self._pf_route(req, p["attempt"] + 1, now)

    def _pf_adopt(self, rid: int, req: Request, now: float) -> bool:
        """Deliver one shipment: the dedupe gate, then direct admission
        and the first-token emission — the sim's `adopt_prefilled` on
        the analytic clock.  False = no decode capacity; the caller
        requeues the delivery."""
        sched = self.sched
        adm = sched.admit_direct(req, now)
        if adm is None:
            reason = sched.last_stall or "none"
            self._requeue_reason.setdefault(rid, reason)
            return False
        slot_idx, st = adm
        reason = self._queued_reason(rid)
        self._first_reason.setdefault(rid, reason)
        self._enter_seq.pop(rid, None)
        self._requeue_reason.pop(rid, None)
        if self.ledger is not None:
            self.ledger.on_admit(rid, len(st.pages), now)
        t = req.tenant
        peaks = self.quota_peaks.get(t)
        if peaks is None:
            peaks = self.quota_peaks[t] = {"slots": 0, "pages": 0}
        peaks["slots"] = max(peaks["slots"],
                             sched.tenant_slots.get(t, 0))
        peaks["pages"] = max(peaks["pages"],
                             sched.tenant_pages.get(t, 0))
        st.prefilling = False
        st.pos = req.prompt_len
        st.generated.append(0)      # the shipped first token (modeled)
        self.tokens_out += 1
        self.adoptions += 1
        st.stats.first_token_t = now
        if self._sampled(rid):
            if reason != "none":
                self.tracer.on_stall([rid], reason)
            self.tracer.on_admit(req, slot_idx, now, shared_tokens=0)
            self.tracer.on_first_token(req, slot_idx, now, chunk=0)
            self._log(event="admit", req=rid, slot=slot_idx,
                      prompt_len=req.prompt_len, chunks=0,
                      ttft_s=st.stats.ttft_s,
                      queue_wait_s=st.stats.queue_wait_s, now=now,
                      slo_class=req.slo.name, tenant=req.tenant,
                      shared_tokens=0, disagg=True,
                      queue_depth=sched.queue_depth,
                      page_util=self.pool.utilization,
                      **self._weight_fields())
        if len(st.generated) >= req.max_new_tokens:
            self._finish(slot_idx, st, now)
        return True

    def _disagg_step(self, now: float, step_idx: int) -> float:
        """One prefill-tier step (runs CONCURRENTLY with decode: the
        caller takes max(tier dt, decode dt)): chaos, degraded-state
        transitions, arrival routing, one chunk per live prefill, wire
        deliveries with the dedupe gate, ack/timeout processing."""
        plan = self.fault_plan
        sched = self.sched
        pf_down = False
        if plan is not None:
            if plan.should_kill_prefill(step_idx):
                self._kill_prefill_tier(now)
            pf_down = plan.prefill_down(step_idx)
        if pf_down and not self._pf_degraded:
            self._pf_degraded = True
            self._pf_degraded_t0 = now
            self.degraded_entries += 1
            self._log(event="degraded", state="enter", now=now,
                      queue_depth=sched.queue_depth)
        elif not pf_down and self._pf_degraded:
            self._pf_degraded = False
            span = now - self._pf_degraded_t0
            self.degraded_s += span
            self._log(event="degraded", state="exit", now=now,
                      degraded_s=span)
        if self._pf_degraded:
            self.degraded_steps += 1
        # route arrivals: degraded+fallback -> colocate; degraded
        # without fallback (the naive baseline) -> wait out the outage
        if self._pf_arrivals:
            if not self._pf_degraded:
                for req in self._pf_arrivals:
                    self._pf_route(req, 0, now)
                self._pf_arrivals.clear()
            elif self.cfg.fallback:
                for req in self._pf_arrivals:
                    self._fallback_colocate(req, now)
                self._pf_arrivals.clear()
        dt = 0.0
        if not pf_down:
            while len(self._pf_live) < self._pf_slots \
                    and self._pf_queue:
                req, attempt = self._pf_queue.popleft()
                if req.rid in self._pf_awaiting:
                    self._pf_live[req.rid] = [req, 0, attempt]
                    if self.pf_tracer is not None \
                            and self._sampled(req.rid):
                        self.pf_tracer.on_admit(req, None, now)
            for rid in list(self._pf_live):
                ent = self._pf_live[rid]
                req, done, attempt = ent
                C = self.cfg.prefill_chunk
                s = done * C
                dt += self.service.prefill_chunk_s(C, s)
                ent[1] = done + 1
                self.tier_prefill_chunks += 1
                if s + C < math.ceil(req.prompt_len / C) * C:
                    continue
                del self._pf_live[rid]
                p = self._pf_awaiting.get(rid)
                if p is None:
                    # terminated while prefilling: the hop's work is
                    # discarded but must still tile and stitch
                    self._pf_hop_evict(req, now, reason="dropped")
                    continue
                self._pf_hop_ship(req, now)
                self._pf_seq += 1
                p["shipped"] = True
                p["seq"] = self._pf_seq
                p["deadline"] = now + self.cfg.ship_timeout_s
                self._pf_armed[rid] = None
                self._pf_send(rid, p, now)
        # wire deliveries due by now, in send order
        due = [e for e in self._pf_wire if e["due"] <= now]
        if due:
            self._pf_wire = [e for e in self._pf_wire
                             if e["due"] > now]
            for e in due:
                rid = e["rid"]
                if rid in self._pf_finished \
                        or rid not in self._pf_awaiting:
                    self.ship_dedups += 1   # late duplicate
                    continue
                if not sched.apply_shipment(rid, e["seq"]):
                    self.ship_dedups += 1
                    continue
                p = self._pf_awaiting[rid]
                if self._pf_adopt(rid, p["req"], now):
                    self._pf_awaiting.pop(rid, None)   # implicit ack
                else:
                    # no decode capacity: un-burn the seq, redeliver
                    # next step, hold the sender timer
                    sched.unapply_shipment(rid, e["seq"])
                    e["due"] = now + self.service.step_overhead_s
                    self._pf_wire.append(e)
                    p["deadline"] = now + self.cfg.ship_timeout_s
        # timeouts: resend up to the budget, then re-prefill — walking
        # only the ARMED entries; the unshipped backlog has deadline=inf
        # and never needs the scan
        for rid in list(self._pf_armed):
            p = self._pf_awaiting.get(rid)
            if p is None or p["deadline"] == math.inf:
                del self._pf_armed[rid]     # resolved or re-queued
                continue
            if now < p["deadline"]:
                continue
            if p["shipped"] and p["resends"] < self.cfg.ship_retry:
                p["resends"] += 1
                self.ship_resends += 1
                p["deadline"] = now + self.cfg.ship_timeout_s
                self._pf_send(rid, p, now)
            else:
                self._pf_reprefill(rid, p, now)
        if dt == 0.0 and (self._pf_wire or self._pf_awaiting
                          or self._pf_arrivals or self._pf_queue):
            # the tier is waiting on wire/timeout events: virtual time
            # must advance or the deliveries never come due
            dt = self.service.step_overhead_s
        return dt

    # ------------------------------------------------------------- step
    def _step(self, now: float, step_idx: int) -> float:
        """One engine-step equivalent at virtual time `now`; returns the
        modeled step duration."""
        sched = self.sched
        plan = self.fault_plan
        down = False
        if plan is not None:
            if plan.should_kill_engine(step_idx):
                self._fail_over(now)
            down = plan.engine_down(step_idx)
        if self.cfg.deadline:
            self._expire_deadlines(now)
        pf_dt = 0.0
        if self.cfg.disagg:
            # the prefill tier steps CONCURRENTLY with decode:
            # adoption/colocation it performs is visible to this step's
            # admission loop, and the step consumes max(tier, decode)
            pf_dt = self._disagg_step(now, step_idx)
        if not down:
            while True:
                adm = sched.admit_next(now)
                if adm is None:
                    if (self.cfg.preempt and sched.queue
                            and self._try_preempt(now)):
                        continue
                    break
                slot_idx, st = adm
                self._on_admit(slot_idx, st, now)
        if not down and sched.queue:
            reason = sched.last_stall or "none"
            self._stall_seq += 1
            self._stall_reason = reason
            self.stall_steps[reason] = self.stall_steps.get(reason, 0) + 1
        dt = 0.0
        finished0 = self.completed
        for i in sched.active_slots():
            st = sched.slots[i]
            if st is not None and st.prefilling:
                dt += self._advance_prefill(i, st, now)
        decoding = [i for i in sched.active_slots()
                    if not sched.slots[i].prefilling]
        if decoding:
            kv_tokens = sum(sched.slots[i].pos for i in decoding)
            dt += self.service.decode_step_s(len(decoding), kv_tokens)
            for i in decoding:
                st = sched.slots[i]
                st.generated.append(0)
                st.pos += 1
                self.tokens_out += 1
                if self._sampled(st.request.rid):
                    self.tracer.on_token(st.request, now)
                if len(st.generated) >= st.request.max_new_tokens:
                    self._finish(i, st, now)
        if self.completed > finished0:
            survivors = [sched.slots[i].request.rid
                         for i in sched.active_slots()
                         if not sched.slots[i].prefilling
                         and self._sampled(sched.slots[i].request.rid)]
            if survivors:
                self.tracer.on_split(survivors, now, "evict")
        if self.cfg.brownout:
            self._maybe_brownout(now)
        dt = max(dt, pf_dt)     # disagg tiers overlap in wall-clock
        if plan is not None:
            dt += plan.step_delay(0, step_idx)
        if down:
            # the down-window must consume virtual time even with every
            # slot drained, else the rejoin step never arrives
            dt = max(dt, self.service.step_overhead_s)
        return dt

    # -------------------------------------------------------------- run
    def run(self, requests: Sequence[Request]) -> Dict[str, Any]:
        """Replay the trace to completion; returns `report()`."""
        reqs = sorted(requests, key=lambda r: (r.arrival_t, r.rid))
        n = len(reqs)
        i = 0
        now = reqs[0].arrival_t if reqs else 0.0
        self._start = now
        sched = self.sched
        every = self.cfg.invariant_every
        while True:
            while i < n and reqs[i].arrival_t <= now + 1e-12:
                self._submit(reqs[i])
                i += 1
            if not any(s is not None for s in sched.slots) \
                    and not sched.queue \
                    and not (self._pf_arrivals or self._pf_queue
                             or self._pf_live or self._pf_wire
                             or self._pf_awaiting):
                if i >= n:
                    break
                now = max(now, reqs[i].arrival_t)
                continue
            before = (sched.admitted, self.completed, self.faulted)
            dt = self._step(now, self.steps)
            self.steps += 1
            if every and self.steps % every == 0:
                sched.check_invariants()
                self.invariant_checks += 1
            if dt <= 0.0:
                # a zero-duration step made no progress toward any
                # event: admit/finish must have moved, else we are
                # wedged (a quota no request can ever satisfy is
                # rejected at submit, so this is a genuine bug)
                if (sched.admitted, self.completed,
                        self.faulted) == before and i >= n:
                    raise RuntimeError(
                        f"fleet sim wedged at step {self.steps}: queue "
                        f"depth {sched.queue_depth}, stall "
                        f"{sched.last_stall!r}, no progress possible")
                dt = self.service.step_overhead_s
            now += dt
        if self._pf_degraded:
            # outage reached end-of-run: flush the open degraded span
            self.degraded_s += now - self._pf_degraded_t0
            self._pf_degraded = False
        self._end = now
        sched.check_invariants()
        self.invariant_checks += 1
        if self.run_log is not None:
            elapsed = max(now - self._start, 1e-9)
            self._log(event="report", requests=self.completed,
                      tokens=self.tokens_out, elapsed_s=elapsed,
                      now=now, tokens_per_s=self.tokens_out / elapsed)
        if self.registry is not None:
            self._flush_registry()
        return self.report()

    def _flush_registry(self):
        """Exact counters/gauges into the metrics registry in one batch
        (the hot loop never takes the registry lock)."""
        reg = self.registry
        reg.inc("serve.requests_submitted", value=self.submitted)
        reg.inc("serve.requests_done", value=self.completed)
        reg.inc("serve.tokens_out", value=self.tokens_out)
        reg.inc("serve.prefill_chunks", value=self.prefill_chunks)
        reg.inc("serve.preemptions", value=self.preemptions)
        if self.failovers:
            reg.inc("serve.failovers", value=self.failovers)
        if self.replica_requeues:
            reg.inc("serve.replica_requeues",
                    value=self.replica_requeues)
        if self.retry_exhausted:
            reg.inc("serve.retry_exhausted", value=self.retry_exhausted)
        if self.expired:
            reg.inc("serve.deadline_exceeded", value=self.expired)
        if self.shed:
            reg.inc("serve.brownout_shed", value=self.shed)
        if self.tokens_discarded:
            reg.inc("serve.tokens_discarded",
                    value=self.tokens_discarded)
        if self.cfg.disagg:
            # same counter names the live DisaggCoordinator flushes, so
            # one reader (slo_report/tools_obs_report) covers both
            reg.inc("serve.tier_prefill_chunks",
                    value=self.tier_prefill_chunks)
            reg.inc("serve.ship_sent", value=self.ship_sent)
            reg.inc("serve.ship_acked", value=self.adoptions)
            if self.ship_dedups:
                reg.inc("serve.ship_dedups", value=self.ship_dedups)
            if self.ship_resends:
                reg.inc("serve.ship_resends", value=self.ship_resends)
            if self.reprefills:
                reg.inc("serve.disagg_reprefills",
                        value=self.reprefills)
            if self.colocated:
                reg.inc("serve.colocated_prefills",
                        value=self.colocated)
            if self.prefill_kills:
                reg.inc("serve.prefill_tier_kills",
                        value=self.prefill_kills)
            if self.degraded_entries:
                reg.inc("serve.degraded_entries",
                        value=self.degraded_entries)
        for reason, c in sorted(self.stall_steps.items()):
            reg.inc("serve.admission_stalls", value=c, reason=reason)
        for t, peaks in sorted(self.quota_peaks.items()):
            reg.set_gauge("serve.tenant_slots_peak", peaks["slots"],
                          tenant=t)
            reg.set_gauge("serve.tenant_pages_peak", peaks["pages"],
                          tenant=t)

    # ----------------------------------------------------------- report
    def _check_traces(self) -> Dict[str, Any]:
        """Validate + reconcile every kept (sampled) trace: exact span
        tiling means zero residual by construction — any nonzero
        residual is a tracer/sim bug, surfaced here."""
        max_residual = 0.0
        checked = 0
        for tr in self.tracer.traces.values():
            tr.validate()
            term = tr.terminal
            e2e = term.attrs.get("e2e_s") if term is not None else None
            r = tr.reconcile(e2e)
            if r is not None:
                checked += 1
                max_residual = max(max_residual, r)
        out = {"traces_checked": checked,
               "max_residual_s": max_residual}
        out.update(self._check_stitch())
        return out

    def _check_stitch(self) -> Dict[str, Any]:
        """Stitch every kept hop (decode + prefill-tier) and captured
        edge event into per-rid `FleetTrace`s, enforce the fleet-scope
        tiling contract, and decompose every completed request's
        critical path — the storm tests assert zero residual off this
        block (docs/observability.md, Distributed tracing)."""
        hops = list(self.tracer.completed)
        if self.pf_tracer is not None:
            hops += self.pf_tracer.completed
        if not hops:
            return {}
        from hetu_tpu.obs.critpath import critical_path
        fts = FleetTrace.stitch(traces=hops, events=self._events)
        quantum = self.service.step_overhead_s
        paths = 0
        max_cp = 0.0
        max_ttft = 0.0
        for ft in fts.values():
            ft.validate(step_quantum=quantum)
            cp = critical_path(ft)
            if cp is None:
                continue
            paths += 1
            max_cp = max(max_cp, abs(cp["residual_s"]))
            if cp["ttft_residual_s"] is not None:
                max_ttft = max(max_ttft, abs(cp["ttft_residual_s"]))
        return {"stitched": len(fts), "critical_paths": paths,
                "max_critpath_residual_s": max_cp,
                "max_ttft_residual_s": max_ttft}

    @staticmethod
    def _bucket_report(b: _Bucket, elapsed: float) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "requests": b.requests, "tokens_out": b.tokens,
            "slo_attainment": (b.slo_ok / b.requests
                               if b.requests else None),
            "goodput_tokens": b.goodput_tokens,
            "goodput_tokens_per_s": (b.goodput_tokens / elapsed
                                     if elapsed > 0 else None),
            "preemptions": b.preemptions,
            "stall_breakdown": dict(sorted(b.stalls.items())),
            "ttft_s": _hist_summary(b.ttft),
            "e2e_s": _hist_summary(b.e2e),
            "queue_wait_s": _hist_summary(b.queue_wait),
        }
        # fault fields only when nonzero: a no-fault run's report stays
        # byte-identical to the pre-fault-layer schema
        if b.retries:
            out["retries"] = b.retries
        if b.faults:
            out["faults"] = dict(sorted(b.faults.items()))
        if any(b.costs.values()):
            out["cost"] = dict(b.costs)
        return out

    def report(self) -> Dict[str, Any]:
        """The fleet report (tools_fleet.py's --json payload): derived
        ONLY from virtual-clock quantities and seeded reservoirs, so the
        same seed + trace reproduces it byte-identically."""
        elapsed = max(self._end - self._start, 0.0)
        tenants: Dict[str, _Bucket] = {}
        classes: Dict[str, _Bucket] = {}
        stall_breakdown: Dict[str, int] = {}
        for (tenant, cls), b in self._buckets.items():
            for agg_key, agg in ((tenant, tenants), (cls, classes)):
                m = agg.get(agg_key)
                if m is None:
                    m = agg[agg_key] = _Bucket()
                m.requests += b.requests
                m.tokens += b.tokens
                m.slo_ok += b.slo_ok
                m.goodput_tokens += b.goodput_tokens
                m.preemptions += b.preemptions
                m.retries += b.retries
                for k, v in b.faults.items():
                    m.faults[k] = m.faults.get(k, 0) + v
                for k, v in b.stalls.items():
                    m.stalls[k] = m.stalls.get(k, 0) + v
                for k, v in b.costs.items():
                    m.costs[k] += v
                for attr in ("ttft", "e2e", "queue_wait"):
                    _merge_hist(getattr(m, attr), getattr(b, attr))
            for k, v in b.stalls.items():
                stall_breakdown[k] = stall_breakdown.get(k, 0) + v
        quotas: Dict[str, Any] = {}
        for t, q in sorted(self.cfg.quotas.items()):
            peaks = self.quota_peaks.get(t, {"slots": 0, "pages": 0})
            quotas[t] = dict(q.to_dict(), peak_slots=peaks["slots"],
                             peak_pages=peaks["pages"])
        costs = {
            "by_tenant": {t: dict(m.costs)
                          for t, m in sorted(tenants.items())
                          if any(m.costs.values())},
        } if self.ledger is not None else None
        if costs is not None:
            total = {k: 0.0 for k in COST_FIELDS}
            for c in costs["by_tenant"].values():
                for k in COST_FIELDS:
                    total[k] += c[k]
            costs["total"] = total
        out: Dict[str, Any] = {
            "fleet_schema": FLEET_SCHEMA,
            "requests": self.submitted,
            "completed": self.completed,
            "tokens_out": self.tokens_out,
            "elapsed_s": elapsed,
            "tokens_per_s": (self.tokens_out / elapsed
                             if elapsed > 0 else None),
            "steps": self.steps,
            "admitted": self.sched.admitted,
            "preemptions": self.preemptions,
            "faults": {
                "failovers": self.failovers,
                "replica_requeues": self.replica_requeues,
                "retry_exhausted": self.retry_exhausted,
                "deadline_exceeded": self.expired,
                "brownout_shed": self.shed,
                "faulted": self.faulted,
                "tokens_discarded": self.tokens_discarded,
            },
            "prefill_chunks": self.prefill_chunks,
            "stall_steps": dict(sorted(self.stall_steps.items())),
            "stall_breakdown": dict(sorted(stall_breakdown.items())),
            "tenants": {t: self._bucket_report(m, elapsed)
                        for t, m in sorted(tenants.items())},
            "classes": {c: self._bucket_report(m, elapsed)
                        for c, m in sorted(classes.items())},
            "quotas": quotas,
            "invariants": {"checks": self.invariant_checks, "ok": True},
            "trace_check": self._check_traces(),
            "sample": self.sample,
            "service_model": self.service.to_dict(),
        }
        if self.cfg.disagg:
            # two-tier section only when the tier exists: colocated
            # runs keep the pre-disagg payload byte-identical
            out["disagg"] = {
                "prefill_slots": self._pf_slots,
                "tier_prefill_chunks": self.tier_prefill_chunks,
                "shipments": {
                    "sent": self.ship_sent,
                    "dropped": self.ship_dropped,
                    "duped": self.ship_duped,
                    "delayed": self.ship_delayed,
                    "dedups": self.ship_dedups,
                    "resends": self.ship_resends,
                },
                "adoptions": self.adoptions,
                "reprefills": self.reprefills,
                "colocated_prefills": self.colocated,
                "prefill_kills": self.prefill_kills,
                "degraded_entries": self.degraded_entries,
                "degraded_steps": self.degraded_steps,
                "degraded_s": self.degraded_s,
                "fallback": self.cfg.fallback,
            }
        if costs is not None:
            out["costs"] = costs
        if self.prefix_cache is not None:
            out["prefix_cache"] = {
                k: v for k, v in self.prefix_cache.stats().items()}
        return out


def attainment_delta(report: Dict[str, Any],
                     baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Per-tenant / per-class SLO-attainment degradation of a faulted
    fleet run against its no-fault baseline (two `report()` payloads
    from the same workload).  ``delta`` < 0 means the faults cost that
    tenant attainment; tools_fleet.py and the chaos recovery reports
    surface it."""
    out: Dict[str, Any] = {"tenants": {}, "classes": {}}
    for key in ("tenants", "classes"):
        for name, sec in report.get(key, {}).items():
            base = baseline.get(key, {}).get(name)
            if base is None:
                continue
            a = sec.get("slo_attainment")
            b = base.get("slo_attainment")
            if a is None or b is None:
                continue
            out[key][name] = {"attainment": a, "baseline": b,
                              "delta": a - b}
    return out


def fleet_workload(n: int, *, rate_per_s: float, burst: int = 0,
                   tenants: Sequence[str] = ("default",),
                   slo_classes=None, prompt_lens=(16, 64),
                   max_new=(4, 16), shared_prefix_len: int = 0,
                   vocab_size: int = 32000, seed: int = 0
                   ) -> List[Request]:
    """The canonical multi-tenant fleet trace: seeded arrivals (Poisson,
    or bursty when ``burst`` > 0) with tenants and SLO classes assigned
    round-robin — the shared workload builder tools_fleet.py, the chaos
    ``fleet-storm`` schedule and the tests all use."""
    from hetu_tpu.serving.traces import (bursty_arrivals,
                                         poisson_arrivals,
                                         synthetic_requests)
    arrivals = (bursty_arrivals(n, rate_per_s, burst=burst, seed=seed)
                if burst else poisson_arrivals(n, rate_per_s, seed=seed))
    return synthetic_requests(
        n, vocab_size=vocab_size, prompt_lens=prompt_lens,
        max_new=max_new, arrivals=arrivals, slo_classes=slo_classes,
        shared_prefix_len=shared_prefix_len,
        tenants=list(tenants), seed=seed)
