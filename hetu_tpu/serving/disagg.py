"""Disaggregated prefill/decode serving: acked KV shipping with
graceful colocation fallback.

The Hetis split (ROADMAP item 1): a PREFILL tier computes prompt KV
with the same chunk program the engine runs colocated, then ships the
finished scratch — optionally int8/int4-quantized on the wire per
EQuARX's cheap-collectives argument — to a DECODE tier that scatters it
into pool pages through the engine's own write program and decodes.
Because chunked prefill, the first-token rule
(`engine.first_token_from_logits`), and the page write are the SAME
programs both ways, the disaggregated path is token-byte-identical to
the single-engine run (with exact `ship_quant="none"` payloads; the
quantized wire modes trade that bit-exactness for bytes, like the
quantized KV pool itself).

Every new seam is a failure mode, so the handoff is an AT-LEAST-ONCE
protocol from day one:

* shipments carry a channel-global ``seq``; the decode side's
  `Scheduler.apply_shipment` gate dedupes redeliveries BEFORE any page
  is allocated — a double-delivered shipment can never alias pages
  (`check_invariants` holds the no-rid-in-two-slots rule);
* the receiver acks every delivery (including dedupes); the sender
  retransmits un-acked shipments after ``ship_timeout`` coordinator
  steps, up to ``ship_retry`` resends;
* past the resend budget — or when the prefill tier died with the
  request in flight — the request RE-PREFILLS under the decode
  engine's per-rid retry budget (HETU_TPU_SERVE_RETRY): the `attempt`
  accounting rides the same ``retry`` serve events and
  ``stats.retries`` fields replica failover uses, and past THAT budget
  the request terminates ``retry_exhausted``;
* a dead prefill tier (chaos ``prefill_kill``, consulted through
  `chaos.inject.maybe_chaos_disagg`) flips the coordinator DEGRADED:
  arrivals and timed-out re-prefills route to the decode engine's own
  queue — colocated chunked prefill, deterministically the same
  tokens — behind a sticky ``prefill_tier_down`` stall reason, metered
  as degraded-mode seconds, auto-recovering when the down-window
  passes.

The chaos wire kinds ``shipment_drop`` / ``shipment_dup`` /
``shipment_delay`` fire inside `ShipmentChannel` via
`FaultPlan.shipment_fault` — matching-call windows on the ship/ack
exchanges, deterministic given the plan.  On this in-process channel a
``shipment_delay``'s ``delay_s`` is counted in whole coordinator steps
(ceil) so replays are step-deterministic and hardware-free.

See docs/serving.md ("Disaggregated serving") and
docs/fault_tolerance.md for the operational story.
"""
from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.models.generation import extend_cache
from hetu_tpu.serving.engine import first_token_from_logits
from hetu_tpu.serving.kv_pool import dequantize_heads, quantize_heads
from hetu_tpu.serving.request import Request, RequestResult
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.disagg")

SHIP_QUANT_MODES = ("none", "int8", "int4")


@dataclasses.dataclass
class _Prefill:
    """One in-flight prefill on the worker."""
    request: Request
    cache: object
    chunks_done: int = 0
    attempt: int = 0


class PrefillWorker:
    """The prefill tier: chunked prompt prefill into a dense scratch
    cache — the engine's chunk program (`models/generation.extend_cache`)
    jitted standalone, advancing each in-flight prompt ONE chunk per
    step (the engine's disaggregation contract, kept even off-engine so
    service times stay comparable).  Finished prefills emit
    ``(request, attempt, t1, ks, vs)`` payloads: the full
    [L, max_len, n_kv, hd] scratch K/V plus the first token, computed
    with the shared `first_token_from_logits` rule — everything the
    decode tier needs to adopt the request byte-identically.

    No page pool lives here: prefill only ever touches scratch.  Dense
    models only (the resident-quantized MoE expert path stays on the
    engine)."""

    def __init__(self, model, params, *, prefill_chunk: int,
                 max_len: int, num_slots: int = 2,
                 sampling: bool = False, registry=None):
        if max_len % prefill_chunk:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"prefill_chunk {prefill_chunk}")
        self.model = model
        self.params = params
        self.prefill_chunk = prefill_chunk
        self.max_len = max_len
        self.num_slots = num_slots
        self.sampling = sampling
        self._registry = registry
        c = model.config
        n_kv = getattr(c, "num_key_value_heads", c.num_attention_heads)
        shape = (c.num_hidden_layers, 1, max_len, n_kv, c.head_dim)
        self._scratch = (jnp.zeros(shape, c.compute_dtype),
                         jnp.zeros(shape, c.compute_dtype))

        def chunk_fn(params, chunk, cache, start):
            return extend_cache(model, params, chunk, cache, start)

        self._chunk_jit = jax.jit(chunk_fn)
        self._queue: Deque[Tuple[Request, int]] = collections.deque()
        self._live: Dict[int, _Prefill] = {}
        self.chunks = 0
        self.finished = 0
        self.killed = 0

    def submit(self, req: Request, attempt: int = 0):
        if req.prompt_len > self.max_len:
            raise ValueError(f"request {req.rid}: prompt "
                             f"{req.prompt_len} exceeds max_len "
                             f"{self.max_len}")
        self._queue.append((req, attempt))

    def has(self, rid: int) -> bool:
        return rid in self._live or any(r.rid == rid
                                        for r, _ in self._queue)

    def drop(self, rid: int):
        """Forget `rid` wherever it sits (a terminated request must not
        keep burning prefill chunks)."""
        self._live.pop(rid, None)
        for item in list(self._queue):
            if item[0].rid == rid:
                self._queue.remove(item)

    @property
    def idle(self) -> bool:
        return not self._live and not self._queue

    def kill(self) -> List[int]:
        """The tier process dies (chaos ``prefill_kill``): every
        in-flight AND queued prefill is lost — the coordinator re-routes
        them (re-prefill / colocation fallback).  Returns the lost
        rids."""
        lost = list(self._live.keys()) + [r.rid for r, _ in self._queue]
        self._live.clear()
        self._queue.clear()
        self.killed += 1
        return lost

    def step(self) -> List[Tuple[Request, int, int, np.ndarray,
                                 np.ndarray]]:
        """Admit up to the slot limit, advance every in-flight prefill
        one chunk; returns the payloads that finished this step."""
        while len(self._live) < self.num_slots and self._queue:
            req, attempt = self._queue.popleft()
            self._live[req.rid] = _Prefill(request=req,
                                           cache=self._scratch,
                                           attempt=attempt)
        out = []
        for rid in list(self._live.keys()):
            pf = self._live[rid]
            req = pf.request
            plen = req.prompt_len
            C = self.prefill_chunk
            padded = math.ceil(plen / C) * C
            s = pf.chunks_done * C
            ids = np.zeros(C, np.int32)
            seg = req.prompt[s: min(s + C, plen)]
            ids[: len(seg)] = seg
            logits, pf.cache = self._chunk_jit(
                self.params, jnp.asarray(ids[None]), pf.cache,
                jnp.int32(s))
            pf.chunks_done += 1
            self.chunks += 1
            if self._registry is not None:
                self._registry.inc("serve.tier_prefill_chunks")
            if s + C < padded:
                continue
            t1 = first_token_from_logits(req, logits[0, plen - 1 - s],
                                         plen, sampling=self.sampling)
            ks = np.asarray(pf.cache[0][:, 0])
            vs = np.asarray(pf.cache[1][:, 0])
            del self._live[rid]
            self.finished += 1
            out.append((req, pf.attempt, int(t1), ks, vs))
        return out


@dataclasses.dataclass
class Shipment:
    """One prefill→decode KV handoff unit.  ``quant="none"`` ships the
    exact scratch; int8/int4 ship blockwise payloads + f32 scale planes
    (kv_pool.quantize_heads — the same wire format KV re-paging uses)."""
    seq: int
    rid: int
    attempt: int
    t1: int
    quant: str
    ks: np.ndarray
    vs: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None
    resend: int = 0

    @property
    def wire_bytes(self) -> int:
        n = self.ks.nbytes + self.vs.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n


def pack_shipment(seq: int, req: Request, attempt: int, t1: int,
                  ks: np.ndarray, vs: np.ndarray,
                  quant: str = "none") -> Shipment:
    """Quantize a prefill payload for the wire (a pure host-side
    transform — the decode program never sees the wire format)."""
    if quant not in SHIP_QUANT_MODES:
        raise ValueError(f"ship quant {quant!r} invalid; choices: "
                         f"{SHIP_QUANT_MODES}")
    if quant == "none":
        return Shipment(seq=seq, rid=req.rid, attempt=attempt, t1=t1,
                        quant=quant, ks=ks, vs=vs)
    bits = 8 if quant == "int8" else 4
    kq, ksc = quantize_heads(jnp.asarray(ks), bits=bits)
    vq, vsc = quantize_heads(jnp.asarray(vs), bits=bits)
    return Shipment(seq=seq, rid=req.rid, attempt=attempt, t1=t1,
                    quant=quant, ks=np.asarray(kq), vs=np.asarray(vq),
                    k_scale=np.asarray(ksc), v_scale=np.asarray(vsc))


def unpack_shipment(ship: Shipment) -> Tuple[np.ndarray, np.ndarray]:
    """Dequantize a wire payload back to the dense scratch shape the
    engine's write program expects (the pool re-quantizes on write when
    it is itself int8/int4)."""
    if ship.quant == "none":
        return ship.ks, ship.vs
    bits = 8 if ship.quant == "int8" else 4
    ks = dequantize_heads(jnp.asarray(ship.ks),
                          jnp.asarray(ship.k_scale), bits=bits)
    vs = dequantize_heads(jnp.asarray(ship.vs),
                          jnp.asarray(ship.v_scale), bits=bits)
    return np.asarray(ks), np.asarray(vs)


class ShipmentChannel:
    """The deterministic in-process prefill→decode wire: deliveries and
    acks land one coordinator step after their send, with the chaos
    shipment_* kinds consulted per exchange (`FaultPlan.shipment_fault`,
    op ``"ship"`` / ``"ack"``) — a drop loses the message (the sender's
    timeout machinery recovers it), a dup delivers it twice (the
    receiver's dedupe gate absorbs it), a delay defers delivery by
    ceil(delay_s) extra steps."""

    def __init__(self, plan=None, rank: Optional[int] = None):
        self.plan = plan
        self.rank = rank
        self._ships: List[Tuple[int, Shipment]] = []
        self._acks: List[Tuple[int, int]] = []
        self.sent = 0
        self.dropped = 0
        self.duped = 0
        self.delayed = 0
        self.acks_sent = 0
        self.acks_dropped = 0

    def _fault(self, op: str):
        if self.plan is None:
            return None
        return self.plan.shipment_fault(op, self.rank)

    def send(self, ship: Shipment, step: int) -> bool:
        """Put a shipment on the wire at `step`; False = the wire ate
        it (shipment_drop) — the sender keeps it pending and the
        retransmit timeout recovers."""
        self.sent += 1
        spec = self._fault("ship")
        due = step + 1
        if spec is not None and spec.kind == "shipment_drop":
            self.dropped += 1
            return False
        if spec is not None and spec.kind == "shipment_delay":
            due += max(1, math.ceil(spec.delay_s))
            self.delayed += 1
        self._ships.append((due, ship))
        if spec is not None and spec.kind == "shipment_dup":
            self._ships.append((due, ship))
            self.duped += 1
        return True

    def send_ack(self, seq: int, step: int) -> bool:
        """Ack `seq` back to the sender; a dropped ack leaves the
        shipment pending there — the retransmit is then deduped here."""
        self.acks_sent += 1
        spec = self._fault("ack")
        due = step + 1
        if spec is not None and spec.kind == "shipment_drop":
            self.acks_dropped += 1
            return False
        if spec is not None and spec.kind == "shipment_delay":
            due += max(1, math.ceil(spec.delay_s))
        self._acks.append((due, seq))
        if spec is not None and spec.kind == "shipment_dup":
            self._acks.append((due, seq))
        return True

    def requeue(self, ship: Shipment, step: int):
        """Put an undeliverable-right-now shipment (no decode capacity)
        back on the wire for the next step — no fault consult, it
        already survived the wire once."""
        self._ships.append((step + 1, ship))

    def poll(self, step: int) -> Tuple[List[Shipment], List[int]]:
        """Everything due at `step`, in send order (deterministic)."""
        ships = [s for due, s in self._ships if due <= step]
        self._ships = [(d, s) for d, s in self._ships if d > step]
        acks = [a for due, a in self._acks if due <= step]
        self._acks = [(d, a) for d, a in self._acks if d > step]
        return ships, acks

    @property
    def idle(self) -> bool:
        return not self._ships and not self._acks


@dataclasses.dataclass
class _PendingShip:
    """Sender-side bookkeeping for one request's handoff."""
    request: Request
    attempt: int = 0
    deadline: int = 0            # coordinator step the timeout fires at
    shipment: Optional[Shipment] = None
    resends: int = 0


class DisaggCoordinator:
    """Drives one prefill tier + one decode engine through the acked
    shipment protocol on a virtual clock (the engine.run discipline:
    arrivals from ``arrival_t``, time advanced by real step wall cost).

    ``fallback=False`` is the naive no-degradation model: while the
    prefill tier is down, arrivals just wait — the comparison baseline
    the fleet attainment test holds the graceful mode strictly above.
    """

    def __init__(self, prefill: PrefillWorker, decode, *, plan=None,
                 ship_timeout: int = 4, ship_retry: int = 2,
                 ship_quant: Optional[str] = None,
                 fallback: bool = True, rank: Optional[int] = None):
        if ship_timeout < 1:
            raise ValueError(f"ship_timeout must be >= 1, "
                             f"got {ship_timeout}")
        if ship_quant is None:
            from hetu_tpu.utils import flags
            ship_quant = flags.str_flag("HETU_TPU_SERVE_SHIP_QUANT")
        if ship_quant not in SHIP_QUANT_MODES:
            raise ValueError(f"ship_quant {ship_quant!r} invalid; "
                             f"choices: {SHIP_QUANT_MODES}")
        self.prefill = prefill
        self.decode = decode
        self.plan = plan
        self.ship_timeout = ship_timeout
        self.ship_retry = ship_retry
        self.ship_quant = ship_quant
        self.fallback = fallback
        self.rank = rank
        self.channel = ShipmentChannel(plan=plan, rank=rank)
        self._registry = decode._registry
        # the prefill TIER's flight recorder (only when the decode
        # engine's own tracer is on — same HETU_TPU_SERVE_TRACE gate):
        # each prefill incarnation of a request is its own hop trace
        # (tier="prefill") of queued -> prefill -> done("shipped"), so
        # FleetTrace.stitch sees the remote prompt work as a first-class
        # node with a ship edge into the decode hop
        self.pf_tracer = None
        if decode.tracer is not None:
            from hetu_tpu.serving.tracing import RequestTracer
            self.pf_tracer = RequestTracer(
                run_log=decode.run_log, registry=self._registry,
                keep=True, tier="prefill", replica=rank,
                clock=decode.clock_basis)
        self._now = 0.0
        self._seq = 0
        self._arrivals: Deque[Request] = collections.deque()
        self._awaiting: Dict[int, _PendingShip] = {}
        self._finished: set = set()
        self._step_idx = 0
        self.degraded = False
        self.degraded_steps = 0
        self.degraded_s = 0.0
        self._degraded_t0: Optional[float] = None
        self.colocated = 0
        self.reprefills = 0
        self.ship_dedups = 0
        self.adoptions = 0
        self.ship_bytes = 0
        self.steps_done = 0

    # ----------------------------------------------------------- intake
    def submit(self, req: Request, now: Optional[float] = None):
        """Accept a request into the two-tier pipeline: submission
        accounting (and the tracer's queued span) land on the decode
        replica that will own it; routing — prefill tier vs colocated
        fallback — happens at the next coordinator step so it sees the
        current degraded state."""
        self.decode.note_remote_submit(req, now)
        self._arrivals.append(req)

    # ----------------------------------------------------------- faults
    def kill_prefill_tier(self):
        """The prefill tier dies (chaos ``prefill_kill``): every
        in-flight and queued prefill is lost.  Their pending entries'
        timeouts are pulled forward to THIS step — the protocol's
        recovery path (resend has nothing to resend, so each re-prefills
        under the retry budget) runs immediately instead of waiting out
        the timer."""
        lost = self.prefill.kill()
        self._registry.inc("serve.prefill_tier_kills")
        for rid in lost:
            p = self._awaiting.get(rid)
            if p is not None and p.shipment is None:
                p.deadline = self._step_idx
            self._pf_close(rid, self._now, reason="prefill_kill")
        return lost

    def _enter_degraded(self, now: float):
        self.degraded = True
        self._degraded_t0 = now
        self._registry.inc("serve.degraded_entries")
        self.decode._log_serve(event="degraded", state="enter", now=now,
                               queue_depth=self.decode.scheduler
                               .queue_depth)

    def _exit_degraded(self, now: float):
        self.degraded = False
        span = now - (self._degraded_t0 or now)
        self.degraded_s += span
        self._degraded_t0 = None
        self.decode._log_serve(event="degraded", state="exit", now=now,
                               degraded_s=span)

    # ---------------------------------------------------------- routing
    def _fallback_submit(self, req: Request, now: float):
        """Colocated chunked prefill on the decode engine (graceful
        degradation): the request enters the decode scheduler's own
        queue — submission was already accounted at `submit`, so only
        the queue entry and the sticky stall reason land here."""
        self.decode.scheduler.submit(req)
        self.colocated += 1
        self._registry.inc("serve.colocated_prefills")
        if self.decode.tracer is not None:
            self.decode.tracer.on_stall([req.rid], "prefill_tier_down")

    # --------------------------------------- prefill-tier hop tracing
    def _pf_close(self, rid: int, now: float, *, reason: str):
        """Close a still-open prefill hop with an ``evicted`` terminal
        (the tier died / the request re-prefills) so the hop's spans
        stay a complete, stitchable trace."""
        if self.pf_tracer is None or rid not in self.pf_tracer._open:
            return
        st = self.pf_tracer._open[rid]
        p = self._awaiting.get(rid)
        req = p.request if p is not None else None
        if req is None:
            req = Request(rid=rid, prompt=np.zeros(1, np.int32),
                          max_new_tokens=1)
        now = max(now, st.last_t)
        self.pf_tracer.on_finish(req, None, reason, now, tokens=0,
                                 evicted=True)

    def _pf_observe_admissions(self, now: float):
        """Prefill-tier admissions happen inside the worker's step;
        close the hop's queued span the first time we see the rid live
        (its first chunk lands this same step)."""
        if self.pf_tracer is None:
            return
        for rid, pf in self.prefill._live.items():
            st = self.pf_tracer._open.get(rid)
            if st is not None and st.phase == "queued":
                self.pf_tracer.on_admit(pf.request, None, now)

    def _pf_shipped(self, req: Request, now: float):
        """The hop's terminal: the finished scratch went on the wire —
        prefill span closes at the ship and the hop ends ``done``
        (reason ``shipped``), the source node of the stitcher's
        ship -> adopt edge."""
        if self.pf_tracer is None or req.rid not in self.pf_tracer._open:
            return
        st = self.pf_tracer._open[req.rid]
        if st.phase == "queued":     # admitted+finished in one step
            self.pf_tracer.on_admit(req, None, now)
        chunks = math.ceil(req.prompt_len / self.prefill.prefill_chunk)
        self.pf_tracer.on_first_token(req, None, now, chunk=chunks)
        self.pf_tracer.on_finish(req, None, "shipped", now, tokens=0)

    def _route(self, req: Request, now: float, attempt: int = 0):
        if self.degraded and self.fallback:
            self._awaiting.pop(req.rid, None)
            if self.decode._sampled(req.rid):
                self.decode._log_serve(event="dispatch", req=req.rid,
                                       tier="decode", now=now,
                                       fallback=True)
            self._fallback_submit(req, now)
            return
        self.prefill.submit(req, attempt=attempt)
        if self.pf_tracer is not None:
            self.pf_tracer.on_submit(req, at=now)
        if self.decode._sampled(req.rid):
            self.decode._log_serve(event="dispatch", req=req.rid,
                                   tier="prefill", now=now,
                                   **({"attempt": attempt}
                                      if attempt else {}))
        p = self._awaiting.get(req.rid)
        if p is None:
            p = self._awaiting[req.rid] = _PendingShip(request=req)
        p.attempt = attempt
        p.shipment = None
        p.resends = 0
        p.deadline = self._step_idx + self.ship_timeout

    def _log_ship(self, ship: Shipment, now: float, **extra):
        if self.decode._sampled(ship.rid):
            self.decode._log_serve(event="ship", req=ship.rid,
                                   seq=ship.seq, attempt=ship.attempt,
                                   resend=ship.resend, now=now,
                                   quant=ship.quant, **extra)

    def _reprefill(self, rid: int, p: _PendingShip, now: float):
        """The give-up path: the shipment (or the prefill itself) is
        unrecoverable — re-prefill under the decode engine's retry
        budget, or terminate ``retry_exhausted`` past it.  The retry
        rides the same `scheduler.retries` / ``retry`` serve-event
        `attempt` machinery replica failover uses, so done events carry
        the full attempt history either way."""
        sched = self.decode.scheduler
        req = p.request
        self._pf_close(rid, now, reason="reprefill")
        retries = sched.retries.get(rid, 0)
        if retries >= self.decode.config.retry_budget:
            self.prefill.drop(rid)
            self._awaiting.pop(rid, None)
            self._finished.add(rid)
            if self.decode.tracer is not None:
                self.decode.tracer.on_finish(
                    req, -1, "retry_exhausted", now, tokens=0,
                    e2e_s=now - float(req.arrival_t), evicted=True)
            self.decode._finish_faulted(
                req, now, self.decode._fault_results,
                reason="retry_exhausted", event="evict", tokens=[])
            return
        sched.retries[rid] = retries + 1
        self.reprefills += 1
        self._registry.inc("serve.disagg_reprefills")
        if self.decode._sampled(rid):
            self.decode._log_serve(event="retry", req=rid, now=now,
                                   attempt=retries + 1, ship=True,
                                   tokens_discarded=0,
                                   slo_class=req.slo.name,
                                   tenant=req.tenant,
                                   **self.decode._weight_fields())
        self.prefill.drop(rid)
        self._route(req, now, attempt=p.attempt + 1)

    # ------------------------------------------------------------- step
    def step(self, now: float) -> List[RequestResult]:
        """One coordinator iteration: chaos, degraded-state transitions,
        arrival routing, one prefill-tier step, wire deliveries +
        adoption, ack/timeout processing, then one decode-engine step."""
        from hetu_tpu.chaos.inject import maybe_chaos_disagg
        step_idx = self._step_idx
        self._now = now
        chaos = maybe_chaos_disagg(self.plan, self, step_idx,
                                   self.rank)
        down = chaos["prefill_down"]
        if down and not self.degraded:
            self._enter_degraded(now)
        elif not down and self.degraded:
            self._exit_degraded(now)
        if self.degraded:
            self.degraded_steps += 1

        while self._arrivals:
            req = self._arrivals[0]
            if self.degraded and not self.fallback:
                break               # naive model: wait out the outage
            self._arrivals.popleft()
            self._route(req, now)

        if not down:
            finished_pf = self.prefill.step()
            self._pf_observe_admissions(now)
            for req, attempt, t1, ks, vs in finished_pf:
                self._seq += 1
                ship = pack_shipment(self._seq, req, attempt, t1, ks,
                                     vs, quant=self.ship_quant)
                p = self._awaiting.get(req.rid)
                if p is None:       # dropped/terminated meanwhile
                    self._pf_close(req.rid, now, reason="dropped")
                    continue
                self._pf_shipped(req, now)
                p.shipment = ship
                p.deadline = step_idx + self.ship_timeout
                self.ship_bytes += ship.wire_bytes
                self._registry.inc("serve.ship_sent")
                self._log_ship(ship, now)
                self.channel.send(ship, step_idx)

        ships, acks = self.channel.poll(step_idx)
        sched = self.decode.scheduler
        for ship in ships:
            rid = ship.rid
            if rid in self._finished or rid not in self._awaiting:
                # a late duplicate of a request that already completed
                # its handoff — dedupe, but still ack (the sender may
                # not have heard yet)
                self.ship_dedups += 1
                self._registry.inc("serve.ship_dedups")
                self._log_ship(ship, now, dedup=True)
                self.channel.send_ack(ship.seq, step_idx)
                continue
            if not sched.apply_shipment(rid, ship.seq):
                self.ship_dedups += 1
                self._registry.inc("serve.ship_dedups")
                self._log_ship(ship, now, dedup=True)
                self.channel.send_ack(ship.seq, step_idx)
                continue
            ks, vs = unpack_shipment(ship)
            req = self._awaiting[rid].request
            if not self.decode.adopt_prefilled(req, ks, vs, ship.t1,
                                               now):
                # no decode capacity right now: un-burn the seq, put
                # the delivery back for next step, and push the sender
                # deadline — the shipment is safely on the in-process
                # wire, so a retransmit would only add dedupe noise
                sched.unapply_shipment(rid, ship.seq)
                self.channel.requeue(ship, step_idx)
                self._awaiting[rid].deadline = \
                    step_idx + self.ship_timeout
                continue
            self.adoptions += 1
            self.channel.send_ack(ship.seq, step_idx)
        for seq in acks:
            for rid, p in list(self._awaiting.items()):
                if p.shipment is not None and p.shipment.seq == seq:
                    del self._awaiting[rid]
                    self._registry.inc("serve.ship_acked")
                    break

        for rid, p in list(self._awaiting.items()):
            if step_idx < p.deadline:
                continue
            live = any(st is not None and st.request.rid == rid
                       for st in sched.slots)
            if rid in self._finished or live:
                # adopted but the ack went missing: retransmit so the
                # receiver's dedupe gate re-acks; past the budget the
                # in-process sender may trust local state and stand down
                if p.shipment is not None and p.resends < self.ship_retry:
                    p.resends += 1
                    p.shipment.resend += 1
                    p.deadline = step_idx + self.ship_timeout
                    self._registry.inc("serve.ship_resends")
                    self._log_ship(p.shipment, now)
                    self.channel.send(p.shipment, step_idx)
                else:
                    del self._awaiting[rid]
                continue
            if p.shipment is not None and p.resends < self.ship_retry:
                p.resends += 1
                p.shipment.resend += 1
                p.deadline = step_idx + self.ship_timeout
                self._registry.inc("serve.ship_resends")
                self._log_ship(p.shipment, now)
                self.channel.send(p.shipment, step_idx)
            elif p.shipment is None and self.prefill.has(rid):
                # no shipment yet but the (live) prefill tier still
                # holds the request — it is queued/advancing, not lost;
                # only a kill clears the worker and lets the timer fire
                p.deadline = step_idx + self.ship_timeout
            else:
                self._reprefill(rid, p, now)

        results = self.decode.step(now)
        for r in results:
            self._finished.add(r.rid)
            self._awaiting.pop(r.rid, None)
            sched.ship_forget(r.rid)
        self._step_idx += 1
        self.steps_done += 1
        return results

    # -------------------------------------------------------------- run
    @property
    def idle(self) -> bool:
        return (not self._arrivals and not self._awaiting
                and self.prefill.idle and self.channel.idle
                and not self.decode.scheduler.active_slots()
                and not self.decode.scheduler.queue
                and not self.decode._fault_results)

    def run(self, requests: Sequence[Request], *, start: float = 0.0,
            on_step=None) -> List[RequestResult]:
        """Drive the two-tier pipeline over a request trace to
        completion (the engine.run contract: virtual arrivals, wall-cost
        clock, ``on_step(i)`` inside the timed window)."""
        pending = sorted(requests, key=lambda r: (r.arrival_t, r.rid))
        now = start
        results: List[RequestResult] = []
        i = 0
        while True:
            while i < len(pending) and \
                    pending[i].arrival_t <= now + 1e-12:
                self.submit(pending[i])
                i += 1
            if self.idle:
                if i >= len(pending):
                    break
                now = max(now, pending[i].arrival_t)
                continue
            t0 = time.perf_counter()
            if on_step is not None:
                self.decode._last_clock = max(
                    self.decode._last_clock, now)
                on_step(self._step_idx)
            results.extend(self.step(now))
            now += time.perf_counter() - t0
        if self.degraded:
            self._exit_degraded(now)
            self.degraded = True        # state stands; metering flushed
        n_tokens = sum(len(r.tokens) for r in results)
        elapsed = max(now - start, 1e-9)
        self.decode._log_serve(event="report", requests=len(results),
                               tokens=n_tokens, elapsed_s=elapsed,
                               now=now,
                               tokens_per_s=n_tokens / elapsed)
        return sorted(results, key=lambda r: r.rid)

    def summary(self) -> Dict[str, object]:
        """Protocol + degradation accounting for reports and tests."""
        return {
            "ship_sent": self.channel.sent,
            "ship_dropped": self.channel.dropped,
            "ship_duped": self.channel.duped,
            "ship_delayed": self.channel.delayed,
            "ship_dedups": self.ship_dedups,
            "ship_resends": self._registry_count("serve.ship_resends"),
            "adoptions": self.adoptions,
            "reprefills": self.reprefills,
            "colocated": self.colocated,
            "degraded_steps": self.degraded_steps,
            "degraded_s": self.degraded_s,
            "ship_bytes": self.ship_bytes,
            "sched_ship_dedups": self.decode.scheduler.ship_dedups,
        }

    def _registry_count(self, name: str) -> int:
        for c in self._registry.snapshot()["counters"]:
            if c["name"] == name:
                return int(c["value"])
        return 0
