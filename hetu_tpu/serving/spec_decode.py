"""Speculative decoding: draft k tokens on the host, verify k+1 in one
batched forward.

The decode-step cost of a serving engine is HBM-bound: every step reads
the full parameter set once no matter how many tokens it emits.
Speculative decoding amortizes that read — a cheap DRAFTER proposes k
tokens per slot, and ONE `verify_step_slots` forward
(models/generation.py — the `extend_cache` machinery with per-slot
depths) scores all k+1 positions.  Accepted drafts emit in bulk; the
roofline win is provable hardware-free (`roofline_report`, the
comm/wire.py discipline; bench.py detail.serving records it).

**Acceptance = sample-then-match.**  Per verify position the engine
computes the token the SEQUENTIAL path would have emitted there —
argmax for greedy rows, `sampling.sample_tokens` with the position's
own fold_in key for sampling rows — and accepts draft tokens while they
match.  For a DETERMINISTIC drafter (a point-mass proposal q) this is
exactly the standard speculative rejection rule: the draft is accepted
with probability p(d), and conditioned on rejection the emitted token
is distributed as the residual norm(max(p - q, 0)) = p restricted to
tokens != d — so the output DISTRIBUTION matches the non-speculative
path, and because the per-position PRNG keys are identical, sampled
output is token-IDENTICAL run-for-run too.  Greedy is the
temperature->0 case: accept iff draft == argmax (token-identical to
sequential `generate()`, the acceptance golden).

**Drafters** are pluggable host-side proposers (`Drafter.propose`).
`NGramDrafter` is the built-in model-free one (prompt-lookup decoding):
match the longest recent n-gram earlier in the sequence and replay the
tokens that followed it — free to compute, and highly effective on the
repetitive spans (code, quotations, structured output) where serving
traffic actually burns tokens.  A small draft MODEL plugs in as a
`Drafter` returning its own argmax rollout; the engine only sees
`propose`.

Gated by ``HETU_TPU_SPEC_DECODE`` (none | ngram; registered identity
contract — unset builds the pre-speculative decode program
byte-for-byte) with ``HETU_TPU_SPEC_K`` draft tokens per step.  See
docs/serving.md.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class Drafter:
    """Host-side draft proposer interface."""

    #: how many trailing context tokens `propose` actually reads; the
    #: engine slices the sequence to this before calling (None = the
    #: full history) so drafting stays O(window) per step instead of
    #: rebuilding the whole prompt+generated list on the decode hot
    #: loop (quadratic per request at long contexts)
    window: Optional[int] = None

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        """Propose k draft continuations of `tokens` (the trailing
        `window` of prompt + generated so far).  Must return exactly k
        token ids."""
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the longest trailing n-gram (n down to 1) and propose the tokens
    that followed it; pad by repeating the last token when the lookup
    comes up short (a deliberately cheap tail — mismatches cost one
    rejected draft, not correctness)."""

    def __init__(self, max_ngram: int = 3, window: int = 1024):
        if max_ngram < 1:
            raise ValueError("max_ngram must be >= 1")
        self.max_ngram = max_ngram
        self.window = window

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        toks = list(tokens[-self.window:])
        n = len(toks)
        out: List[int] = []
        for m in range(min(self.max_ngram, n - 1), 0, -1):
            tail = toks[n - m:]
            # most recent earlier occurrence of the trailing m-gram
            for s in range(n - m - 1, -1, -1):
                if toks[s:s + m] == tail:
                    out = toks[s + m: s + m + k]
                    break
            if out:
                break
        last = toks[-1] if toks else 0
        while len(out) < k:
            out.append(out[-1] if out else last)
        return out[:k]


class CallableDrafter(Drafter):
    """Adapter: any ``fn(tokens, k) -> [k] ids`` (e.g. a small draft
    model's rollout) as a Drafter."""

    def __init__(self, fn):
        self.fn = fn

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        out = list(self.fn(tokens, k))
        if len(out) != k:
            raise ValueError(f"drafter returned {len(out)} tokens, "
                             f"wanted {k}")
        return out


def make_drafter(mode: str, **kw) -> Optional[Drafter]:
    """The HETU_TPU_SPEC_DECODE vocabulary -> a Drafter (None for
    'none')."""
    if mode == "none":
        return None
    if mode == "ngram":
        return NGramDrafter(**kw)
    raise ValueError(f"unknown spec-decode mode {mode!r}; "
                     "choices: ('none', 'ngram')")


def accept_counts(targets: np.ndarray, drafts: np.ndarray) -> np.ndarray:
    """Host-side twin of the in-graph acceptance rule (the engine's
    program computes this with cumprod; tests pin the two together).
    targets: [S, k+1] the per-position sequential-path tokens; drafts:
    [S, k].  Returns [S] n_emit in [1, k+1]: the longest matched prefix
    plus the one always-emitted correction/bonus token."""
    match = targets[:, :-1] == drafts            # [S, k]
    acc = np.cumprod(match.astype(np.int64), axis=1).sum(axis=1)
    return acc + 1


# ---------------------------------------------------------------------------
# analytic roofline (bench.py detail.serving, the hardware-free pattern)
# ---------------------------------------------------------------------------

def expected_tokens_per_step(acceptance: float, k: int) -> float:
    """E[tokens emitted per verify step] under per-position acceptance
    probability `acceptance`: 1 + a + a^2 + ... + a^k (the matched
    prefix is geometric, truncated at k, plus the always-emitted
    bonus/correction token)."""
    if not 0.0 <= acceptance <= 1.0:
        raise ValueError(f"acceptance must be in [0, 1], got {acceptance}")
    if acceptance == 1.0:
        return float(k + 1)
    return (1.0 - acceptance ** (k + 1)) / (1.0 - acceptance)


def roofline_report(*, n_params: float, flops_per_token: float,
                    step_bytes: float, slots: int, k: int,
                    acceptance: float, peak_flops: float,
                    hbm_bytes_per_s: float) -> Dict[str, float]:
    """Analytic spec-decode speedup at the roofline (hardware-free).

    A plain decode step moves `step_bytes` (params + every slot's KV)
    and computes `slots * flops_per_token`; a verify step moves the
    SAME bytes (params read once, KV read once — the k+1 queries share
    them) but computes (k+1)x the FLOPs and emits
    `expected_tokens_per_step(acceptance, k)` tokens per slot.  While
    decode is HBM-bound (it always is at serving batch sizes), the
    verify step's extra FLOPs ride under the same memory roof and the
    speedup approaches E[emit] directly."""
    e_emit = expected_tokens_per_step(acceptance, k)
    t_decode = max(slots * flops_per_token / peak_flops,
                   step_bytes / hbm_bytes_per_s)
    t_verify = max(slots * (k + 1) * flops_per_token / peak_flops,
                   step_bytes / hbm_bytes_per_s)
    base = slots / t_decode
    spec = slots * e_emit / t_verify
    return {
        "k": float(k),
        "acceptance": acceptance,
        "expected_tokens_per_step": round(e_emit, 4),
        "decode_step_s": t_decode,
        "verify_step_s": t_verify,
        "decode_tokens_per_s": round(base, 1),
        "spec_tokens_per_s": round(spec, 1),
        "speedup": round(spec / base, 3),
    }
