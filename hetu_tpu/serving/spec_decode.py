"""Speculative decoding: draft k tokens on the host, verify k+1 in one
batched forward.

The decode-step cost of a serving engine is HBM-bound: every step reads
the full parameter set once no matter how many tokens it emits.
Speculative decoding amortizes that read — a cheap DRAFTER proposes k
tokens per slot, and ONE `verify_step_slots` forward
(models/generation.py — the `extend_cache` machinery with per-slot
depths) scores all k+1 positions.  Accepted drafts emit in bulk; the
roofline win is provable hardware-free (`roofline_report`, the
comm/wire.py discipline; bench.py detail.serving records it).

**Acceptance = sample-then-match.**  Per verify position the engine
computes the token the SEQUENTIAL path would have emitted there —
argmax for greedy rows, `sampling.sample_tokens` with the position's
own fold_in key for sampling rows — and accepts draft tokens while they
match.  For a DETERMINISTIC drafter (a point-mass proposal q) this is
exactly the standard speculative rejection rule: the draft is accepted
with probability p(d), and conditioned on rejection the emitted token
is distributed as the residual norm(max(p - q, 0)) = p restricted to
tokens != d — so the output DISTRIBUTION matches the non-speculative
path, and because the per-position PRNG keys are identical, sampled
output is token-IDENTICAL run-for-run too.  Greedy is the
temperature->0 case: accept iff draft == argmax (token-identical to
sequential `generate()`, the acceptance golden).

**Stochastic drafters** (``Drafter.stochastic``) expose the full
proposal DISTRIBUTION q per draft position (`propose_with_probs`), and
the engine verifies them with the full rejection rule instead
(:func:`stochastic_verify`, in-graph): draft d_i is accepted with
probability min(1, p(d_i)/q(d_i)), and the first rejected position
resamples from the residual norm(max(p - q, 0)).  The output
distribution is exactly p (the sequential path's), for ANY q — the
sample-then-match rule is the point-mass special case.  All the draws
(accept uniforms, residual Gumbels) come from the same counter-based
hash of the (seed, absolute_position) fold_in key the sampler uses
(`ops/pallas/sample.hash_uniform`, lanes 1/2), so stochastic verify is
as replay-deterministic as everything else.

**Drafters** are pluggable host-side proposers (`Drafter.propose`).
`NGramDrafter` is the built-in model-free one (prompt-lookup decoding):
match the longest recent n-gram earlier in the sequence and replay the
tokens that followed it — free to compute, and highly effective on the
repetitive spans (code, quotations, structured output) where serving
traffic actually burns tokens.  `ModelDrafter` runs a small draft MODEL
(resident-quantized, the serving/experts.py discipline) and samples its
rollout from the model's own temperature-scaled softmax — the q the
stochastic rule needs.

Gated by ``HETU_TPU_SPEC_DECODE`` (none | ngram | model; registered
identity contract — unset builds the pre-speculative decode program
byte-for-byte) with ``HETU_TPU_SPEC_K`` draft tokens per step.  See
docs/serving.md.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class Drafter:
    """Host-side draft proposer interface."""

    #: how many trailing context tokens `propose` actually reads; the
    #: engine slices the sequence to this before calling (None = the
    #: full history) so drafting stays O(window) per step instead of
    #: rebuilding the whole prompt+generated list on the decode hot
    #: loop (quadratic per request at long contexts)
    window: Optional[int] = None

    #: True when the drafter SAMPLES its proposals and reports the full
    #: distribution via `propose_with_probs`; the engine then verifies
    #: with the stochastic p/q rejection rule instead of
    #: sample-then-match (which stays exact only for point-mass q)
    stochastic: bool = False

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        """Propose k draft continuations of `tokens` (the trailing
        `window` of prompt + generated so far).  Must return exactly k
        token ids."""
        raise NotImplementedError

    def propose_with_probs(self, tokens: Sequence[int], k: int, *,
                           seed: int = 0, start_pos: int = 0
                           ) -> Tuple[List[int], np.ndarray]:
        """Stochastic form: k draft tokens plus the [k, V] proposal
        distributions they were drawn from.  `seed`/`start_pos` feed the
        replay-deterministic draw (the request's sampling seed and the
        absolute position of the first drafted token).  Only drafters
        with ``stochastic = True`` implement this."""
        raise NotImplementedError(
            f"{type(self).__name__} is a deterministic drafter; the "
            "engine verifies it by sample-then-match")


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the longest trailing n-gram (n down to 1) and propose the tokens
    that followed it; pad by repeating the last token when the lookup
    comes up short (a deliberately cheap tail — mismatches cost one
    rejected draft, not correctness)."""

    def __init__(self, max_ngram: int = 3, window: int = 1024):
        if max_ngram < 1:
            raise ValueError("max_ngram must be >= 1")
        self.max_ngram = max_ngram
        self.window = window

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        toks = list(tokens[-self.window:])
        n = len(toks)
        out: List[int] = []
        for m in range(min(self.max_ngram, n - 1), 0, -1):
            tail = toks[n - m:]
            # most recent earlier occurrence of the trailing m-gram
            for s in range(n - m - 1, -1, -1):
                if toks[s:s + m] == tail:
                    out = toks[s + m: s + m + k]
                    break
            if out:
                break
        last = toks[-1] if toks else 0
        while len(out) < k:
            out.append(out[-1] if out else last)
        return out[:k]


class CallableDrafter(Drafter):
    """Adapter: any ``fn(tokens, k) -> [k] ids`` (e.g. a small draft
    model's rollout) as a Drafter."""

    def __init__(self, fn):
        self.fn = fn

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        out = list(self.fn(tokens, k))
        if len(out) != k:
            raise ValueError(f"drafter returned {len(out)} tokens, "
                             f"wanted {k}")
        return out


def _quantize_resident(params, *, bits: int, block: int):
    """Blockwise-quantize every float matrix leaf of a params tree for
    RESIDENT storage (the serving/experts.py discipline, applied to the
    whole draft model: the int payload + f32 scales live in device
    memory; the forward dequantizes a working copy in-program).  1-D
    leaves (norm gains, biases) stay fp — they are bytes-trivial and
    precision-critical.  Returns (tree_q, spec)."""
    from hetu_tpu.comm.compress import quantize_blockwise
    spec: Dict[str, Any] = {}

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        leaf = node
        if getattr(leaf, "ndim", 0) < 2 or not jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        flat = jnp.asarray(leaf).reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        pad = (-n) % block
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        q, s = quantize_blockwise(flat, block, bits=bits)
        spec["/".join(path)] = {"shape": tuple(int(d) for d in leaf.shape),
                                "dtype": jnp.asarray(leaf).dtype}
        return {"q": q, "s": s}

    return walk(params, ()), spec


def _dequantize_resident(params_q, spec):
    """In-program inverse of `_quantize_resident`."""
    from hetu_tpu.comm.compress import dequantize_blockwise

    def walk(node, path):
        if not isinstance(node, dict):
            return node
        meta = spec.get("/".join(path))
        if meta is not None:
            flat = dequantize_blockwise(node["q"], node["s"])
            n = int(np.prod(meta["shape"]))
            return flat[:n].reshape(meta["shape"]).astype(meta["dtype"])
        return {k: walk(v, path + (k,)) for k, v in node.items()}

    return walk(params_q, ())


class ModelDrafter(Drafter):
    """A small draft MODEL as a stochastic drafter.

    Proposals are SAMPLED from the draft model's temperature-scaled
    softmax — exactly the q distribution `propose_with_probs` reports,
    which is what makes the engine's stochastic p/q rejection rule
    distribution-exact for any draft model, good or bad.  The draw is
    Gumbel-argmax over the shared counter-based hash (lane 3) of the
    request's (seed, absolute_position) fold_in key, so drafts replay
    deterministically like every other sampled token.  temperature=0
    degenerates to an argmax rollout with a point-mass q (the
    deterministic rule falls out of the stochastic one).

    The draft params are blockwise-quantized at construction and live
    resident in int8 (`_quantize_resident`); each propose runs k full
    forwards over a bounded trailing window — the draft model is small
    enough that re-reading its params k times still costs a fraction of
    one target-model verify step."""

    stochastic = True

    def __init__(self, model, params, *, temperature: float = 1.0,
                 window: int = 256, quantize_bits: int = 8,
                 quantize_block: int = 64):
        if window < 1:
            raise ValueError("window must be >= 1")
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        self.model = model
        self.temperature = float(temperature)
        self.window = int(window)
        self.params_q, self._spec = _quantize_resident(
            params, bits=quantize_bits, block=quantize_block)

        def fwd(pq, ctx):
            from hetu_tpu.models import generation
            p = _dequantize_resident(pq, self._spec)
            logits, _ = generation.prefill(model, p, ctx, ctx.shape[1])
            return logits[0].astype(jnp.float32)           # [V]

        self._fwd = jax.jit(fwd)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        return self.propose_with_probs(tokens, k)[0]

    def propose_with_probs(self, tokens: Sequence[int], k: int, *,
                           seed: int = 0, start_pos: int = 0
                           ) -> Tuple[List[int], np.ndarray]:
        from hetu_tpu.ops.pallas.sample import gumbel
        from hetu_tpu.serving.sampling import key_words
        toks = list(tokens[-self.window:]) or [0]
        out: List[int] = []
        probs: List[np.ndarray] = []
        for i in range(k):
            ctx = jnp.asarray([toks[-self.window:]], jnp.int32)
            logits = self._fwd(self.params_q, ctx)
            if self.temperature > 0:
                scaled = logits / self.temperature
                words = key_words(jnp.asarray([seed]),
                                  jnp.asarray([start_pos + i]))
                g = gumbel(words[0, 0], words[0, 1],
                           jnp.arange(logits.shape[0], dtype=jnp.uint32),
                           lane=3)
                tok = int(jnp.argmax(scaled + g))
                q = np.asarray(jax.nn.softmax(scaled))
            else:
                tok = int(jnp.argmax(logits))
                q = np.zeros(logits.shape[0], np.float32)
                q[tok] = 1.0
            out.append(tok)
            probs.append(q)
            toks.append(tok)
        return out, np.stack(probs)


def make_drafter(mode: str, **kw) -> Optional[Drafter]:
    """The HETU_TPU_SPEC_DECODE vocabulary -> a Drafter (None for
    'none').  mode='model' requires `model` and `params` kwargs (the
    engine forwards its draft_model/draft_params)."""
    if mode == "none":
        return None
    if mode == "ngram":
        return NGramDrafter(**kw)
    if mode == "model":
        if "model" not in kw or "params" not in kw:
            raise ValueError("spec-decode mode 'model' needs a draft "
                             "model: pass model=/params= (the engine's "
                             "draft_model/draft_params kwargs)")
        return ModelDrafter(**kw)
    raise ValueError(f"unknown spec-decode mode {mode!r}; "
                     "choices: ('none', 'ngram', 'model')")


def stochastic_verify(logits_grid, q_probs, drafts, seeds, positions,
                      temps, top_ks, top_ps):
    """The full speculative rejection rule, in-graph (the stochastic
    drafters' verify epilogue; jnp, jit-safe).

    logits_grid: [S, k+1, V] target logits at the verify positions;
    q_probs: [S, k, V] the drafter's proposal distributions; drafts:
    [S, k] the proposed tokens (SAMPLED from q); positions: [S, k+1]
    absolute sequence positions of the tokens being decided (the key
    derivation input); temps/top_ks/top_ps: [S] per-slot sampling
    params.  Returns (out_tokens [S, k+1] int32, n_emit [S] int32).

    Per draft position i: the target distribution p is the softmax of
    the FILTERED temperature-scaled logits (exactly what the sequential
    sampler draws from); accept with probability min(1, p(d_i)/q(d_i))
    using a lane-1 hash uniform of the position's fold_in key; the
    first rejected position emits a residual resample from
    norm(max(p - q, 0)) via lane-2 Gumbel-argmax.  Greedy rows
    (temp == 0) collapse to accept-iff-argmax with an argmax
    correction.  Full acceptance emits the bonus token, sampled at
    position k with the position's own lane-0 key — identical to the
    sequential path's draw there."""
    from hetu_tpu.ops.pallas.sample import gumbel, hash_uniform
    from hetu_tpu.serving import sampling

    S, C, V = logits_grid.shape
    k = C - 1
    rep = lambda x: jnp.repeat(x, k)  # noqa: E731 — [S] -> [S*k]

    # target distribution p at the k draft positions: softmax of the
    # SAME filtered logits the sequential sampler argmax-Gumbels over
    filt = sampling.filtered_logits(
        logits_grid[:, :k].reshape(S * k, V), rep(temps), rep(top_ks),
        rep(top_ps)).reshape(S, k, V)
    p = jax.nn.softmax(filt, axis=-1)                          # [S, k, V]
    q = q_probs.astype(jnp.float32)

    rows = jnp.arange(S)
    d = drafts.astype(jnp.int32)
    p_d = jnp.take_along_axis(p, d[..., None], axis=-1)[..., 0]  # [S, k]
    q_d = jnp.take_along_axis(q, d[..., None], axis=-1)[..., 0]

    words = sampling.key_words(rep(seeds), positions[:, :k].reshape(-1))
    u = hash_uniform(words[:, 0], words[:, 1],
                     jnp.zeros((S * k,), jnp.uint32),
                     lane=1).reshape(S, k)
    ratio = p_d / jnp.maximum(q_d, 1e-30)
    greedy_tok = jnp.argmax(logits_grid, axis=-1).astype(jnp.int32)
    sampling_row = (temps > 0)[:, None]
    accept = jnp.where(sampling_row, u <= ratio,
                       d == greedy_tok[:, :k])                 # [S, k]

    # residual resample per draft position (only position r is used);
    # p <= q everywhere (p == q) leaves no residual -> resample from p
    res = jnp.maximum(p - q, 0.0)
    has_res = jnp.sum(res, axis=-1, keepdims=True) > 1e-9
    scores = jnp.where(
        has_res, jnp.where(res > 0, jnp.log(jnp.maximum(res, 1e-30)),
                           -1e30),
        filt)
    g = gumbel(words[:, 0:1], words[:, 1:2],
               jnp.arange(V, dtype=jnp.uint32)[None, :],
               lane=2).reshape(S, k, V)
    resample = jnp.argmax(scores + g, axis=-1).astype(jnp.int32)
    resample = jnp.where(sampling_row, resample, greedy_tok[:, :k])

    # bonus token at position k: the sequential path's own draw there
    bonus = sampling.sample_tokens(
        logits_grid[:, k], seeds, positions[:, k], temps, top_ks, top_ps)

    acc_cum = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    r = jnp.sum(acc_cum, axis=1)                               # [S] in [0, k]
    n_emit = (r + 1).astype(jnp.int32)
    correction = jnp.where(
        r < k, resample[rows, jnp.clip(r, 0, k - 1)], bonus)
    out = jnp.concatenate([d, bonus[:, None]], axis=1)
    out = out.at[rows, r].set(correction)
    return out.astype(jnp.int32), n_emit


def accept_counts(targets: np.ndarray, drafts: np.ndarray) -> np.ndarray:
    """Host-side twin of the in-graph acceptance rule (the engine's
    program computes this with cumprod; tests pin the two together).
    targets: [S, k+1] the per-position sequential-path tokens; drafts:
    [S, k].  Returns [S] n_emit in [1, k+1]: the longest matched prefix
    plus the one always-emitted correction/bonus token."""
    match = targets[:, :-1] == drafts            # [S, k]
    acc = np.cumprod(match.astype(np.int64), axis=1).sum(axis=1)
    return acc + 1


# ---------------------------------------------------------------------------
# analytic roofline (bench.py detail.serving, the hardware-free pattern)
# ---------------------------------------------------------------------------

def expected_tokens_per_step(acceptance: float, k: int) -> float:
    """E[tokens emitted per verify step] under per-position acceptance
    probability `acceptance`: 1 + a + a^2 + ... + a^k (the matched
    prefix is geometric, truncated at k, plus the always-emitted
    bonus/correction token)."""
    if not 0.0 <= acceptance <= 1.0:
        raise ValueError(f"acceptance must be in [0, 1], got {acceptance}")
    if acceptance == 1.0:
        return float(k + 1)
    return (1.0 - acceptance ** (k + 1)) / (1.0 - acceptance)


def roofline_report(*, n_params: float, flops_per_token: float,
                    step_bytes: float, slots: int, k: int,
                    acceptance: float, peak_flops: float,
                    hbm_bytes_per_s: float,
                    draft_flops_per_step: float = 0.0,
                    draft_bytes_per_step: float = 0.0
                    ) -> Dict[str, float]:
    """Analytic spec-decode speedup at the roofline (hardware-free).

    A plain decode step moves `step_bytes` (params + every slot's KV)
    and computes `slots * flops_per_token`; a verify step moves the
    SAME bytes (params read once, KV read once — the k+1 queries share
    them) but computes (k+1)x the FLOPs and emits
    `expected_tokens_per_step(acceptance, k)` tokens per slot.  While
    decode is HBM-bound (it always is at serving batch sizes), the
    verify step's extra FLOPs ride under the same memory roof and the
    speedup approaches E[emit] directly.

    A MODEL drafter (HETU_TPU_SPEC_DECODE=model) is not free like the
    n-gram table: its k sequential forwards cost
    `draft_flops_per_step` / `draft_bytes_per_step` per verify step
    (the resident-int8 draft params are the bytes term).  The draft
    phase rides its own roofline and adds to the step; a drafter earns
    its keep when the acceptance gain beats its step tax."""
    e_emit = expected_tokens_per_step(acceptance, k)
    t_decode = max(slots * flops_per_token / peak_flops,
                   step_bytes / hbm_bytes_per_s)
    t_draft = max(draft_flops_per_step / peak_flops,
                  draft_bytes_per_step / hbm_bytes_per_s)
    t_verify = max(slots * (k + 1) * flops_per_token / peak_flops,
                   step_bytes / hbm_bytes_per_s) + t_draft
    base = slots / t_decode
    spec = slots * e_emit / t_verify
    rec = {
        "k": float(k),
        "acceptance": acceptance,
        "expected_tokens_per_step": round(e_emit, 4),
        "decode_step_s": t_decode,
        "verify_step_s": t_verify,
        "decode_tokens_per_s": round(base, 1),
        "spec_tokens_per_s": round(spec, 1),
        "speedup": round(spec / base, 3),
    }
    if draft_flops_per_step or draft_bytes_per_step:
        rec["draft_step_s"] = t_draft
    return rec
