"""Radix prefix cache: shared-prompt KV pages stay resident across
requests (the vLLM/SGLang automatic-prefix-caching move, TPU-shaped).

A serving fleet's traffic is dominated by shared prefixes — the system
prompt every request carries, few-shot preambles, multi-turn histories.
Today each admission re-prefills those tokens from scratch.  This module
keeps their KV pages ALIVE after the owning request finishes, indexed by
a radix tree over the token stream at PAGE granularity:

* **Tree shape.**  Each edge holds exactly ``page_size`` tokens and the
  id of the pool page caching their K/V.  Matching a new prompt walks
  the tree page-block by page-block; the matched chain IS the resident
  prefix.  Page granularity makes the tree the page table: no partial
  blocks, no splitting — an edge either matches wholly or not at all.

* **COW refcounts** (serving/kv_pool.py): every owner of a page — the
  cache itself plus each live slot sharing it — holds one reference;
  pages free only when the last owner releases them.  Shared pages are
  NEVER written by sharers: a page is cacheable only when the sequence
  has advanced past it (its content is final), and an admitting request
  writes strictly above its shared prefix.  "Copy"-on-write therefore
  degenerates to allocate-fresh-for-the-suffix — there is no device
  page copy on any path.

* **Match cap.**  A hit covers at most the page-aligned prefix of
  ``plen - 1`` tokens: at least one prompt token always prefills so the
  engine has last-position logits to emit the first token from.

* **Eviction.**  LRU over leaves (a parent's page is live context for
  every descendant, so only leaves are evictable).  ``evict()`` runs on
  demand — the scheduler calls it when an admission's reservation comes
  up short, so cached pages act as best-effort page-pool slack, never
  as a reason to queue (cache retention can never deadlock admission).

Host-side bookkeeping only: the device sees nothing but the page tables
it already reads.  Gated by ``HETU_TPU_SERVE_PREFIX_CACHE`` (registered
identity contract — the decode program is untouched either way; prefill
merely starts at the shared boundary).  See docs/serving.md.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class _Node:
    """One radix-tree node; the edge INTO it carries `block` (a
    page_size token tuple) and `page` (the pool page caching it)."""

    __slots__ = ("block", "page", "children", "parent", "last_used")

    def __init__(self, block: Optional[Tuple[int, ...]],
                 page: Optional[int], parent: Optional["_Node"]):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0.0


class RadixPrefixCache:
    """Token-prefix -> resident-page-chain index over a PagePool."""

    def __init__(self, pool, *, max_pages: int = 0):
        self.pool = pool
        self.page_size = pool.page_size
        #: cache page budget; 0 = bounded only by pool pressure (the
        #: scheduler evicts on demand when reservations come up short)
        self.max_pages = max_pages
        self.root = _Node(None, None, None)
        self._pages = 0         # pages the cache currently owns
        self._clock = 0.0       # virtual LRU clock (monotonic)
        self.hits = 0
        self.misses = 0
        self.shared_tokens_total = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # ------------------------------------------------------------ sizing
    @property
    def num_pages(self) -> int:
        return self._pages

    def _blocks(self, tokens: np.ndarray, limit: int
                ) -> List[Tuple[int, ...]]:
        """Page-granular blocks of `tokens[:limit]` (full pages only)."""
        ps = self.page_size
        n = limit // ps
        return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                for i in range(n)]

    # ------------------------------------------------------------- match
    def match(self, prompt: np.ndarray, now: float = 0.0
              ) -> Tuple[int, List[int]]:
        """Longest cached page-aligned prefix of `prompt`, capped at
        ``plen - 1`` tokens (at least one token must prefill).  Returns
        (shared_tokens, shared_pages); the pages are NOT ref'd — the
        caller (scheduler admission) increfs what it takes."""
        self._clock = max(self._clock, now)
        plen = int(len(prompt))
        node, pages = self.root, []
        for block in self._blocks(prompt, plen - 1):
            child = node.children.get(block)
            if child is None:
                break
            child.last_used = self._clock
            pages.append(child.page)
            node = child
        if pages:
            self.hits += 1
            self.shared_tokens_total += len(pages) * self.page_size
        else:
            self.misses += 1
        return len(pages) * self.page_size, pages

    # ------------------------------------------------------------ insert
    def insert(self, prompt: np.ndarray, pages: List[int],
               now: float = 0.0) -> int:
        """Index a just-prefilled prompt: walk the tree along its full
        page blocks, and for each block not yet cached take ownership
        of the request's corresponding page (incref — the request keeps
        its own reference and releases it normally on finish).  Blocks
        already cached keep the EXISTING page (the request's duplicate
        stays private to it).  Returns pages newly adopted.

        Only blocks the sequence has advanced past are insertable: the
        cap at ``plen - 1`` matches `match`, so a block is cached only
        when no live writer can ever touch it again (the COW
        invariant).  Respects ``max_pages`` by evicting LRU leaves
        first; blocks that still do not fit are simply not cached."""
        self._clock = max(self._clock, now)
        plen = int(len(prompt))
        node, adopted = self.root, 0
        for i, block in enumerate(self._blocks(prompt, plen - 1)):
            child = node.children.get(block)
            if child is None:
                if self.max_pages and self._pages >= self.max_pages:
                    if self.evict(1, protect=node) < 1:
                        break
                page = pages[i]
                self.pool.incref([page])
                child = _Node(block, page, node)
                node.children[block] = child
                self._pages += 1
                self.inserted_pages += 1
                adopted += 1
            child.last_used = self._clock
            node = child
        return adopted

    # ---------------------------------------------------------- eviction
    def _leaves(self) -> List[_Node]:
        out, stack = [], [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root and not n.children:
                out.append(n)
            stack.extend(n.children.values())
        return out

    def evict(self, n_pages: int, protect: Optional[_Node] = None, *,
              require_free: bool = False) -> int:
        """Drop up to `n_pages` LRU leaf entries, releasing the cache's
        reference on each.  Evicting a leaf may expose its parent as
        the next leaf — the loop re-walks until satisfied or the tree
        is spent.

        ``require_free=False`` (the insert-budget path) counts cache
        ENTRIES released — the goal is bounding the cache's footprint.
        ``require_free=True`` (the scheduler's page-pressure path)
        counts pages actually RETURNED to the free list, and only
        considers leaves the cache solely owns (refcount 1): evicting
        a leaf a live slot still shares frees nothing now and burns
        its future hit value for zero benefit.  Returns the count in
        the requested currency."""
        released = 0
        while released < n_pages:
            leaves = [lf for lf in self._leaves() if lf is not protect]
            if require_free:
                leaves = [lf for lf in leaves
                          if self.pool.refcount[lf.page] == 1]
            if not leaves:
                break
            victim = min(leaves, key=lambda lf: lf.last_used)
            victim.parent.children.pop(victim.block)
            free0 = self.pool.free_count
            self.pool.free([victim.page])
            self._pages -= 1
            self.evicted_pages += 1
            released += (self.pool.free_count - free0 if require_free
                         else 1)
        return released

    def clear(self):
        self.evict(self._pages + 1)

    # --------------------------------------------------------- integrity
    def owned_pages(self) -> List[int]:
        """Every page the cache holds a reference on (one entry per
        tree node) — the scheduler's `check_invariants` counts these
        against the pool refcounts."""
        out, stack = [], [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                out.append(n.page)
            stack.extend(n.children.values())
        return out

    def stats(self) -> Dict[str, float]:
        lookups = self.hits + self.misses
        return {
            "pages": self._pages,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hits / lookups if lookups else 0.0,
            "shared_tokens": self.shared_tokens_total,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }


def maybe_prefix_cache(pool) -> Optional[RadixPrefixCache]:
    """A RadixPrefixCache when HETU_TPU_SERVE_PREFIX_CACHE is set, else
    None — the engine's one gate (the maybe_tracer discipline: flag
    unset provably means zero per-admission cache work)."""
    from hetu_tpu.utils import flags
    if not flags.bool_flag("HETU_TPU_SERVE_PREFIX_CACHE"):
        return None
    return RadixPrefixCache(
        pool, max_pages=flags.int_flag("HETU_TPU_SERVE_PREFIX_PAGES"))
