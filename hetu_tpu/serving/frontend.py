"""Fault-tolerant serving frontend: a health-checked router over N
decode replicas.

One `Frontend` owns a fleet of `ServingEngine` replicas and routes every
arriving request by the replicas' own occupancy digests (queue depth +
live slots — the heartbeat the engines already expose through their
schedulers), skipping replicas that are DOWN (chaos ``engine_kill``
down-windows, `FaultPlan.engine_down`) or DRAINING (operator-initiated
`drain()`; `rejoin()` puts a replica back in rotation).

Fault handling is the engine's own failover machinery composed at fleet
scope:

* a replica death fires `engine.fail_over()` (in-flight requests
  requeue there under HETU_TPU_SERVE_RETRY, replaying token-identically
  on recovery), then the frontend DRAINS the dead replica's queue and
  re-routes every queued request to a healthy replica — queued work
  never waits out a down-window;
* fleet-wide per-tenant quotas (`TenantQuota.max_slots` counted across
  ALL replicas, not per-engine) hold over-quota arrivals in the
  frontend queue until the tenant's live count drops;
* hedged re-dispatch (HETU_TPU_SERVE_HEDGE = N router steps): a request
  stuck queued on its replica longer than the hedge patience is
  speculatively re-submitted to the next-best healthy replica.  Results
  are DEDUPED BY RID — the first replica to finish wins (``hedge_win``
  when the hedge copy beat the primary), the loser's queued copy is
  withdrawn, and a loser that already ran to completion is dropped with
  its tokens counted as discarded work (`hedge_discarded_tokens`) so
  EMITTED vs FINISHED token accounting reconciles exactly.

Routing, health, and hedging are pure host-side policy over unmodified
engines: every compiled program is the engine's own, and per-request
token streams stay byte-identical to a single-replica run (decode math
is row-independent, so batch composition never changes a stream).
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Deque, Dict, List, Optional, Sequence

from hetu_tpu.obs.metrics import get_registry
from hetu_tpu.serving.request import (Request, RequestResult, TenantQuota)
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.frontend")


@dataclasses.dataclass
class _Replica:
    """One decode replica and the frontend's view of its health."""
    engine: object
    idx: int
    down: bool = False
    draining: bool = False
    kills: int = 0

    def digest(self) -> Dict[str, object]:
        """The heartbeat/occupancy digest routing consumes (and reports
        surface): everything here is host-side scheduler state the
        engine already maintains."""
        sched = self.engine.scheduler
        return {
            "replica": self.idx,
            "alive": not self.down,
            "draining": self.draining,
            "queue_depth": sched.queue_depth,
            "occupancy": len(sched.active_slots()),
            "num_slots": sched.num_slots,
            "kills": self.kills,
        }


@dataclasses.dataclass
class _Routed:
    """Frontend bookkeeping for one in-flight request."""
    request: Request
    primary: int                 # replica idx the request routed to
    routed_step: int
    hedged_to: Optional[int] = None
    hedged_step: Optional[int] = None


class Frontend:
    """Routes requests over decode replicas; dedupes results by rid.

    ``plan`` drives chaos health: `should_kill_engine(step, rank=idx)`
    kills replica `idx` (one-shot) and `engine_down(step, rank=idx)`
    holds it out of rotation for the down-window.  With no plan the
    frontend is a plain least-loaded balancer."""

    def __init__(self, engines: Sequence, *, plan=None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 hedge_after: Optional[int] = None, registry=None):
        if not engines:
            raise ValueError("frontend needs at least one replica")
        if hedge_after is None:
            from hetu_tpu.utils import flags
            hedge_after = flags.int_flag("HETU_TPU_SERVE_HEDGE")
        if hedge_after < 0:
            raise ValueError(f"hedge_after must be >= 0, "
                             f"got {hedge_after}")
        self.replicas = [_Replica(engine=e, idx=i)
                         for i, e in enumerate(engines)]
        # stamp each replica's hop identity onto its tracer so every
        # span records which engine of the fleet emitted it — the trace
        # context (rid, attempt, tier, replica) the stitcher
        # (obs/spans.FleetTrace) keys causal edges on
        for i, r in enumerate(self.replicas):
            tr = getattr(r.engine, "tracer", None)
            if tr is not None:
                tr.tier = "decode"
                tr.replica = i
        self.plan = plan
        self.quotas = quotas or {}
        self.hedge_after = hedge_after
        self._registry = registry if registry is not None \
            else get_registry()
        self._held: Deque[Request] = collections.deque()
        self._routed: Dict[int, _Routed] = {}
        self._finished: set = set()
        self._step_idx = 0
        self.reroutes = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.hedge_withdrawn = 0
        self.hedge_dupes = 0
        self.hedge_discarded_tokens = 0
        self.quota_holds = 0
        self.steps_done = 0

    # --------------------------------------------------------- operator
    def drain(self, idx: int):
        """Take replica `idx` out of routing rotation (existing work
        finishes; nothing new lands) — the rolling-restart primitive."""
        self.replicas[idx].draining = True
        self._log(event="replica", replica=idx, state="drain")

    def rejoin(self, idx: int):
        """Put a drained (or recovered) replica back in rotation."""
        r = self.replicas[idx]
        was = "drain" if r.draining else ("down" if r.down else "live")
        r.draining = False
        r.down = False
        self._log(event="replica", replica=idx, state="rejoin",
                  was=was)

    def digests(self) -> List[Dict[str, object]]:
        return [r.digest() for r in self.replicas]

    def _log(self, **fields):
        # frontend events ride replica 0's serve-event sink: ONE RunLog
        # carries the whole fleet story for the one-reader report
        self.replicas[0].engine._log_serve(**fields)

    # ---------------------------------------------------------- routing
    def _healthy(self) -> List[_Replica]:
        return [r for r in self.replicas
                if not r.down and not r.draining]

    def _pick(self, exclude: Optional[int] = None) -> Optional[_Replica]:
        """Least-loaded healthy replica (queued + live, ties to the
        lowest idx — deterministic routing for replayable tests)."""
        best = None
        best_key = None
        for r in self._healthy():
            if r.idx == exclude:
                continue
            d = r.digest()
            key = (d["queue_depth"] + d["occupancy"], r.idx)
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _tenant_live(self, tenant: str) -> int:
        """Fleet-wide live+queued count for `tenant` — the frontend's
        admission view (its own books, no engine scan)."""
        return sum(1 for rt in self._routed.values()
                   if rt.request.tenant == tenant)

    def _over_quota(self, req: Request) -> bool:
        q = self.quotas.get(req.tenant)
        return (q is not None and q.max_slots
                and self._tenant_live(req.tenant) >= q.max_slots)

    def submit(self, req: Request, now: Optional[float] = None):
        if now is not None:
            req.arrival_t = now
        self._held.append(req)

    def _route_held(self, now: float):
        deferred: List[Request] = []
        while self._held:
            req = self._held.popleft()
            if self._over_quota(req):
                self.quota_holds += 1
                self._registry.inc("serve.frontend_quota_holds")
                deferred.append(req)
                continue
            r = self._pick()
            if r is None:           # whole fleet down/draining: hold
                deferred.append(req)
                continue
            # arrival_t is already stamped; the engine must see the
            # TRUE arrival so queue-wait accounting spans the held time
            r.engine.submit(req)
            self._routed[req.rid] = _Routed(request=req, primary=r.idx,
                                            routed_step=self._step_idx)
            self._log(event="dispatch", req=req.rid, replica=r.idx,
                      tier="decode", now=now)
        self._held.extend(deferred)

    # ------------------------------------------------------------ hedge
    def _maybe_hedge(self, now: float):
        if not self.hedge_after:
            return
        for rid, rt in self._routed.items():
            if rt.hedged_to is not None:
                continue
            if self._step_idx - rt.routed_step < self.hedge_after:
                continue
            primary = self.replicas[rt.primary]
            if primary.down:
                continue            # death handling reroutes, not hedge
            sched = primary.engine.scheduler
            if not any(q.rid == rid for q in sched.queue):
                continue            # admitted (or already finished)
            alt = self._pick(exclude=rt.primary)
            if alt is None:
                continue
            alt.engine.submit(rt.request)
            rt.hedged_to = alt.idx
            rt.hedged_step = self._step_idx
            self.hedges += 1
            self._registry.inc("serve.hedges")
            self._log(event="hedge", req=rid, primary=rt.primary,
                      hedge=alt.idx, now=now,
                      waited_steps=self._step_idx - rt.routed_step)

    def _withdraw(self, rid: int, rt: _Routed, winner: int,
                  res: RequestResult, now: float):
        """The OTHER copy of a hedged rid must not reach the client:
        withdraw it if still queued, otherwise let it finish and drop
        the duplicate result (its tokens are discarded work)."""
        loser_idx = rt.hedged_to if winner == rt.primary else rt.primary
        loser = self.replicas[loser_idx]
        if loser.engine.scheduler.drop_queued(rt.request):
            self.hedge_withdrawn += 1
            self._registry.inc("serve.hedge_withdrawn")
            # the losing copy gets its TERMINAL span so fleet-wide
            # span accounting closes over the discarded wait too
            tr = getattr(loser.engine, "tracer", None)
            if tr is not None:
                tr.on_withdraw(rt.request, now, reason="hedge_loss")
        if winner == rt.hedged_to:
            self.hedge_wins += 1
            self._registry.inc("serve.hedge_wins")
            self._log(event="hedge_win", req=rid, primary=rt.primary,
                      hedge=rt.hedged_to, now=now,
                      tokens=len(res.tokens))

    # ------------------------------------------------------------- step
    def _check_health(self, now: float):
        if self.plan is None:
            return
        for r in self.replicas:
            if self.plan.should_kill_engine(self._step_idx, rank=r.idx):
                r.kills += 1
                r.engine.fail_over(now)
                r.down = True
                self._registry.inc("serve.frontend_replica_kills")
                self._log(event="replica", replica=r.idx, state="down",
                          now=now)
                # queued work must not wait out the down-window: pull
                # the dead replica's ENTIRE queue and re-route it (the
                # requeued in-flight included — another replica replays
                # them token-identically from the prompt)
                sched = r.engine.scheduler
                tracer = getattr(r.engine, "tracer", None)
                pulled = []
                while sched.queue:
                    pulled.append(sched.queue.popleft())
                for req in pulled:
                    sched.retries.pop(req.rid, None)
                    # close the dead replica's hop with a withdrawal
                    # terminal: the rid's story continues on another
                    # replica, but THIS hop's spans must still close
                    if tracer is not None:
                        tracer.on_withdraw(req, now, reason="rerouted")
                    alt = self._pick(exclude=r.idx)
                    if alt is None:
                        self._held.append(req)
                        continue
                    alt.engine.submit(req)
                    self._log(event="dispatch", req=req.rid,
                              replica=alt.idx, tier="decode", now=now,
                              rerouted_from=r.idx)
                    rt = self._routed.get(req.rid)
                    if rt is not None:
                        rt.primary = alt.idx
                        rt.routed_step = self._step_idx
                        rt.hedged_to = None
                        rt.hedged_step = None
                    self.reroutes += 1
                    self._registry.inc("serve.frontend_reroutes")
            elif r.down and not self.plan.engine_down(self._step_idx,
                                                      rank=r.idx):
                self.rejoin(r.idx)

    def step(self, now: float) -> List[RequestResult]:
        """One router iteration: health transitions, admission routing,
        hedging, then one step of every live replica; returns the
        rid-deduped results."""
        self._check_health(now)
        self._route_held(now)
        self._maybe_hedge(now)
        out: List[RequestResult] = []
        for r in self.replicas:
            if r.down:
                continue
            for res in r.engine.step(now):
                rid = res.rid
                if rid in self._finished:
                    # the hedge loser ran to completion: duplicate
                    # result, discarded work — never reaches the client
                    self.hedge_dupes += 1
                    self.hedge_discarded_tokens += len(res.tokens)
                    self._registry.inc("serve.hedge_dupes")
                    self._registry.inc("serve.hedge_discarded_tokens",
                                       value=len(res.tokens))
                    self._log(event="hedge_dupe", req=rid,
                              replica=r.idx, now=now,
                              tokens=len(res.tokens))
                    continue
                self._finished.add(rid)
                rt = self._routed.pop(rid, None)
                if rt is not None and rt.hedged_to is not None:
                    self._withdraw(rid, rt, r.idx, res, now)
                out.append(res)
        self._step_idx += 1
        self.steps_done += 1
        return out

    # -------------------------------------------------------------- run
    @property
    def idle(self) -> bool:
        if self._held or self._routed:
            return False
        for r in self.replicas:
            sched = r.engine.scheduler
            if sched.queue or sched.active_slots() \
                    or r.engine._fault_results:
                return False
        return True

    def run(self, requests: Sequence[Request], *,
            start: float = 0.0) -> List[RequestResult]:
        """Drive the fleet over a request trace to completion (the
        engine.run contract: virtual arrivals, wall-cost clock)."""
        pending = sorted(requests, key=lambda r: (r.arrival_t, r.rid))
        now = start
        results: List[RequestResult] = []
        i = 0
        while True:
            while i < len(pending) and \
                    pending[i].arrival_t <= now + 1e-12:
                self.submit(pending[i])
                i += 1
            if self.idle:
                if i >= len(pending):
                    break
                now = max(now, pending[i].arrival_t)
                continue
            t0 = time.perf_counter()
            results.extend(self.step(now))
            now += time.perf_counter() - t0
        return sorted(results, key=lambda r: r.rid)

    def summary(self) -> Dict[str, object]:
        return {
            "replicas": self.digests(),
            "reroutes": self.reroutes,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "hedge_withdrawn": self.hedge_withdrawn,
            "hedge_dupes": self.hedge_dupes,
            "hedge_discarded_tokens": self.hedge_discarded_tokens,
            "quota_holds": self.quota_holds,
        }
