"""Serving subsystem: continuous batching + paged KV cache over the
training stack (docs/serving.md).

    from hetu_tpu import serving
    eng = serving.ServingEngine(model, params,
                                serving.ServeConfig(num_slots=8))
    results = eng.run(serving.synthetic_requests(16, vocab_size=256,
                                                 seed=0))

Deliberately NOT imported from the package root: training paths never
pay for (or lower differently because of) the serving stack — the
serving flags (HETU_TPU_KV_QUANT, HETU_TPU_SERVE_TRACE + the
serve-shape flags) are read only inside this package, so leaving them
unset cannot perturb any training program.
"""
from hetu_tpu.serving.costs import (COST_FIELDS,  # noqa: F401
                                    CostLedger, CostModel,
                                    aggregate_costs)
from hetu_tpu.serving.disagg import (DisaggCoordinator,  # noqa: F401
                                     PrefillWorker, Shipment,
                                     ShipmentChannel, pack_shipment,
                                     unpack_shipment)
from hetu_tpu.serving.engine import (ServeConfig,  # noqa: F401
                                     ServingEngine,
                                     first_token_from_logits)
from hetu_tpu.serving.frontend import Frontend  # noqa: F401
from hetu_tpu.serving.fleet import (FleetConfig,  # noqa: F401
                                    FleetSimulator, ServiceModel,
                                    analytic_models, attainment_delta,
                                    fleet_workload)
from hetu_tpu.serving.kv_pool import (PagePool,  # noqa: F401
                                      PoolArrays, kv_bytes_per_token)
from hetu_tpu.serving.prefix_cache import (RadixPrefixCache,  # noqa: F401
                                           maybe_prefix_cache)
from hetu_tpu.serving.request import (DEFAULT_SLO, GREEDY,  # noqa: F401
                                      Request, RequestResult,
                                      RequestStats, SamplingParams,
                                      SLOClass, TenantQuota,
                                      parse_quotas, rid_sampled)
from hetu_tpu.serving.reshard import LoadAdaptiveMesh  # noqa: F401
from hetu_tpu.serving.scheduler import Scheduler, SlotState  # noqa: F401
from hetu_tpu.serving.slo_report import (serving_report,  # noqa: F401
                                         render_text)
from hetu_tpu.serving.spec_decode import (CallableDrafter,  # noqa: F401
                                          Drafter, NGramDrafter,
                                          make_drafter)
from hetu_tpu.serving.traces import (bursty_arrivals,  # noqa: F401
                                     poisson_arrivals, synthetic_requests)
from hetu_tpu.serving.tracing import (RequestTracer,  # noqa: F401
                                      maybe_tracer)

__all__ = [
    "ServingEngine", "ServeConfig", "first_token_from_logits",
    "DisaggCoordinator", "PrefillWorker", "Shipment", "ShipmentChannel",
    "pack_shipment", "unpack_shipment", "Frontend",
    "FleetSimulator", "FleetConfig", "ServiceModel", "analytic_models",
    "attainment_delta", "fleet_workload",
    "CostModel", "CostLedger", "COST_FIELDS", "aggregate_costs",
    "PagePool", "PoolArrays", "kv_bytes_per_token",
    "RadixPrefixCache", "maybe_prefix_cache",
    "Request", "RequestResult", "RequestStats", "SLOClass", "DEFAULT_SLO",
    "SamplingParams", "GREEDY",
    "TenantQuota", "parse_quotas", "rid_sampled",
    "Scheduler", "SlotState",
    "LoadAdaptiveMesh",
    "Drafter", "NGramDrafter", "CallableDrafter", "make_drafter",
    "RequestTracer", "maybe_tracer",
    "serving_report", "render_text",
    "poisson_arrivals", "bursty_arrivals", "synthetic_requests",
]
