"""Synthetic arrival traces for the serving load generator.

Seeded, replayable request streams (the chaos-harness discipline applied
to load testing): Poisson arrivals for steady load, a bursty
on/off-modulated process for the spiky traffic that makes continuous
batching and the load-adaptive reshard hook earn their keep.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from hetu_tpu.serving.request import (DEFAULT_SLO, GREEDY, Request,
                                      SamplingParams, SLOClass)


def poisson_arrivals(n: int, rate_per_s: float, *, seed: int = 0
                     ) -> np.ndarray:
    """[n] arrival times of a Poisson process (exponential gaps at
    `rate_per_s`), starting at t=0."""
    if rate_per_s <= 0:
        raise ValueError("rate_per_s must be positive")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_per_s, size=n)
    gaps[0] = 0.0
    return np.cumsum(gaps)


def bursty_arrivals(n: int, rate_per_s: float, *, burst: int = 4,
                    idle_s: Optional[float] = None, seed: int = 0
                    ) -> np.ndarray:
    """[n] arrival times of an on/off burst process: groups of `burst`
    near-simultaneous requests separated by idle gaps sized so the MEAN
    rate still matches `rate_per_s` (unless `idle_s` overrides the gap).
    The worst case for a fixed-batch server; the test of token-granular
    admission."""
    if rate_per_s <= 0:
        raise ValueError("rate_per_s must be positive")
    rng = np.random.default_rng(seed)
    gap = idle_s if idle_s is not None else burst / rate_per_s
    out = np.empty(n)
    t = 0.0
    for i in range(n):
        if i and i % burst == 0:
            t += rng.exponential(gap)
        # intra-burst jitter keeps arrivals strictly ordered but tight
        out[i] = t + rng.uniform(0.0, 1e-3)
    return np.sort(out)


def synthetic_requests(n: int, *, vocab_size: int, prompt_lens=(4, 24),
                       max_new=(4, 12), eos_token_id: Optional[int] = None,
                       arrivals: Optional[np.ndarray] = None,
                       slo_classes: Optional[Sequence[SLOClass]] = None,
                       shared_prefix_len: int = 0,
                       sampling: Optional[SamplingParams] = None,
                       tenants: Optional[Sequence[str]] = None,
                       seed: int = 0) -> List[Request]:
    """n seeded requests with uniform prompt lengths / decode budgets and
    the given arrival times (default: all at t=0).  ``slo_classes``
    assigns latency classes round-robin (deterministic — request i gets
    class i % len); None keeps every request in the default class.
    ``tenants`` assigns tenant names the same way (round-robin; None =
    everyone in the default tenant — the fleet simulator's multi-tenant
    workload knob).

    ``shared_prefix_len`` prepends one seeded "system prompt" of that
    many tokens to EVERY request (the radix-prefix-cache workload;
    prompt_lens then sizes the per-request suffix).  ``sampling`` stamps
    the given SamplingParams on every request with a per-request seed
    (base seed + rid — deterministic, distinct streams)."""
    rng = np.random.default_rng(seed)
    if arrivals is None:
        arrivals = np.zeros(n)
    if len(arrivals) != n:
        raise ValueError(f"{len(arrivals)} arrival times for {n} requests")
    prefix = (rng.integers(0, vocab_size,
                           size=shared_prefix_len).astype(np.int32)
              if shared_prefix_len else None)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
        mnew = int(rng.integers(max_new[0], max_new[1] + 1))
        slo = (slo_classes[i % len(slo_classes)] if slo_classes
               else DEFAULT_SLO)
        prompt = rng.integers(0, vocab_size, size=plen).astype(np.int32)
        if prefix is not None:
            prompt = np.concatenate([prefix, prompt])
        sp = GREEDY
        if sampling is not None:
            sp = SamplingParams(temperature=sampling.temperature,
                                top_k=sampling.top_k,
                                top_p=sampling.top_p,
                                seed=sampling.seed + i)
        reqs.append(Request(
            rid=i, prompt=prompt,
            max_new_tokens=mnew, eos_token_id=eos_token_id,
            arrival_t=float(arrivals[i]), slo=slo, sampling=sp,
            tenant=(tenants[i % len(tenants)] if tenants else "default")))
    return reqs
