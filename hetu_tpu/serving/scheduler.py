"""Continuous-batching scheduler: token-granular admission into fixed
decode slots.

The decode program is one static-shape jitted step over `num_slots`
batch rows; the scheduler's job is to keep those rows full.  Sequences
are admitted the moment a slot AND their full page reservation are free
(reserve-on-admit: prompt + max_new_tokens pages up front, so a running
sequence can never hit a mid-flight out-of-pages condition), evicted the
step they finish (EOS or length budget), and their pages recycled
through the pool's free list for the next admission — requests join and
leave the batch at TOKEN boundaries, nothing waits for a "batch" to
drain (the Orca/vLLM continuous-batching policy, TPU-shaped).

All state here is host-side Python/numpy — the device only ever sees the
[slots, max_pages] int32 page table and the per-slot position vector.
`check_invariants()` is the correctness contract the fuzz test drives:
no two live slots share a page, live + free partition the pool, table
rows mirror the slots' page lists exactly.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from hetu_tpu.serving.kv_pool import PagePool
from hetu_tpu.serving.request import Request, RequestStats, TenantQuota


@dataclass
class SlotState:
    """One live decode slot.  A freshly admitted slot spends its first
    engine steps PREFILLING (one chunk per step, interleaved with the
    decode batch — the engine drives these fields); it joins the decode
    batch when the last chunk lands.  ``shared_tokens`` > 0 means the
    leading pages of ``pages`` are radix-cache pages resident from an
    earlier request (serving/prefix_cache.py) — prefill starts at that
    boundary and those pages are never written by this slot."""
    request: Request
    pages: List[int]
    pos: int                     # next cache write position (= tokens cached)
    generated: List[int] = field(default_factory=list)
    stats: RequestStats = field(default_factory=RequestStats)
    prefilling: bool = False
    prefill_cache: object = None      # scratch KV carry while prefilling
    chunks_done: int = 0
    shared_tokens: int = 0
    admit_seq: int = 0                # admission order (preemption ties)


class Scheduler:
    """Slot + page bookkeeping for the continuous-batching engine.

    ``lookahead`` (speculative decoding, serving/spec_decode.py) widens
    every page reservation by k cache positions: a verify step writes
    draft K/V up to ``pos + k``, so reserve-on-admit must cover
    ``total_len + k`` for the no-mid-flight-out-of-pages guarantee to
    keep holding.  ``prefix_cache`` (serving/prefix_cache.py) lets an
    admission start with its page-aligned shared prefix already
    resident: the reservation shrinks to the unshared suffix and the
    shared pages are ref'd, not copied."""

    def __init__(self, *, num_slots: int, pool: PagePool, max_len: int,
                 prefix_cache=None, lookahead: int = 0,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 retry_budget: int = 0):
        if max_len % pool.page_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"page_size {pool.page_size}")
        if lookahead < 0:
            raise ValueError(f"lookahead must be >= 0, got {lookahead}")
        self.num_slots = num_slots
        self.pool = pool
        self.max_len = max_len
        self.max_pages = max_len // pool.page_size
        self.prefix_cache = prefix_cache
        self.lookahead = lookahead
        #: per-tenant admission caps (HETU_TPU_SERVE_QUOTAS); tenants
        #: absent from the dict are unlimited, {} / None = quota-free
        self.quotas: Dict[str, TenantQuota] = dict(quotas or {})
        self.slots: List[Optional[SlotState]] = [None] * num_slots
        self.queue: Deque[Request] = collections.deque()
        # the device-facing view: row s = slot s's pages, null-padded
        self.page_table = np.zeros((num_slots, self.max_pages), np.int32)
        self.admitted = 0
        self.released = 0
        self.preempted = 0
        #: failover accounting (HETU_TPU_SERVE_RETRY): how many times
        #: each rid re-entered the queue after a replica loss, and the
        #: budget check_invariants() holds every rid to (0 = no budget
        #: configured — requeue_lost is then never legal)
        self.retry_budget = retry_budget
        self.retries: Dict[int, int] = {}
        self.replica_requeues = 0
        #: disaggregated-shipment dedupe (docs/serving.md): seqs whose
        #: KV shipment was already adopted (a redelivery of any of them
        #: must NOT allocate — at-least-once delivery made idempotent),
        #: the per-rid apply history for live requests (popped by
        #: `ship_forget` at finish; check_invariants holds it dup-free),
        #: and how many deliveries the dedupe gate absorbed
        self.ship_seqs: set = set()
        self.ship_applied: Dict[int, List[int]] = {}
        self.ship_dedups = 0
        self._admit_seq = 0
        # live per-tenant usage, maintained at admit/release (the quota
        # check reads these instead of rescanning the slots each time);
        # check_invariants() recomputes them from scratch
        self.tenant_slots: Dict[str, int] = {}
        self.tenant_pages: Dict[str, int] = {}
        #: why the LAST failed admission attempt stalled (the
        #: reserve-on-admit attribution the flight recorder reads):
        #: "no_slot" = every decode slot live, "no_pages" = the queue
        #: head's full reservation was short, "quota_exceeded" = the
        #: head's tenant was over its cap; None = no stall observed
        self.last_stall: Optional[str] = None

    def _reserve_tokens(self, req: Request) -> int:
        """Cache positions an admission must cover: the worst-case
        sequence plus the spec-decode write lookahead."""
        return req.total_len + self.lookahead

    # ----------------------------------------------------------- queue
    def submit(self, req: Request):
        """Queue a request.  Rejects loudly what could NEVER run (a
        permanently stalled queue must be a bug report, not a hang)."""
        if self._reserve_tokens(req) > self.max_len:
            extra = (f" + spec lookahead {self.lookahead}"
                     if self.lookahead else "")
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + "
                f"max_new {req.max_new_tokens}{extra} exceeds max_len "
                f"{self.max_len}")
        if self.pool.pages_for(self._reserve_tokens(req)) \
                > self.pool.num_pages:
            raise ValueError(
                f"request {req.rid}: needs "
                f"{self.pool.pages_for(self._reserve_tokens(req))} pages "
                f"but the pool only has {self.pool.num_pages}")
        q = self.quotas.get(req.tenant)
        if q is not None and q.max_pages and \
                self.pool.pages_for(self._reserve_tokens(req)) > q.max_pages:
            raise ValueError(
                f"request {req.rid}: tenant {req.tenant!r} quota caps "
                f"pages at {q.max_pages} but the reservation alone needs "
                f"{self.pool.pages_for(self._reserve_tokens(req))} — it "
                "could never be admitted")
        self.queue.append(req)

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    # ----------------------------------------------------------- slots
    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    @property
    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    # ------------------------------------------------------- admission
    def admit_next(self, now: float) -> Optional[Tuple[int, SlotState]]:
        """Admit the queue head if a slot and its full page reservation
        are available; FIFO — a large head request blocks the queue
        rather than starving (head-of-line policy, documented limit).

        With a prefix cache attached, the head's page-aligned cached
        prefix admits ALREADY RESIDENT: its pages are ref-shared (COW —
        never written by this slot) and only the unshared suffix is
        freshly allocated.  A short allocation first asks the cache to
        evict LRU entries — cached pages are best-effort slack, never a
        reason to queue."""
        if not self.queue:
            self.last_stall = None
            return None
        free = self.free_slots()
        if not free:
            self.last_stall = "no_slot"
            return None
        req = self.queue[0]
        if not self._quota_admits(req):
            self.last_stall = "quota_exceeded"
            return None
        shared_tokens, shared_pages = 0, []
        if self.prefix_cache is not None:
            shared_tokens, shared_pages = self.prefix_cache.match(
                req.prompt, now)
            if shared_pages:
                # take the slot's reference BEFORE any eviction can
                # run: an unpinned matched chain is itself an
                # evictable LRU leaf, and evict-then-realloc would
                # hand a matched page back as this admission's "fresh"
                # suffix page — prefix and suffix silently aliased
                # onto one physical page (caught by the regression
                # test; released below if the admission still fails)
                self.pool.incref(shared_pages)
        need = self.pool.pages_for(self._reserve_tokens(req)) \
            - len(shared_pages)
        fresh = self.pool.alloc(need)
        if fresh is None and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.pool.free_count,
                                    require_free=True)
            fresh = self.pool.alloc(need)
        if fresh is None:
            if shared_pages:
                self.pool.free(shared_pages)    # unpin the match
            self.last_stall = "no_pages"
            return None
        pages = list(shared_pages) + fresh
        self.last_stall = None
        self.queue.popleft()
        slot_idx = free[0]
        self._admit_seq += 1
        st = SlotState(request=req, pages=pages, pos=0,
                       stats=RequestStats(arrival_t=req.arrival_t,
                                          admit_t=now),
                       shared_tokens=shared_tokens,
                       admit_seq=self._admit_seq)
        st.stats.shared_prefix_tokens = shared_tokens
        self.slots[slot_idx] = st
        row = self.page_table[slot_idx]
        row[:] = PagePool.NULL_PAGE
        row[: len(pages)] = pages
        self.admitted += 1
        t = req.tenant
        self.tenant_slots[t] = self.tenant_slots.get(t, 0) + 1
        self.tenant_pages[t] = self.tenant_pages.get(t, 0) + len(pages)
        return slot_idx, st

    def admit_direct(self, req: Request,
                     now: float) -> Optional[Tuple[int, SlotState]]:
        """Admit `req` into a free slot WITHOUT it ever entering the
        FIFO queue — the disaggregated adoption path (serving/disagg.py):
        the prefill tier already computed this request's KV, so the
        decode engine admits it the moment its shipment lands instead
        of queueing it behind colocated prefills.  Same reserve-on-admit
        and quota rules as `admit_next`; no prefix-cache match (the
        shipment carries the full prompt KV).  Returns None (with
        `last_stall` set) when no slot / reservation / quota headroom —
        the caller retries next step, the shipment stays pending."""
        if self._reserve_tokens(req) > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + max_new "
                f"{req.max_new_tokens} exceeds max_len {self.max_len}")
        live = {st.request.rid for st in self.slots if st is not None}
        if req.rid in live or any(r.rid == req.rid for r in self.queue):
            raise ValueError(
                f"request {req.rid} is already live or queued — a "
                "double adoption would alias its pages")
        free = self.free_slots()
        if not free:
            self.last_stall = "no_slot"
            return None
        if not self._quota_admits(req):
            self.last_stall = "quota_exceeded"
            return None
        need = self.pool.pages_for(self._reserve_tokens(req))
        fresh = self.pool.alloc(need)
        if fresh is None and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.pool.free_count,
                                    require_free=True)
            fresh = self.pool.alloc(need)
        if fresh is None:
            self.last_stall = "no_pages"
            return None
        self.last_stall = None
        slot_idx = free[0]
        self._admit_seq += 1
        st = SlotState(request=req, pages=fresh, pos=0,
                       stats=RequestStats(arrival_t=req.arrival_t,
                                          admit_t=now),
                       admit_seq=self._admit_seq)
        self.slots[slot_idx] = st
        row = self.page_table[slot_idx]
        row[:] = PagePool.NULL_PAGE
        row[: len(fresh)] = fresh
        self.admitted += 1
        t = req.tenant
        self.tenant_slots[t] = self.tenant_slots.get(t, 0) + 1
        self.tenant_pages[t] = self.tenant_pages.get(t, 0) + len(fresh)
        return slot_idx, st

    def _quota_admits(self, req: Request) -> bool:
        """Would admitting `req` keep its tenant within quota?  Checked
        BEFORE the pool is touched, so a quota stall never pins shared
        prefix pages or triggers cache eviction."""
        q = self.quotas.get(req.tenant)
        if q is None:
            return True
        if q.max_slots and \
                self.tenant_slots.get(req.tenant, 0) + 1 > q.max_slots:
            return False
        if q.max_pages:
            need = self.pool.pages_for(self._reserve_tokens(req))
            if self.tenant_pages.get(req.tenant, 0) + need > q.max_pages:
                return False
        return True

    def release(self, slot_idx: int):
        """Evict a finished sequence: pages released (shared prefix
        pages decref — they stay resident while the radix cache or
        another slot holds them), table row re-pointed at the null page
        (the slot keeps decoding as an inactive row; its writes dump
        into page 0)."""
        st = self.slots[slot_idx]
        if st is None:
            raise ValueError(f"slot {slot_idx} is not live")
        self.pool.free(st.pages)
        self.slots[slot_idx] = None
        self.page_table[slot_idx, :] = PagePool.NULL_PAGE
        self.released += 1
        t = st.request.tenant
        self.tenant_slots[t] -= 1
        self.tenant_pages[t] -= len(st.pages)

    # ------------------------------------------------------- preemption
    def preempt_victim(self, priority: int) -> Optional[int]:
        """The slot a `priority`-class admission may evict under
        pressure (HETU_TPU_SERVE_PREEMPT): the lowest-priority live
        slot, STRICTLY below `priority` (equal classes never preempt
        each other — no thrash), youngest admission first among ties
        (least sunk prefill cost).  None = nothing preemptible."""
        live = [(st.request.slo.priority, -st.admit_seq, i)
                for i, st in enumerate(self.slots) if st is not None]
        if not live:
            return None
        prio, _, idx = min(live)
        return idx if prio < priority else None

    def preempt(self, slot_idx: int) -> Request:
        """Evict-and-requeue a live slot: pages released, the ORIGINAL
        request re-queued at the back (it re-prefills from scratch on
        re-admission — deterministic decode regenerates the same
        tokens).  Returns the requeued request."""
        st = self.slots[slot_idx]
        if st is None:
            raise ValueError(f"slot {slot_idx} is not live")
        self.release(slot_idx)
        self.released -= 1          # a preemption is not a completion
        self.preempted += 1
        self.queue.append(st.request)
        return st.request

    # -------------------------------------------------------- failover
    def requeue_lost(self, slot_idx: int) -> Request:
        """Requeue a live slot whose serving replica died (chaos
        ``engine_kill``): same mechanics as :meth:`preempt` — pages
        released, the original request re-queued at the back, a
        deterministic re-prefill/decode regenerates the same tokens —
        but billed against the per-rid retry budget
        (HETU_TPU_SERVE_RETRY).  The CALLER checks the budget before
        requeueing (past it, the request terminates instead);
        `check_invariants` then holds every count to the budget."""
        st = self.slots[slot_idx]
        if st is None:
            raise ValueError(f"slot {slot_idx} is not live")
        rid = st.request.rid
        self.release(slot_idx)
        self.released -= 1          # a failover is not a completion
        self.replica_requeues += 1
        self.retries[rid] = self.retries.get(rid, 0) + 1
        self.queue.append(st.request)
        return st.request

    # ------------------------------------------------- disagg shipments
    def apply_shipment(self, rid: int, seq: int) -> bool:
        """The at-least-once dedupe gate for a delivered KV shipment
        (serving/disagg.py): True = first delivery, the caller may
        adopt it (`admit_direct` + KV write); False = a redelivery (the
        seq was already adopted, or the rid is already live from an
        earlier attempt) — the caller MUST drop it without touching the
        pool.  Double-delivered shipments therefore can never alias
        pages: the second delivery never allocates."""
        if seq in self.ship_seqs:
            self.ship_dedups += 1
            return False
        if any(st is not None and st.request.rid == rid
               for st in self.slots):
            self.ship_dedups += 1
            return False
        self.ship_seqs.add(seq)
        self.ship_applied.setdefault(rid, []).append(seq)
        return True

    def unapply_shipment(self, rid: int, seq: int):
        """Roll back an `apply_shipment` grant whose adoption could not
        land (no slot / reservation / quota headroom): the seq is
        un-burned so the SAME delivery can retry next step without
        counting as a dedupe."""
        self.ship_seqs.discard(seq)
        seqs = self.ship_applied.get(rid)
        if seqs is not None:
            if seq in seqs:
                seqs.remove(seq)
            if not seqs:
                del self.ship_applied[rid]

    def ship_forget(self, rid: int):
        """Drop the per-rid apply history once `rid` finished (the seq
        set stays — late redeliveries of a finished request still hit
        the dedupe gate)."""
        self.ship_applied.pop(rid, None)

    def drop_queued(self, req: Request) -> bool:
        """Remove a still-queued request (a deadline expiry or a
        brownout shed terminates it without ever admitting); False when
        it is not in the queue (already admitted or never submitted)."""
        try:
            self.queue.remove(req)
        except ValueError:
            return False
        return True

    # ------------------------------------------------------ invariants
    def check_invariants(self):
        """The memory-pool correctness contract (fuzz-tested):
        * refcounts are EXACT: every live page's count equals its owner
          count (slots holding it + one per radix-cache entry) — no
          page is shared without a reference, none leaks one,
        * a page shared by two slots is legal ONLY under COW (both
          slots hold it inside their shared page-aligned prefix, below
          every write position) — without a prefix cache this reduces
          to the original no-aliasing rule,
        * live (refcount > 0) + free pages partition the pool exactly,
        * each table row mirrors its slot's page list, null-padded,
        * the null page is never owned and never free-listed,
        * every live position fits its reservation,
        * the incremental per-tenant usage counters match a fresh scan
          of the live slots, and no quota'd tenant exceeds its caps,
        * a requeued request is REALLY requeued: no rid is both queued
          and live in a slot (its pages were released before it
          re-entered the queue — the refcount-after-requeue rule, which
          the partition/refcount checks above then hold to zero leak),
        * no rid's replica-loss requeue count exceeds the configured
          retry budget (HETU_TPU_SERVE_RETRY), and with no budget
          configured no requeue ever happened,
        * no rid is live in TWO slots (a double-delivered disagg
          shipment adopted twice would put one request in two slots
          with two page sets — the aliasing the `apply_shipment`
          dedupe gate exists to prevent),
        * the shipment-dedupe books are coherent: no rid's applied-seq
          history holds a duplicate, and every applied seq is in the
          global seq set."""
        owners: Dict[int, int] = {}
        writers: Dict[int, List[int]] = {}   # slots holding p UNSHARED
        tslots: Dict[str, int] = {}
        tpages: Dict[str, int] = {}
        for i, st in enumerate(self.slots):
            if st is not None:
                t = st.request.tenant
                tslots[t] = tslots.get(t, 0) + 1
                tpages[t] = tpages.get(t, 0) + len(st.pages)
        if {k: v for k, v in self.tenant_slots.items() if v} != tslots:
            raise AssertionError(
                f"tenant slot usage {self.tenant_slots} != scan {tslots}")
        if {k: v for k, v in self.tenant_pages.items() if v} != tpages:
            raise AssertionError(
                f"tenant page usage {self.tenant_pages} != scan {tpages}")
        for t, q in self.quotas.items():
            if q.max_slots and tslots.get(t, 0) > q.max_slots:
                raise AssertionError(
                    f"tenant {t!r} holds {tslots[t]} slots over its "
                    f"quota {q.max_slots}")
            if q.max_pages and tpages.get(t, 0) > q.max_pages:
                raise AssertionError(
                    f"tenant {t!r} holds {tpages[t]} pages over its "
                    f"quota {q.max_pages}")
        for i, st in enumerate(self.slots):
            if st is None:
                if (self.page_table[i] != PagePool.NULL_PAGE).any():
                    raise AssertionError(f"empty slot {i} has a non-null "
                                         "table row")
                continue
            shared_pages = st.shared_tokens // self.pool.page_size
            for j, p in enumerate(st.pages):
                if p == PagePool.NULL_PAGE:
                    raise AssertionError(f"slot {i} owns the null page")
                owners[p] = owners.get(p, 0) + 1
                if j >= shared_pages:
                    writers.setdefault(p, []).append(i)
            row = self.page_table[i]
            want = st.pages + [PagePool.NULL_PAGE] * (self.max_pages
                                                      - len(st.pages))
            if list(row) != want:
                raise AssertionError(f"slot {i} table row {list(row)} != "
                                     f"pages {want}")
            if st.pos > len(st.pages) * self.pool.page_size:
                raise AssertionError(
                    f"slot {i} position {st.pos} beyond its "
                    f"{len(st.pages)}-page reservation")
            if st.pos > self.max_len:
                raise AssertionError(f"slot {i} position {st.pos} beyond "
                                     f"max_len {self.max_len}")
        for p, slots_w in writers.items():
            # at most one slot may hold a page outside its shared
            # prefix (the original allocator — the only legal writer);
            # two writers would be genuine cache-corrupting aliasing
            if len(slots_w) > 1:
                raise AssertionError(
                    f"page {p} aliased OUTSIDE a shared prefix by "
                    f"slots {slots_w}")
        if self.prefix_cache is not None:
            for p in self.prefix_cache.owned_pages():
                if p == PagePool.NULL_PAGE:
                    raise AssertionError("prefix cache owns the null page")
                owners[p] = owners.get(p, 0) + 1
        free = self.pool._free
        if len(set(free)) != len(free):
            raise AssertionError("duplicate pages on the free list")
        if PagePool.NULL_PAGE in free:
            raise AssertionError("null page on the free list")
        overlap = set(free) & set(owners)
        if overlap:
            raise AssertionError(f"pages both live and free: {overlap}")
        if len(owners) + len(free) != self.pool.num_pages:
            raise AssertionError(
                f"pool leak: {len(owners)} live + {len(free)} free != "
                f"{self.pool.num_pages} pages")
        for p, n in owners.items():
            if self.pool.refcount[p] != n:
                raise AssertionError(
                    f"page {p} refcount {self.pool.refcount[p]} != "
                    f"{n} owners")
        stray = [int(p) for p in range(1, self.pool.num_pages + 1)
                 if self.pool.refcount[p] > 0 and p not in owners]
        if stray:
            raise AssertionError(f"refcounted pages with no owner: {stray}")
        slot_rids = [st.request.rid for st in self.slots
                     if st is not None]
        live_rids = set(slot_rids)
        if len(slot_rids) != len(live_rids):
            dups = sorted({r for r in slot_rids
                           if slot_rids.count(r) > 1})
            raise AssertionError(
                f"requests live in TWO slots (double-adopted "
                f"shipment?): {dups}")
        both = live_rids & {r.rid for r in self.queue}
        if both:
            raise AssertionError(
                f"requests both queued and live in a slot: {sorted(both)}")
        for rid, seqs in self.ship_applied.items():
            if len(set(seqs)) != len(seqs):
                raise AssertionError(
                    f"rid {rid} adopted a shipment seq twice: {seqs}")
            missing = [s for s in seqs if s not in self.ship_seqs]
            if missing:
                raise AssertionError(
                    f"rid {rid} applied seqs {missing} missing from "
                    "the global dedupe set")
        over = {rid: n for rid, n in self.retries.items()
                if n > max(self.retry_budget, 0)}
        if over:
            raise AssertionError(
                f"replica-loss requeues over the retry budget "
                f"{self.retry_budget}: {over}")
