"""Continuous-batching scheduler: token-granular admission into fixed
decode slots.

The decode program is one static-shape jitted step over `num_slots`
batch rows; the scheduler's job is to keep those rows full.  Sequences
are admitted the moment a slot AND their full page reservation are free
(reserve-on-admit: prompt + max_new_tokens pages up front, so a running
sequence can never hit a mid-flight out-of-pages condition), evicted the
step they finish (EOS or length budget), and their pages recycled
through the pool's free list for the next admission — requests join and
leave the batch at TOKEN boundaries, nothing waits for a "batch" to
drain (the Orca/vLLM continuous-batching policy, TPU-shaped).

All state here is host-side Python/numpy — the device only ever sees the
[slots, max_pages] int32 page table and the per-slot position vector.
`check_invariants()` is the correctness contract the fuzz test drives:
no two live slots share a page, live + free partition the pool, table
rows mirror the slots' page lists exactly.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from hetu_tpu.serving.kv_pool import PagePool
from hetu_tpu.serving.request import Request, RequestStats


@dataclass
class SlotState:
    """One live decode slot.  A freshly admitted slot spends its first
    engine steps PREFILLING (one chunk per step, interleaved with the
    decode batch — the engine drives these fields); it joins the decode
    batch when the last chunk lands."""
    request: Request
    pages: List[int]
    pos: int                     # next cache write position (= tokens cached)
    generated: List[int] = field(default_factory=list)
    stats: RequestStats = field(default_factory=RequestStats)
    prefilling: bool = False
    prefill_cache: object = None      # scratch KV carry while prefilling
    chunks_done: int = 0


class Scheduler:
    """Slot + page bookkeeping for the continuous-batching engine."""

    def __init__(self, *, num_slots: int, pool: PagePool, max_len: int):
        if max_len % pool.page_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"page_size {pool.page_size}")
        self.num_slots = num_slots
        self.pool = pool
        self.max_len = max_len
        self.max_pages = max_len // pool.page_size
        self.slots: List[Optional[SlotState]] = [None] * num_slots
        self.queue: Deque[Request] = collections.deque()
        # the device-facing view: row s = slot s's pages, null-padded
        self.page_table = np.zeros((num_slots, self.max_pages), np.int32)
        self.admitted = 0
        self.released = 0
        #: why the LAST failed admission attempt stalled (the
        #: reserve-on-admit attribution the flight recorder reads):
        #: "no_slot" = every decode slot live, "no_pages" = the queue
        #: head's full reservation was short; None = no stall observed
        self.last_stall: Optional[str] = None

    # ----------------------------------------------------------- queue
    def submit(self, req: Request):
        """Queue a request.  Rejects loudly what could NEVER run (a
        permanently stalled queue must be a bug report, not a hang)."""
        if req.total_len > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + "
                f"max_new {req.max_new_tokens} exceeds max_len "
                f"{self.max_len}")
        if self.pool.pages_for(req.total_len) > self.pool.num_pages:
            raise ValueError(
                f"request {req.rid}: needs "
                f"{self.pool.pages_for(req.total_len)} pages but the pool "
                f"only has {self.pool.num_pages}")
        self.queue.append(req)

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    # ----------------------------------------------------------- slots
    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    @property
    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    # ------------------------------------------------------- admission
    def admit_next(self, now: float) -> Optional[Tuple[int, SlotState]]:
        """Admit the queue head if a slot and its full page reservation
        are available; FIFO — a large head request blocks the queue
        rather than starving (head-of-line policy, documented limit)."""
        if not self.queue:
            self.last_stall = None
            return None
        free = self.free_slots()
        if not free:
            self.last_stall = "no_slot"
            return None
        req = self.queue[0]
        pages = self.pool.alloc(self.pool.pages_for(req.total_len))
        if pages is None:
            self.last_stall = "no_pages"
            return None
        self.last_stall = None
        self.queue.popleft()
        slot_idx = free[0]
        st = SlotState(request=req, pages=pages, pos=0,
                       stats=RequestStats(arrival_t=req.arrival_t,
                                          admit_t=now))
        self.slots[slot_idx] = st
        row = self.page_table[slot_idx]
        row[:] = PagePool.NULL_PAGE
        row[: len(pages)] = pages
        self.admitted += 1
        return slot_idx, st

    def release(self, slot_idx: int):
        """Evict a finished sequence: pages back on the free list, table
        row re-pointed at the null page (the slot keeps decoding as an
        inactive row; its writes dump into page 0)."""
        st = self.slots[slot_idx]
        if st is None:
            raise ValueError(f"slot {slot_idx} is not live")
        self.pool.free(st.pages)
        self.slots[slot_idx] = None
        self.page_table[slot_idx, :] = PagePool.NULL_PAGE
        self.released += 1

    # ------------------------------------------------------ invariants
    def check_invariants(self):
        """The memory-pool correctness contract (fuzz-tested):
        * no page is owned by two live slots (aliasing),
        * live pages + free pages partition the pool exactly,
        * each table row mirrors its slot's page list, null-padded,
        * the null page is never owned and never free-listed,
        * every live position fits its reservation."""
        seen: Dict[int, int] = {}
        for i, st in enumerate(self.slots):
            if st is None:
                if (self.page_table[i] != PagePool.NULL_PAGE).any():
                    raise AssertionError(f"empty slot {i} has a non-null "
                                         "table row")
                continue
            for p in st.pages:
                if p == PagePool.NULL_PAGE:
                    raise AssertionError(f"slot {i} owns the null page")
                if p in seen:
                    raise AssertionError(
                        f"page {p} aliased by slots {seen[p]} and {i}")
                seen[p] = i
            row = self.page_table[i]
            want = st.pages + [PagePool.NULL_PAGE] * (self.max_pages
                                                      - len(st.pages))
            if list(row) != want:
                raise AssertionError(f"slot {i} table row {list(row)} != "
                                     f"pages {want}")
            if st.pos > len(st.pages) * self.pool.page_size:
                raise AssertionError(
                    f"slot {i} position {st.pos} beyond its "
                    f"{len(st.pages)}-page reservation")
            if st.pos > self.max_len:
                raise AssertionError(f"slot {i} position {st.pos} beyond "
                                     f"max_len {self.max_len}")
        free = self.pool._free
        if len(set(free)) != len(free):
            raise AssertionError("duplicate pages on the free list")
        if PagePool.NULL_PAGE in free:
            raise AssertionError("null page on the free list")
        overlap = set(free) & set(seen)
        if overlap:
            raise AssertionError(f"pages both live and free: {overlap}")
        if len(seen) + len(free) != self.pool.num_pages:
            raise AssertionError(
                f"pool leak: {len(seen)} live + {len(free)} free != "
                f"{self.pool.num_pages} pages")
