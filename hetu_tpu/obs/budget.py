"""Declared perf budgets + the regression sentinel's diff logic.

Every BENCH round and every compile leaves hardware-free perf numbers
(estimated step time, bytes-on-wire, peak HBM, estimated MFU — obs.mfu /
obs.comm / obs.hlo_profile).  Nothing watched the trajectory: a PR that
quietly regressed predicted step time 10% shipped unless a human diffed
the JSON.  This module is the watcher:

* `PerfBudget` — declared ceilings (absolute: max step time / comm
  bytes / peak HBM, min MFU) and relative regression thresholds for
  round-over-round diffs, loaded from a JSON file via `HETU_TPU_BUDGETS`
  (or defaults: +5% step time, +10% comm bytes, +10% peak HBM, -5% MFU).
* `extract_metrics` — ONE reader for every record shape the repo
  produces: driver-wrapped BENCH_r*.json, raw bench metric lines,
  RunLog `compile`/`profile` records, plain dicts.
* `check_absolute` / `diff_metrics` — breach lists; `enforce` raises
  `BudgetError` (the "fails loudly" contract) when a budget declares
  `"enforce": true`.

Consumers: `tools_bench_diff.py` (the CLI sentinel — exits nonzero on a
breach; wire it between BENCH rounds), the Trainer compile hook (a
`budget` RunLog event + `budget.breaches` counter per offending
compile), and `tools_obs_report.py`'s profile section (pass/fail
summary).  docs/observability.md has the walkthrough.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

#: the comparable metric keys, and which direction is "worse"
#: (True = larger is worse; False = smaller is worse)
METRIC_DIRECTION = {
    "step_time_s": True,
    "comm_bytes": True,
    "peak_hbm_bytes": True,
    "estimated_mfu": False,
    "mfu": False,
}

#: default relative regression thresholds for round-over-round diffs
DEFAULT_THRESHOLDS = {
    "step_time_s": 0.05,
    "comm_bytes": 0.10,
    "peak_hbm_bytes": 0.10,
    "estimated_mfu": 0.05,
    "mfu": 0.05,
}


class BudgetError(RuntimeError):
    """A declared perf budget was breached (and enforcement is on)."""


@dataclasses.dataclass
class PerfBudget:
    """Declared perf ceilings + regression thresholds.

    Absolute ceilings (None = unchecked) apply to a single record;
    `thresholds` are max relative regressions for diffs between two
    records (fractions: 0.05 = 5%).  `enforce=True` makes `enforce()`
    raise instead of just reporting — the trainer keeps it off by
    default so a budget file can observe before it gates."""
    max_step_time_s: Optional[float] = None
    max_comm_bytes: Optional[float] = None
    max_peak_hbm_bytes: Optional[float] = None
    min_estimated_mfu: Optional[float] = None
    thresholds: Dict[str, float] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_THRESHOLDS))
    enforce: bool = False
    source: str = "<defaults>"

    _ABS_KEYS = ("max_step_time_s", "max_comm_bytes",
                 "max_peak_hbm_bytes", "min_estimated_mfu")

    @staticmethod
    def load(path: Optional[str] = None) -> "PerfBudget":
        """Resolve the active budget: explicit `path` ->
        `HETU_TPU_BUDGETS` env -> built-in defaults (no absolute
        ceilings, default thresholds).  A file that opens but fails to
        parse or carries unknown keys raises loudly — a typo'd budget
        must not silently watch nothing."""
        from hetu_tpu.utils import flags
        path = path or flags.str_flag("HETU_TPU_BUDGETS")
        if not path:
            return PerfBudget()
        with open(path) as f:
            try:
                raw = json.load(f)
            except ValueError as e:
                raise ValueError(
                    f"invalid budget file ({path}): not valid JSON: {e}"
                ) from None
        if not isinstance(raw, dict):
            raise ValueError(f"invalid budget file ({path}): expected a "
                             f"JSON object, got {type(raw).__name__}")
        known = set(PerfBudget._ABS_KEYS) | {"thresholds", "enforce"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"invalid budget file ({path}): unknown keys "
                f"{sorted(unknown)}; known: {sorted(known)}")
        thresholds = dict(DEFAULT_THRESHOLDS)
        for k, v in (raw.get("thresholds") or {}).items():
            if k not in METRIC_DIRECTION:
                raise ValueError(
                    f"invalid budget file ({path}): unknown threshold "
                    f"{k!r}; known: {sorted(METRIC_DIRECTION)}")
            thresholds[k] = float(v)
        kw = {k: (float(raw[k]) if raw.get(k) is not None else None)
              for k in PerfBudget._ABS_KEYS if k in raw}
        return PerfBudget(thresholds=thresholds,
                          enforce=bool(raw.get("enforce", False)),
                          source=path, **kw)


# ---------------------------------------------------------------------------
# record readers
# ---------------------------------------------------------------------------

def _bench_metric_record(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Unwrap a driver-captured BENCH_r*.json ({"cmd", "rc", "tail",
    "parsed"?}) into the inner {"metric", "value", "detail"} record;
    raw metric records pass through."""
    if "metric" in rec and "value" in rec:
        return rec
    if isinstance(rec.get("parsed"), dict) and "value" in rec["parsed"]:
        return rec["parsed"]
    tail = rec.get("tail")
    if isinstance(tail, str):
        lines = [ln for ln in tail.splitlines()
                 if ln.startswith('{"metric"')]
        if lines:
            try:
                return json.loads(lines[-1])
            except ValueError:
                return None
    return None


def extract_metrics(rec: Dict[str, Any]) -> Dict[str, float]:
    """The comparable metrics of one record, whatever its shape:

    * BENCH records (driver-wrapped or raw): measured `mfu` (value>0),
      `estimated_mfu`, `step_time_s` (measured, else predicted, else
      the analytic estimate), `comm_bytes` (comm_bytes_per_step),
      `peak_hbm_bytes` (detail.profile).
    * RunLog `profile` records (obs.hlo_profile.profile_record):
      estimated step time / wire bytes / peak HBM.
    * RunLog `compile` records: estimated_mfu / estimated_step_s /
      comm_bytes.
    * plain dicts already keyed by metric names pass through.

    Missing fields are simply absent — the diff skips what it cannot
    compare (and says so)."""
    out: Dict[str, float] = {}

    def put(key, val):
        if val is not None:
            try:
                v = float(val)
            except (TypeError, ValueError):
                return
            if v == v:  # not NaN
                out[key] = v

    kind = rec.get("kind")
    if kind == "profile" or "profile_schema" in rec:
        put("step_time_s", rec.get("estimated_step_s"))
        put("comm_bytes", rec.get("total_wire_bytes"))
        put("peak_hbm_bytes", rec.get("peak_hbm_bytes"))
        put("estimated_mfu", rec.get("estimated_mfu"))
        return out
    if kind == "compile":
        put("estimated_mfu", rec.get("estimated_mfu"))
        put("step_time_s", rec.get("estimated_step_s"))
        put("comm_bytes", rec.get("comm_bytes"))
        return out

    m = _bench_metric_record(rec)
    if m is not None:
        if m.get("value"):
            put("mfu", m["value"])
        detail = m.get("detail") or {}
        put("estimated_mfu", detail.get("estimated_mfu"))
        est = detail.get("estimate") or {}
        put("step_time_s",
            detail.get("step_time_s") or detail.get("predicted_step_s")
            or est.get("estimated_step_s"))
        put("comm_bytes", detail.get("comm_bytes_per_step"))
        prof = detail.get("profile") or {}
        put("peak_hbm_bytes", prof.get("peak_hbm_bytes"))
        return out

    # plain dict keyed by metric names
    for k in METRIC_DIRECTION:
        put(k, rec.get(k))
    return out


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

#: (metric key, PerfBudget attribute, "max"|"min") — ONE ceilings table
#: shared by check_absolute and callers that report declared-but-
#: uncheckable ceilings (the trainer's budget warning); adding a
#: budgeted metric here reaches both
ABSOLUTE_CEILINGS = (
    ("step_time_s", "max_step_time_s", "max"),
    ("comm_bytes", "max_comm_bytes", "max"),
    ("peak_hbm_bytes", "max_peak_hbm_bytes", "max"),
    ("estimated_mfu", "min_estimated_mfu", "min"),
)


def check_absolute(metrics: Dict[str, float], budget: PerfBudget
                   ) -> List[Dict[str, Any]]:
    """Breaches of the budget's absolute ceilings in one record's
    metrics.  Each breach: {"metric", "value", "budget", "kind"}."""
    breaches = []
    for key, attr, kind in ABSOLUTE_CEILINGS:
        limit = getattr(budget, attr)
        if limit is None or key not in metrics:
            continue
        v = metrics[key]
        if (kind == "max" and v > limit) or (kind == "min" and v < limit):
            breaches.append({"metric": key, "value": v, "budget": limit,
                             "kind": f"absolute_{kind}"})
    return breaches


def diff_metrics(old: Dict[str, float], new: Dict[str, float],
                 budget: Optional[PerfBudget] = None) -> Dict[str, Any]:
    """Round-over-round regression check.  Returns {"deltas": {metric:
    {"old", "new", "rel"}}, "breaches": [...], "compared": [metrics],
    "skipped": [metrics present on only one side]}.  A metric breaches
    when it moved in its WORSE direction by more than the budget's
    relative threshold."""
    budget = budget or PerfBudget()
    deltas: Dict[str, Any] = {}
    breaches: List[Dict[str, Any]] = []
    compared, skipped = [], []
    for key, larger_is_worse in METRIC_DIRECTION.items():
        o, n = old.get(key), new.get(key)
        if o is None and n is None:
            continue
        if o is None or n is None or o == 0:
            skipped.append(key)
            continue
        rel = (n - o) / abs(o)
        deltas[key] = {"old": o, "new": n, "rel": rel}
        compared.append(key)
        thr = budget.thresholds.get(key, DEFAULT_THRESHOLDS.get(key, 0.1))
        worse = rel > thr if larger_is_worse else rel < -thr
        if worse:
            breaches.append({"metric": key, "old": o, "new": n,
                             "rel": rel, "threshold": thr,
                             "kind": "regression"})
    return {"deltas": deltas, "breaches": breaches,
            "compared": compared, "skipped": skipped}


def enforce(breaches: List[Dict[str, Any]],
            budget: Optional[PerfBudget] = None) -> None:
    """Fail loudly: raise BudgetError when there are breaches and the
    budget declares `enforce`; otherwise return (callers report)."""
    if breaches and budget is not None and budget.enforce:
        raise BudgetError(
            f"perf budget breached ({budget.source}): "
            + "; ".join(f"{b['metric']} {b.get('kind')} "
                        f"value={b.get('new', b.get('value'))}"
                        for b in breaches))


def summarize_breaches(breaches: List[Dict[str, Any]]) -> str:
    """One human line per breach (the sentinel's stderr report)."""
    lines = []
    for b in breaches:
        if b.get("kind") == "regression":
            lines.append(
                f"REGRESSION {b['metric']}: {b['old']:.6g} -> "
                f"{b['new']:.6g} ({b['rel']:+.1%}, threshold "
                f"{b['threshold']:.0%})")
        else:
            lines.append(
                f"BUDGET {b['metric']}: value {b['value']:.6g} vs "
                f"declared {b['budget']:.6g} ({b['kind']})")
    return "\n".join(lines)
