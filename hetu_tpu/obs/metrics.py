"""Process-local metrics registry.

Rebuild of the reference's profiler cost records as an always-on surface
(reference: hetu/impl/profiler/profiler.h:25 per-op cost records,
SURVEY §5.1 HETU_EVENT_TIMING) — but instead of env-gated log lines, a
typed registry the whole runtime writes into and any exit point (trainer
close, bench, tools_obs_report) can snapshot:

    reg = get_registry()
    reg.inc("elastic.replans")
    reg.set_gauge("rpc.worker_last_seen_s", 0.0, rank=3)
    reg.observe("trainer.step_time_s", 0.412)

Counters are monotonic, gauges are last-write-wins, histograms keep a
bounded reservoir and report count/sum/min/max/percentiles.  Every series
is keyed by (name, sorted label items) so per-rank / per-strategy series
coexist under one name.  All operations are thread-safe: the rpc server's
connection threads and the trainer loop write concurrently.
"""
from __future__ import annotations

import json
import math
import random
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def percentile_of_sorted(sorted_vals: List[float],
                         p: float) -> Optional[float]:
    """Nearest-rank percentile (p in [0, 100]) over an ascending list —
    THE percentile definition every obs surface shares (Histogram,
    cluster aggregation), so worker-side and cluster-side p50/p95 can
    never diverge on rounding semantics."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class Histogram:
    """Bounded-reservoir timing histogram.

    Keeps the first `cap` observations verbatim plus running count/sum/
    min/max for everything; past the cap, the reservoir is maintained by
    uniform sampling (Vitter's Algorithm R): observation number k > cap
    replaces a random slot with probability cap/k, so the sample stays a
    uniform draw over the WHOLE run, not a sliding window of the tail —
    whole-run percentiles over 10^6 observations still see early-run
    outliers.  The RNG is seeded per-histogram (deterministic; the
    unseeded-rng lint and the golden tests both rely on that)."""

    __slots__ = ("cap", "count", "total", "vmin", "vmax", "_sample", "_rng",
                 "nonfinite")

    def __init__(self, cap: int = 2048, seed: int = 0):
        self.cap = cap
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._sample: List[float] = []
        self._rng = random.Random(seed)
        self.nonfinite = 0

    def observe(self, value: float):
        v = float(value)
        if not math.isfinite(v):
            # a single NaN/inf observation must not poison the running
            # sum/min/max or the reservoir percentiles (one poisoned
            # export would blind every downstream consumer) — count it
            # separately and keep the finite statistics exact
            self.nonfinite += 1
            return
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        if len(self._sample) < self.cap:
            self._sample.append(v)
        else:
            # Algorithm R: keep this value with probability cap/count,
            # evicting a uniformly random resident
            j = self._rng.randrange(self.count)
            if j < self.cap:
                self._sample[j] = v

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] over the reservoir (exact until `cap` samples)."""
        return percentile_of_sorted(sorted(self._sample), p)

    def summary(self) -> Dict[str, Any]:
        out = {"count": self.count, "sum": self.total,
               "min": self.vmin, "max": self.vmax,
               "mean": (self.total / self.count) if self.count else None}
        for p in (50, 95, 99):
            out[f"p{p}"] = self.percentile(p)
        if self.nonfinite:
            # only surfaced when present so existing summary consumers
            # see an unchanged shape on healthy histograms
            out["nonfinite"] = self.nonfinite
        return out


class MetricsRegistry:
    """One process-local registry of counters/gauges/histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], float] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], float] = {}
        self._hists: Dict[Tuple[str, _LabelKey], Histogram] = {}

    # ------------------------------------------------------------- write
    def inc(self, name: str, value: float = 1.0, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
            h.observe(value)

    def timer(self, name: str, **labels):
        """Context manager observing wall seconds into histogram `name`."""
        return _Timer(self, name, labels)

    # -------------------------------------------------------------- read
    def counter_value(self, name: str, **labels) -> float:
        return self._counters.get((name, _label_key(labels)), 0.0)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        return self._gauges.get((name, _label_key(labels)))

    def histogram(self, name: str, **labels) -> Optional[Histogram]:
        return self._hists.get((name, _label_key(labels)))

    def snapshot(self) -> Dict[str, Any]:
        """{'counters': [...], 'gauges': [...], 'histograms': [...]} with
        each series as {'name', 'labels', ...} — JSON-serializable."""
        with self._lock:
            counters = [{"name": n, "labels": dict(lk), "value": v}
                        for (n, lk), v in sorted(self._counters.items())]
            gauges = [{"name": n, "labels": dict(lk), "value": v}
                      for (n, lk), v in sorted(self._gauges.items())]
            hists = [dict({"name": n, "labels": dict(lk)}, **h.summary())
                     for (n, lk), h in sorted(self._hists.items())]
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    def export_jsonl(self, path: str):
        """One JSONL line per series (kind-tagged) — greppable, appendable."""
        snap = self.snapshot()
        with open(path, "w") as f:
            for kind in ("counters", "gauges", "histograms"):
                for rec in snap[kind]:
                    f.write(json.dumps(dict(rec, kind=kind[:-1])) + "\n")

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class _Timer:
    def __init__(self, reg: MetricsRegistry, name: str, labels: Dict):
        self.reg, self.name, self.labels = reg, name, labels
        self.elapsed: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        self.reg.observe(self.name, self.elapsed, **self.labels)
        return False


_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global default registry (what the trainer/rpc/elastic
    layers write into unless handed an explicit one)."""
    return _default
