"""Unified telemetry: metrics registry, structured run events, Chrome-trace
timelines, the hardware-free MFU/roofline reporter, the bytes-on-wire
collective analyzer, the per-layer analytic step profiler (+ peak-HBM
and perf budgets), cluster-scope aggregation, and the training health
monitor.

One import surface:

    from hetu_tpu import obs
    obs.get_registry().inc("elastic.replans")
    log = obs.RunLog("/ckpts/runlog.jsonl"); log.step(1, 0.42, loss=2.3)
    obs.pipeline_schedule_trace(4, 8, schedule="1f1b").save("sched.json")
    obs.estimate_from_compiled(compiled)["estimated_mfu"]
    obs.collective_report(compiled)["total_wire_bytes"]
    obs.layer_profile(compiled)["top"]               # per-layer roofline
    obs.peak_hbm_estimate(compiled)["peak_bytes"]    # liveness peak HBM
    obs.diff_metrics(old, new, obs.PerfBudget.load())["breaches"]
    obs.straggler_report(snapshot)["stragglers"]     # cluster scope
    obs.HealthMonitor(runlog=log).observe_step(1, 0.42, loss=2.3)
    obs.summarize_numerics(obs.RunLog.read(path))["worst"]  # numerics

See docs/observability.md for the env flags, the RunLog schema, the
telemetry-push wire format and the ClusterSnapshot fields;
docs/comm_compression.md for the collective analyzer's wire-byte model.
"""
from hetu_tpu.obs.aggregate import (ClusterAggregator,  # noqa: F401
                                    ClusterSnapshot, TelemetryPusher,
                                    TelemetrySource, merge_offsets,
                                    snapshot_straggler_hook,
                                    straggler_report)
from hetu_tpu.obs.budget import (BudgetError, PerfBudget,  # noqa: F401
                                 check_absolute, diff_metrics,
                                 extract_metrics)
from hetu_tpu.obs.comm import (collective_report,  # noqa: F401
                               collective_table)
from hetu_tpu.obs.hlo_profile import (PROFILE_SCHEMA,  # noqa: F401
                                      analytic_peak_hbm, flame_trace,
                                      layer_profile, layer_table,
                                      peak_hbm_estimate, profile_record)
from hetu_tpu.obs.health import (HealthMonitor,  # noqa: F401
                                 NumericsHealthMonitor,
                                 ServingHealthMonitor,
                                 maybe_health_monitor,
                                 maybe_numerics_health_monitor,
                                 maybe_serving_health_monitor)
from hetu_tpu.obs.numerics import (NUMERICS_SCHEMA,  # noqa: F401
                                   summarize_numerics, tree_stats)
from hetu_tpu.obs.metrics import (Histogram, MetricsRegistry,  # noqa: F401
                                  get_registry)
from hetu_tpu.obs.mfu import (analytic_transformer_estimate,  # noqa: F401
                              estimate_from_compiled, estimate_mfu,
                              flops_of_compiled, load_hardware_profile)
from hetu_tpu.obs.runlog import (SCHEMA_VERSION, RunLog,  # noqa: F401
                                 default_runlog_path)
from hetu_tpu.obs.spans import (SPAN_SCHEMA, RequestTrace,  # noqa: F401
                                Span, collect_traces)
from hetu_tpu.obs.trace import (ChromeTrace,  # noqa: F401
                                merge_runlogs, numerics_trace,
                                pipeline_schedule_trace,
                                schedule_bubble_fraction, serving_trace,
                                trace_from_runlog)

__all__ = [
    "MetricsRegistry", "Histogram", "get_registry",
    "RunLog", "SCHEMA_VERSION", "default_runlog_path",
    "ChromeTrace", "pipeline_schedule_trace", "schedule_bubble_fraction",
    "trace_from_runlog", "merge_runlogs", "serving_trace",
    "Span", "RequestTrace", "collect_traces", "SPAN_SCHEMA",
    "estimate_mfu", "estimate_from_compiled", "flops_of_compiled",
    "analytic_transformer_estimate", "load_hardware_profile",
    "collective_report", "collective_table",
    "layer_table", "layer_profile", "peak_hbm_estimate",
    "analytic_peak_hbm", "profile_record", "flame_trace",
    "PROFILE_SCHEMA",
    "PerfBudget", "BudgetError", "check_absolute", "diff_metrics",
    "extract_metrics",
    "ClusterAggregator", "ClusterSnapshot", "TelemetrySource",
    "TelemetryPusher", "straggler_report", "snapshot_straggler_hook",
    "merge_offsets",
    "HealthMonitor", "maybe_health_monitor",
    "ServingHealthMonitor", "maybe_serving_health_monitor",
    "NumericsHealthMonitor", "maybe_numerics_health_monitor",
    "NUMERICS_SCHEMA", "summarize_numerics", "tree_stats",
    "numerics_trace",
]
