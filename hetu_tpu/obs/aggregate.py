"""Cluster-scope telemetry aggregation.

PR 1's telemetry is strictly process-local: the coordination server only
ever sees heartbeat gaps, never step times, losses, MFU or comm bytes —
so nothing online can feed the hetero-planner the load signals it needs
(SURVEY §5.1/§5.6; Hetis closes the same gap with live cluster state,
Galvatron fills it offline with profiling).  This module closes it:

* ``TelemetrySource`` — worker side.  Builds **delta-encoded** snapshots
  of the process-local metrics registry (counters ship as deltas since
  the last delivered push; gauges last-write-wins) plus the recent step
  records and RunLog tail events accumulated since the last push.  Every
  payload carries a ``(boot, seq)`` identity so the server can fold
  retried/duplicated deliveries exactly once — a reconnecting client may
  re-send a push, a restarted worker starts a fresh ``boot``.
* ``TelemetryPusher`` — the periodic worker loop: every
  ``HETU_TPU_TELEMETRY_PUSH`` seconds it ships the next payload through
  ``CoordinationClient.telemetry_push``.  A failed delivery is held
  pending and re-sent with its original (boot, seq) identity, so an
  applied-but-unacked push dedupes server-side instead of
  double-counting, and no counts are ever lost.
* ``ClusterAggregator`` — server side.  Folds pushes into per-worker
  state with monotonic-counter delta merging (restarts/reattaches never
  double-count) and renders the time-windowed ``ClusterSnapshot``:
  per-worker step rate, step-time percentiles, loss, estimated MFU,
  comm bytes, heartbeat gap, clock offset.
* ``straggler_report`` — robust per-worker step-time ratios/z-scores
  over the window (leave-one-out median/MAD, so one slow worker cannot
  hide inside its own baseline), exposed as ``cluster.straggler_*``
  gauges plus a ``straggler`` RunLog event on flag transitions.  The
  elastic controller consumes the report via a pluggable hook
  (``snapshot_straggler_hook``) so a persistent straggler can trigger
  the existing replan path within a budget (default off).

Everything is gated by ``HETU_TPU_TELEMETRY_PUSH`` (unset = no push op
ever hits the wire) and deterministic on CPU — the chaos harness drives
the acceptance test.  See docs/observability.md for the wire format and
the ClusterSnapshot field reference.
"""
from __future__ import annotations

import itertools
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from hetu_tpu.utils.logging import get_logger

logger = get_logger("obs.aggregate")

#: RunLog kinds that ride the telemetry push as EVENTS.  Deliberately
#: excludes high-rate kinds whose signal already travels another way —
#: ``step`` (the dedicated steps channel + registry series), ``span``
#: (per-request records stay local; serving workers ship serve events
#: + serve.* counter deltas), ``numerics`` (the per-scope numerics.*
#: gauges) — pushing those verbatim would multiply the wire cost for
#: data the coordinator already has.  ``scaler`` transitions are rare
#: and rich, so they ride.
EVENT_KINDS = ("compile", "anomaly", "straggler", "fault", "elastic_epoch",
               "switch", "serve", "scaler")

_boot_counter = itertools.count()


def _default_registry():
    from hetu_tpu.obs.metrics import get_registry
    return get_registry()


def flat_series(name: str, labels: Dict[str, Any]) -> str:
    """One stable string key per (name, labels) series — the wire form of
    the registry's tuple keys (``rpc.op_retries{op=put}``)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def push_interval() -> float:
    """Seconds between telemetry pushes from HETU_TPU_TELEMETRY_PUSH
    (0.0 = telemetry push disabled — the default)."""
    from hetu_tpu.utils import flags
    raw = flags.str_flag("HETU_TPU_TELEMETRY_PUSH").strip()
    if not raw:
        return 0.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"HETU_TPU_TELEMETRY_PUSH={raw!r} is not a push interval in "
            "seconds (e.g. '2.0'; unset/empty = off)") from None
    return max(v, 0.0)


def _percentile(sorted_vals: List[float], p: float) -> Optional[float]:
    from hetu_tpu.obs.metrics import percentile_of_sorted
    return percentile_of_sorted(sorted_vals, p)


def _median(vals: List[float]) -> Optional[float]:
    return _percentile(sorted(vals), 50) if vals else None


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

class TelemetrySource:
    """Worker-side delta encoder for the ``telemetry_push`` payload.

    Counters are delta-encoded against the last *built* payload: the
    registry's values at construction are the baseline (a source built in
    a long-lived process must not re-ship history).  The TelemetryPusher
    re-sends a failed payload with its original (boot, seq) identity, so
    deltas are never lost or double-shipped; :meth:`unpush` exists for
    manual senders that abandon a payload instead.  Step records arrive
    via :meth:`note_step` (the elastic loop measures wall time around
    ``train_step``); RunLog tail events drain from ``runlog_fn()`` at
    payload-build time.
    """

    def __init__(self, worker: int, registry=None,
                 runlog_fn: Optional[Callable[[], Any]] = None,
                 max_steps: int = 512, max_events: int = 128):
        self.worker = int(worker)
        self.registry = registry if registry is not None \
            else _default_registry()
        self._runlog_fn = runlog_fn
        self._max_steps = max_steps
        self._max_events = max_events
        #: restarts are visible server-side as a boot change: the pid and
        #: an in-process counter make the id unique per source incarnation
        self.boot = f"{os.getpid()}.{next(_boot_counter)}"
        self.seq = 0
        self._lock = threading.Lock()
        self._steps: List[List[Any]] = []
        self._events: List[Dict[str, Any]] = []
        self._last_counters: Dict[str, float] = {
            flat_series(rec["name"], rec["labels"]): rec["value"]
            for rec in self.registry.snapshot()["counters"]}

    # ------------------------------------------------------------------
    def note_step(self, step: int, step_time_s: float, *,
                  loss: Optional[float] = None,
                  tokens_per_s: Optional[float] = None,
                  t: Optional[float] = None):
        """Record one completed training step for the next push."""
        rec = [int(step), float(time.time() if t is None else t),
               float(step_time_s),
               None if loss is None else float(loss),
               None if tokens_per_s is None else float(tokens_per_s)]
        with self._lock:
            self._steps.append(rec)
            del self._steps[:-self._max_steps]

    def note_event(self, rec: Dict[str, Any]):
        """Queue a RunLog-shaped record (compile/anomaly/...) for the next
        push.  Kinds outside EVENT_KINDS are dropped (step records travel
        on the dedicated channel)."""
        if rec.get("kind") not in EVENT_KINDS:
            return
        with self._lock:
            self._events.append(rec)
            del self._events[:-self._max_events]

    def has_data(self) -> bool:
        """Steps/events queued for the next payload (counter deltas are
        only visible at build time — this is the cheap pre-check the
        final flush uses)."""
        with self._lock:
            return bool(self._steps or self._events)

    # ------------------------------------------------------------------
    def payload(self, hb_rtt_s: Optional[float] = None) -> Dict[str, Any]:
        """Build (and COMMIT) the next delta payload.  The caller owns
        delivery: on permanent failure call :meth:`unpush` to merge the
        payload back, else its deltas are lost."""
        runlog = self._runlog_fn() if self._runlog_fn is not None else None
        if runlog is not None:
            for rec in getattr(runlog, "drain_tail", lambda: [])():
                self.note_event(rec)
        snap = self.registry.snapshot()
        with self._lock:
            counters: Dict[str, float] = {}
            for rec in snap["counters"]:
                key = flat_series(rec["name"], rec["labels"])
                delta = rec["value"] - self._last_counters.get(key, 0.0)
                if delta:
                    counters[key] = delta
                self._last_counters[key] = rec["value"]
            gauges = {flat_series(rec["name"], rec["labels"]): rec["value"]
                      for rec in snap["gauges"]}
            self.seq += 1
            out = {"worker": self.worker, "boot": self.boot,
                   "seq": self.seq, "t": time.time(),
                   "hb_rtt_s": hb_rtt_s, "counters": counters,
                   "gauges": gauges, "steps": self._steps,
                   "events": self._events}
            self._steps = []
            self._events = []
        return out

    def unpush(self, payload: Dict[str, Any]):
        """Merge an undeliverable payload back so the next push re-ships
        its counter deltas, steps and events (idempotent bookkeeping:
        the server never saw this seq)."""
        with self._lock:
            for key, delta in payload.get("counters", {}).items():
                self._last_counters[key] = \
                    self._last_counters.get(key, 0.0) - delta
            self._steps = (list(payload.get("steps", []))
                           + self._steps)[-self._max_steps:]
            self._events = (list(payload.get("events", []))
                            + self._events)[-self._max_events:]


class TelemetryPusher:
    """Periodic telemetry push loop over a CoordinationClient.

    ``interval`` defaults to the HETU_TPU_TELEMETRY_PUSH flag; 0 means
    the pusher never starts a thread (``push_now()`` still works for
    deterministic tests).  A payload whose delivery fails is held as
    PENDING and re-sent **with the same (boot, seq) identity** on the
    next beat — not rebuilt — so the case where the server applied the
    push but the ack was lost in the tear resolves as a server-side
    dup-ack, never a double-count.  Steps/events that accumulate while a
    payload is pending simply ride the next one; nothing is lost.  A
    lock serializes pushes, so close()'s final flush cannot race an
    in-flight delivery and reorder seqs.
    """

    def __init__(self, client, source: Optional[TelemetrySource] = None,
                 interval: Optional[float] = None, registry=None,
                 runlog_fn: Optional[Callable[[], Any]] = None,
                 start: bool = True):
        self.client = client
        self.registry = registry if registry is not None \
            else _default_registry()
        self.source = source or TelemetrySource(
            client.rank, registry=self.registry, runlog_fn=runlog_fn)
        self.interval = push_interval() if interval is None else \
            float(interval)
        self.pushes = 0
        self.failures = 0
        self._pending: Optional[Dict[str, Any]] = None
        self._push_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start and self.interval > 0:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def push_now(self) -> bool:
        """One push, synchronously.  Returns delivery success.  Delivers
        the pending (previously failed) payload before building a new
        one; on failure the payload is kept pending for the next call."""
        with self._push_lock:
            payload = self._pending
            if payload is None:
                rtt_h = self.registry.histogram("rpc.heartbeat_rtt_s",
                                                rank=self.client.rank)
                payload = self.source.payload(
                    hb_rtt_s=(rtt_h.percentile(50)
                              if rtt_h is not None else None))
            try:
                self.client.telemetry_push(payload)
            except Exception as e:
                # keep the SAME payload (same seq) for the next beat: if
                # the server DID apply it and only the ack was lost, the
                # re-send dedupes server-side instead of double-counting
                self._pending = payload
                self.failures += 1
                self.registry.inc("rpc.telemetry_push_failures")
                logger.warning(f"telemetry push seq {payload['seq']} "
                               f"failed ({e!r}); held pending for the "
                               "next beat")
                return False
            self._pending = None
            self.pushes += 1
            self.registry.inc("rpc.telemetry_pushes")
            return True

    def _loop(self):
        while not self._stop.wait(self.interval):
            if getattr(self.client, "stale", False) or \
                    getattr(self.client, "_shutdown", False):
                return
            self.push_now()

    def close(self, final_push: bool = True):
        """Stop the loop; by default flush one last payload so the tail
        of the run (final steps, summary events) reaches the server.
        The push lock serializes with any in-flight loop delivery even
        if the join timed out on a wedged transport."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_push and not getattr(self.client, "stale", False):
            try:
                if self.push_now() and (self.source.has_data()
                                        or self._pending is not None):
                    self.push_now()   # the pending payload flushed; now
                                      # ship what accumulated behind it
            except Exception:
                pass


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class ClusterSnapshot(dict):
    """The time-windowed cluster view the aggregator renders — a plain
    JSON-serializable dict (it crosses the rpc wire as-is):

        {"t": <server time>, "window_s": w,
         "workers": {"<rank>": {
             "steps_total", "steps_window", "step_rate",
             "step_time_p50"/"p95"/"mean", "loss", "tokens_per_s",
             "estimated_mfu", "comm_bytes_per_step",
             "heartbeat_gap_s", "last_push_age_s", "clock_offset_s",
             "pushes", "dup_pushes", "anomalies": {kind: n},
             "counters": {series: total}, "gauges": {series: value}}}}

    Worker keys are STRINGS (JSON object keys) — use ``int(rank)`` when
    ordering numerically."""


class _WorkerState:
    __slots__ = ("boot", "last_seq", "counters", "gauges", "steps",
                 "events", "anomalies", "last_push_t", "clock_offset_s",
                 "pushes", "dup_pushes", "estimated_mfu", "comm_bytes",
                 "steps_total")

    def __init__(self):
        self.boot: Optional[str] = None
        self.last_seq = -1
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.steps: List[tuple] = []     # (step, t_worker, dt, loss, tps)
        self.events: List[Dict[str, Any]] = []
        self.anomalies: Dict[str, int] = {}
        self.last_push_t: Optional[float] = None
        self.clock_offset_s: Optional[float] = None
        self.pushes = 0
        self.dup_pushes = 0
        self.estimated_mfu: Optional[float] = None
        self.comm_bytes: Optional[float] = None
        self.steps_total = 0


class ClusterAggregator:
    """Folds workers' telemetry pushes into the ClusterSnapshot.

    Monotonic-counter delta merging: each worker's counters accumulate
    from shipped deltas; a duplicated delivery (same ``(boot, seq)`` —
    a client retry after a reattach, or a chaos ``rpc_dup``) is applied
    exactly once, and a **boot change** (worker restart) resets the seq
    watermark while keeping the cumulative totals — restarts never
    double-count and never lose history."""

    def __init__(self, window_s: float = 60.0, max_steps: int = 2048,
                 max_events: int = 64, runlog=None, registry=None):
        self.window_s = float(window_s)
        self._max_steps = max_steps
        self._max_events = max_events
        self.runlog = runlog
        self.registry = registry if registry is not None \
            else _default_registry()
        self._lock = threading.Lock()
        self._workers: Dict[int, _WorkerState] = {}
        self._last_flagged: frozenset = frozenset()

    # ------------------------------------------------------------------
    def ingest(self, payload: Dict[str, Any],
               recv_t: Optional[float] = None) -> Dict[str, Any]:
        """Fold one push payload; returns the ack ({'applied', 'seq'})."""
        now = time.time() if recv_t is None else recv_t
        worker = int(payload["worker"])
        boot, seq = payload.get("boot"), int(payload.get("seq", 0))
        with self._lock:
            st = self._workers.setdefault(worker, _WorkerState())
            if boot == st.boot and seq <= st.last_seq:
                st.dup_pushes += 1
                self.registry.inc("cluster.telemetry_dup_pushes")
                return {"applied": False, "seq": st.last_seq}
            if boot != st.boot:
                # a restarted worker: fresh seq space, cumulative counters
                # carry on (its source re-baselined at 0, so deltas are
                # counts since restart — no overlap with history)
                st.boot, st.last_seq = boot, -1
            st.last_seq = seq
            for key, delta in payload.get("counters", {}).items():
                st.counters[key] = st.counters.get(key, 0.0) + float(delta)
            st.gauges.update(payload.get("gauges", {}))
            send_t = payload.get("t")
            if send_t is not None:
                # worker-clock -> server-clock offset, the heartbeat-RTT
                # estimate: recv = send + offset + rtt/2
                rtt = payload.get("hb_rtt_s") or 0.0
                off = now - float(send_t) - rtt / 2.0
                st.clock_offset_s = off if st.clock_offset_s is None else \
                    0.8 * st.clock_offset_s + 0.2 * off
            for s in payload.get("steps", []):
                st.steps.append(tuple(s))
                st.steps_total += 1
            del st.steps[:-self._max_steps]
            for ev in payload.get("events", []):
                kind = ev.get("kind")
                if kind == "compile":
                    if ev.get("estimated_mfu") is not None:
                        st.estimated_mfu = float(ev["estimated_mfu"])
                    if ev.get("comm_bytes") is not None:
                        st.comm_bytes = float(ev["comm_bytes"])
                elif kind == "anomaly":
                    k = str(ev.get("anomaly", "unknown"))
                    st.anomalies[k] = st.anomalies.get(k, 0) + 1
                st.events.append(ev)
                del st.events[:-self._max_events]
            st.last_push_t = now
            st.pushes += 1
        self.registry.inc("cluster.telemetry_pushes")
        return {"applied": True, "seq": seq}

    # ------------------------------------------------------------------
    def worker_counter(self, worker: int, series: str) -> float:
        """Cumulative value of one worker's pushed counter series."""
        with self._lock:
            st = self._workers.get(int(worker))
            return 0.0 if st is None else st.counters.get(series, 0.0)

    def snapshot(self, window_s: Optional[float] = None,
                 heartbeats: Optional[Dict[int, float]] = None,
                 now: Optional[float] = None) -> ClusterSnapshot:
        """Render the ClusterSnapshot over the trailing window.
        ``heartbeats`` ({rank: gap_s}, from the coordination server's
        beat bookkeeping) enriches workers with their heartbeat gap."""
        w = self.window_s if window_s is None else float(window_s)
        now = time.time() if now is None else now
        workers: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for rank in sorted(self._workers):
                st = self._workers[rank]
                off = st.clock_offset_s or 0.0
                recent = [s for s in st.steps
                          if now - (s[1] + off) <= w]
                times = sorted(s[2] for s in recent)
                losses = [s[3] for s in recent if s[3] is not None]
                tps = [s[4] for s in recent if s[4] is not None]
                workers[str(rank)] = {
                    "steps_total": st.steps_total,
                    "steps_window": len(recent),
                    "step_rate": len(recent) / w if w > 0 else None,
                    "step_time_p50": _percentile(times, 50),
                    "step_time_p95": _percentile(times, 95),
                    "step_time_mean": (sum(times) / len(times)
                                       if times else None),
                    "loss": losses[-1] if losses else None,
                    "tokens_per_s": tps[-1] if tps else None,
                    "estimated_mfu": st.estimated_mfu,
                    "comm_bytes_per_step": st.comm_bytes,
                    "last_push_age_s": (None if st.last_push_t is None
                                        else now - st.last_push_t),
                    "clock_offset_s": st.clock_offset_s,
                    "pushes": st.pushes,
                    "dup_pushes": st.dup_pushes,
                    "anomalies": dict(st.anomalies),
                    "counters": dict(st.counters),
                    "gauges": dict(st.gauges),
                }
                # serving workers (serve.* series in the pushed deltas):
                # the dashboard-facing digest, so tools_cluster.py shows
                # a serving worker's load next to training workers
                if any(k.startswith("serve.") for k in st.counters) or \
                        any(k.startswith("serve.") for k in st.gauges):
                    workers[str(rank)]["serving"] = {
                        "requests_done":
                            st.counters.get("serve.requests_done", 0.0),
                        "tokens_out":
                            st.counters.get("serve.tokens_out", 0.0),
                        "queue_depth":
                            st.gauges.get("serve.queue_depth"),
                        "page_util": st.gauges.get("serve.page_util"),
                        "slot_occupancy":
                            st.gauges.get("serve.slot_occupancy"),
                    }
        for rank, gap in (heartbeats or {}).items():
            workers.setdefault(str(rank), {})["heartbeat_gap_s"] = gap
        return ClusterSnapshot(t=now, window_s=w, workers=workers)

    # ------------------------------------------------------------------
    def straggler_report(self, snapshot: Optional[Dict[str, Any]] = None,
                         **kw) -> Dict[str, Any]:
        """Compute the straggler report over a snapshot (defaults to a
        fresh one), publish `cluster.straggler_*` gauges, and log a
        `straggler` RunLog event when the flagged set changes."""
        snap = snapshot if snapshot is not None else self.snapshot()
        report = straggler_report(snap, **kw)
        for rank_s, rec in report["workers"].items():
            self.registry.set_gauge("cluster.straggler_ratio",
                                    rec["ratio"], rank=rank_s)
            self.registry.set_gauge("cluster.straggler_z",
                                    rec["z"], rank=rank_s)
        flagged = frozenset(report["stragglers"])
        newly = flagged - self._last_flagged
        if newly:
            self.registry.inc("cluster.stragglers_flagged", len(newly))
        if flagged != self._last_flagged:
            self._last_flagged = flagged
            if self.runlog is not None:
                self.runlog.log(
                    "straggler", stragglers=sorted(flagged),
                    workers={r: {k: rec[k] for k in
                                 ("step_time_p50", "baseline_p50",
                                  "ratio", "z", "straggler")}
                             for r, rec in report["workers"].items()})
        return report


def straggler_report(snapshot: Dict[str, Any], *,
                     ratio_threshold: float = 2.0,
                     z_threshold: float = 3.0,
                     min_samples: int = 3) -> Dict[str, Any]:
    """Robust per-worker step-time straggler scoring over a
    ClusterSnapshot (pure function — no gauges, no log).

    Each worker's window-median step time is compared against the
    **leave-one-out** median of the other workers' medians (so a slow
    worker cannot hide inside its own baseline), with a MAD-scaled
    z-score.  At small world sizes the MAD degenerates (2 workers: the
    spread of one sample is 0), so the scale is floored at 0.1% of the
    baseline and the FLAG rule requires ratio AND z: the ratio carries
    the decision when the spread is degenerate, the z-score guards
    against flagging wide-but-normal distributions.
    """
    per: Dict[str, Dict[str, Any]] = {}
    meds = {}
    for rank_s, w in snapshot.get("workers", {}).items():
        if w.get("step_time_p50") is not None and \
                w.get("steps_window", 0) >= min_samples:
            meds[rank_s] = float(w["step_time_p50"])
    for rank_s, med in meds.items():
        others = [m for r, m in meds.items() if r != rank_s]
        if not others:
            continue
        base = _median(others)
        mad = _median([abs(m - base) for m in others]) or 0.0
        scale = 1.4826 * mad + 1e-3 * base + 1e-12
        z = (med - base) / scale
        ratio = med / base if base > 0 else math.inf
        per[rank_s] = {
            "step_time_p50": med, "baseline_p50": base,
            "ratio": ratio, "z": z,
            "straggler": bool(ratio >= ratio_threshold
                              and z >= z_threshold),
        }
    return {"t": snapshot.get("t"), "window_s": snapshot.get("window_s"),
            "workers": per,
            "stragglers": sorted(int(r) for r, rec in per.items()
                                 if rec["straggler"])}


def snapshot_straggler_hook(window_s: Optional[float] = None):
    """A ready-made straggler hook for ElasticController: fetch the
    coordinator's snapshot+report via the worker's own client."""
    def hook(client) -> Optional[Dict[str, Any]]:
        resp = client.telemetry_snapshot(window_s=window_s)
        return resp.get("straggler")
    return hook


def merge_offsets(snapshot: Dict[str, Any]) -> Dict[str, float]:
    """{worker: clock_offset_s} from a ClusterSnapshot — feed to
    obs.trace.merge_runlogs to align per-worker RunLogs on server time."""
    out: Dict[str, float] = {}
    for rank_s, w in snapshot.get("workers", {}).items():
        off = w.get("clock_offset_s")
        if off is not None:
            out[rank_s] = float(off)
    return out
