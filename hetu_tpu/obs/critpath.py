"""Critical-path extraction over stitched fleet traces.

Answers the fleet-tuning question the stitched DAG exists for: *where
did this request's latency go?*  `critical_path` decomposes one
`FleetTrace`'s client-visible lifetime into EXCLUSIVE per-hop segments

    frontend_queue   routing/held time before the first tier saw it
                     (includes the prefill tier's admission queue)
    prefill          prompt processing — remote tier or colocated
    shipment_wait    waiting on the KV shipment wire (drops, delays,
                     re-prefill turnarounds included)
    decode_queue     waiting on a decode slot/pages after dispatch
    decode           token generation
    reshard_pause    frozen under a LoadAdaptiveMesh reshard
    replay           re-queued time after a preemption / replica loss /
                     prefill-tier fallback (failover re-admission)

The segments PARTITION the primary hop's span tiling — every piece of
every span lands in exactly one bucket — so their sum reconciles with
the end-to-end latency with zero residual (<= one step quantum per
attempt boundary, the same allowance the span contract itself has).
TTFT gets the same decomposition by clipping the piecewise path at the
first-token boundary.

Pure host-side arithmetic over `obs/spans.py` shapes: no jax, no
serving imports.  `serving/slo_report.py` (the one reader) rolls these
up per tenant and SLO class; `tools_serving_report.py --request` renders
one request's hop tree with the path highlighted.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from hetu_tpu.obs.spans import (TERMINAL_KINDS, FleetTrace,
                                RequestTrace, _ev_t)

#: the exclusive latency buckets, in pipeline order
SEGMENTS = ("frontend_queue", "prefill", "shipment_wait",
            "decode_queue", "decode", "reshard_pause", "replay")

#: stall reasons whose queued span is failover re-admission time
_REPLAY_REASONS = ("preempted", "replica_lost", "prefill_tier_down",
                   "brownout_shed")


def _pf_hop_bounds(hop: RequestTrace) -> Dict[str, float]:
    """(queue_end, work_end) of one prefill-tier hop: where its queued
    wait turned into chunk work, and where the work stopped (the ship
    or the fallback)."""
    pf = hop.by_kind("prefill")
    first = hop.spans[0]
    q_end = pf[0].t0 if pf else first.t1
    work_end = pf[-1].t1 if pf else q_end
    return {"q_end": q_end, "work_end": work_end}


def _split_queued(span, *, pf_hops: Sequence[RequestTrace],
                  dispatch_ts: Sequence[float],
                  eps: float = 1e-9) -> List[tuple]:
    """Partition one queued span into (t0, t1, segment) pieces using
    the causal context: prefill-tier hop boundaries carve out remote
    prefill and shipment wait, the dispatch event carves frontend
    routing from decode-queue wait, and a failover/preempt re-queue is
    replay wholesale."""
    reason = span.attrs.get("reason")
    if span.attempt > 1 or reason in _REPLAY_REASONS:
        return [(span.t0, span.t1, "replay")]
    if reason == "shipment_wait":
        return [(span.t0, span.t1, "shipment_wait")]
    overlapping = [h for h in pf_hops if h.spans
                   and h.spans[0].t0 <= span.t1 + eps
                   and h.spans[-1].t1 >= span.t0 - eps]
    if overlapping:
        pieces: List[tuple] = []
        cur = span.t0
        first = True
        for hop in overlapping:
            b = _pf_hop_bounds(hop)
            q_end = min(max(b["q_end"], cur), span.t1)
            work_end = min(max(b["work_end"], q_end), span.t1)
            if q_end > cur + eps:
                pieces.append((cur, q_end,
                               "frontend_queue" if first
                               else "shipment_wait"))
            if work_end > q_end + eps:
                pieces.append((q_end, work_end, "prefill"))
            cur = max(cur, work_end)
            first = False
        if span.t1 > cur + eps:
            pieces.append((cur, span.t1, "shipment_wait"))
        return pieces or [(span.t0, span.t1, "shipment_wait")]
    cut = None
    for t in dispatch_ts:
        if span.t0 - eps <= t <= span.t1 + eps:
            cut = min(max(t, span.t0), span.t1)
            break
    if cut is not None and cut > span.t0 + eps:
        return [(span.t0, cut, "frontend_queue"),
                (cut, span.t1, "decode_queue")]
    return [(span.t0, span.t1, "decode_queue")]


def _ttft_t(prim: RequestTrace) -> Optional[float]:
    """First-token time on the primary hop: the close of the final
    prefill chunk (``last=True``; an adopted shipment emits it
    zero-duration at adoption), else the first decode boundary."""
    lasts = [s for s in prim.by_kind("prefill") if s.attrs.get("last")]
    if lasts:
        return lasts[-1].t1
    dec = prim.by_kind("decode")
    if dec:
        return dec[0].t0
    return None


def critical_path(ft: FleetTrace, *, eps: float = 1e-9
                  ) -> Optional[Dict[str, Any]]:
    """Decompose one stitched request into the exclusive SEGMENTS.

    Returns None when the trace has no client terminal (the request is
    still in flight).  Otherwise a dict with the per-segment totals
    (``segments``), the TTFT-clipped totals (``ttft_segments``), the
    merged piecewise ``path`` [(segment, t0, t1)...], and the
    reconciliation ``residual_s`` = e2e - sum(segments) — zero for any
    contiguous tiling, <= one step quantum per attempt boundary
    otherwise."""
    prim = ft.primary
    if prim is None or not prim.spans:
        return None
    pf_hops = [h for h in ft.hops if h.tier == "prefill"]
    dispatch_ts = sorted(
        _ev_t(ev) for ev in ft.events
        if ev.get("event") == "dispatch"
        and ev.get("tier") in (None, "decode"))
    pieces: List[tuple] = []
    for s in prim.spans:
        if s.kind in TERMINAL_KINDS:
            continue
        if s.kind == "queued":
            pieces.extend(_split_queued(s, pf_hops=pf_hops,
                                        dispatch_ts=dispatch_ts,
                                        eps=eps))
        elif s.kind == "prefill":
            pieces.append((s.t0, s.t1, "prefill"))
        elif s.kind == "decode":
            pieces.append((s.t0, s.t1, "decode"))
        elif s.kind == "reshard_pause":
            pieces.append((s.t0, s.t1, "reshard_pause"))
    pieces = [(t0, t1, seg) for (t0, t1, seg) in pieces if t1 > t0]
    # merge adjacent pieces of the same segment for the rendered path
    path: List[Dict[str, Any]] = []
    for t0, t1, seg in pieces:
        if path and path[-1]["segment"] == seg \
                and abs(path[-1]["t1"] - t0) <= eps:
            path[-1]["t1"] = t1
        else:
            path.append({"segment": seg, "t0": t0, "t1": t1})
    segments = {seg: 0.0 for seg in SEGMENTS}
    for t0, t1, seg in pieces:
        segments[seg] += t1 - t0
    arrival = prim.spans[0].t0
    terminal = prim.spans[-1].t1
    e2e_s = terminal - arrival
    ttft_t = _ttft_t(prim)
    ttft_segments = {seg: 0.0 for seg in SEGMENTS}
    ttft_s = None
    if ttft_t is not None:
        ttft_s = ttft_t - arrival
        for t0, t1, seg in pieces:
            ttft_segments[seg] += max(0.0, min(t1, ttft_t) - t0)
    return {
        "rid": ft.rid,
        "slo_class": ft.slo_class,
        "segments": segments,
        "ttft_segments": ttft_segments,
        "path": path,
        "e2e_s": e2e_s,
        "ttft_s": ttft_s,
        "residual_s": e2e_s - sum(segments.values()),
        "ttft_residual_s": (None if ttft_s is None
                            else ttft_s
                            - sum(ttft_segments.values())),
        "attempts": len(prim.attempts()),
        "hops": len(ft.hops),
    }


def rollup(paths: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate per-request decompositions: total and mean seconds per
    segment plus the worst reconciliation residual — the shape
    slo_report embeds per tenant / SLO class."""
    n = len(paths)
    total = {seg: 0.0 for seg in SEGMENTS}
    for cp in paths:
        for seg in SEGMENTS:
            total[seg] += cp["segments"][seg]
    return {
        "requests": n,
        "total_s": total,
        "mean_s": {seg: (total[seg] / n if n else 0.0)
                   for seg in SEGMENTS},
        "max_residual_s": max((abs(cp["residual_s"]) for cp in paths),
                              default=0.0),
    }


__all__ = ["SEGMENTS", "critical_path", "rollup"]
