"""Bytes-on-wire analyzer: count collectives in a compiled step's HLO.

The TPU tunnel being down must not make a comm optimization unverifiable:
this module walks the POST-OPTIMIZATION HLO text of a compiled train step
(available on any backend, incl. the 8-device CPU test mesh) and reports,
per collective opcode —  all-reduce / reduce-scatter / all-gather /
all-to-all / collective-permute — the op count and the bytes each puts on
the wire per participant under the standard ring algorithms:

    all-reduce          2 (n-1)/n * payload
    all-gather            (n-1)/n * gathered output
    reduce-scatter        (n-1)   * scattered output   (= (n-1)/n * input)
    all-to-all            (n-1)/n * local buffer
    collective-permute              output             (one hop)

`n` is parsed from each op's replica_groups.  Predicted comm time prices
all-reduce-class ops at the profile's `ici_allreduce_gbps` bus bandwidth
and permutes at `ici_p2p_gbps` (hardware_profile_v5e.json — the same
numbers the search cost model uses).

Consumers: Trainer compile run-events (RunLog `comm_bytes`), bench.py
(`comm_bytes_per_step` even when the backend is unreachable, via the
analytic twin in comm/wire.py), tools_comm_report.py (the per-collective
table), and the ZeRO-1 HLO-assertion test (reduce-scatter + all-gather
tripwire for GSPMD regressions).

Caveat: the count is STATIC — a collective inside a while-loop body
(scan-over-layers, grad-accumulation scan) is counted once, not
trip-count times.  For exact per-step accounting lower the model with
`use_scan=False` (the comm tests and tools_comm_report.py do).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from hetu_tpu.comm.wire import analytic_dp_sync  # noqa: F401  (re-export)

#: collective opcodes we account (async "-start" forms fold into these)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1, "c64": 8,
                "c128": 16}

# `%x = <shapes> opcode(...)` — same output-section anchoring as
# utils.profiling.phase_breakdown: shapes AFTER '=' and BEFORE the opcode
# token; operand shapes (inside the parens) must not count
_LINE_PAT = re.compile(r'=\s*(?P<out>.*?)\s*(?P<op>[a-z][a-z0-9_.-]*)\(')
_SHAPE_PAT = re.compile(r'\b([a-z][a-z0-9]*)\[([0-9,]*)\]')
_GROUPS_PAT = re.compile(r'replica_groups=\{\{([0-9, ]*)\}')
_IOTA_GROUPS_PAT = re.compile(r'replica_groups=\[(\d+),(\d+)\]<=')


def _component_bytes(section: str):
    out = []
    for dt, dims in _SHAPE_PAT.findall(section):
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        out.append(numel * _DTYPE_BYTES.get(dt, 4))
    return out


def _payload_bytes(section: str, is_start: bool) -> int:
    """Payload of one collective from its output-shape section.

    Sync forms: the output IS the payload (sum tuple components — a tuple
    all-to-all's components add up to the local buffer).  Async "-start"
    forms output a tuple carrying the OPERAND buffer(s) too —
    (operand, result, context...) — so summing would double-count; the
    largest component is the full transfer buffer for every async
    collective (result for all-gather, operand for reduce-scatter, either
    for all-reduce/permute), and `_wire_bytes` applies full-buffer
    formulas for starts."""
    comps = _component_bytes(section)
    if not comps:
        return 0
    return max(comps) if is_start else sum(comps)


def _group_size(line: str, default_world: int) -> int:
    m = _GROUPS_PAT.search(line)
    if m:
        first = [t for t in m.group(1).split(",") if t.strip()]
        return max(len(first), 1)
    m = _IOTA_GROUPS_PAT.search(line)
    if m:  # iota form [num_groups, group_size]<=[world]
        return max(int(m.group(2)), 1)
    return max(default_world, 1)


def _wire_bytes(op: str, payload: int, n: int, is_start: bool) -> float:
    """Per-participant ring wire bytes.  `payload` is the output-section
    payload (_payload_bytes): for sync reduce-scatter that is the SHARD
    (output), for async starts it is the FULL buffer — hence the two
    reduce-scatter formulas."""
    if op == "collective-permute":
        # point-to-point: one hop, group size does not apply (the op
        # carries source_target_pairs, not replica_groups)
        return float(payload)
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * payload
    if op == "all-gather":
        return (n - 1) / n * payload
    if op == "reduce-scatter":
        if is_start:  # payload = full input buffer
            return (n - 1) / n * payload
        return float(n - 1) * payload  # payload = the output shard
    if op == "all-to-all":
        return (n - 1) / n * payload
    return 0.0


def collective_table(compiled_or_text, default_world: int = 1
                     ) -> List[Dict[str, Any]]:
    """One row per collective instruction in the optimized HLO:
    {op, out_bytes, group_size, wire_bytes, line}.  Accepts a compiled
    executable (as_text()) or the HLO text itself."""
    txt = (compiled_or_text if isinstance(compiled_or_text, str)
           else compiled_or_text.as_text())
    rows = []
    for line in txt.splitlines():
        # cheap prefilter before the regex work
        if "all-" not in line and "reduce-scatter" not in line \
                and "collective-permute" not in line:
            continue
        m = _LINE_PAT.search(line)
        if m is None:
            continue
        op = m.group("op")
        if op.endswith("-done"):
            continue  # the -start carries the payload
        is_start = op.endswith("-start")
        base = op[:-6] if is_start else op
        if base not in COLLECTIVE_OPS:
            continue
        out_bytes = _payload_bytes(m.group("out"), is_start)
        n = _group_size(line, default_world)
        rows.append({
            "op": base,
            "out_bytes": out_bytes,
            "group_size": n,
            "wire_bytes": _wire_bytes(base, out_bytes, n, is_start),
            "line": line.strip()[:200],
        })
    return rows


def collective_report(compiled_or_text, *, hw: Optional[Dict] = None,
                      default_world: int = 1) -> Dict[str, Any]:
    """Aggregate bytes-on-wire report for one compiled step.

    {collectives: {op: {count, wire_bytes}}, num_collectives,
     total_wire_bytes, predicted_comm_s, chip} — predicted_comm_s is the
    serial ring-time estimate over the hardware profile's ICI rates (an
    upper bound: real collectives overlap compute)."""
    rows = collective_table(compiled_or_text, default_world)
    per_op: Dict[str, Dict[str, float]] = {}
    for r in rows:
        rec = per_op.setdefault(r["op"], {"count": 0, "wire_bytes": 0.0})
        rec["count"] += 1
        rec["wire_bytes"] += r["wire_bytes"]
    if hw is None:
        from hetu_tpu.obs.mfu import load_hardware_profile
        hw = load_hardware_profile()
    ar_bw = float(hw.get("ici_allreduce_gbps", 45.0)) * 1e9
    p2p_bw = float(hw.get("ici_p2p_gbps", 90.0)) * 1e9
    t = 0.0
    for op, rec in per_op.items():
        bw = p2p_bw if op == "collective-permute" else ar_bw
        t += rec["wire_bytes"] / bw
    return {
        "collectives": per_op,
        "num_collectives": len(rows),
        "total_wire_bytes": sum(r["wire_bytes"] for r in rows),
        "predicted_comm_s": t,
        "chip": hw.get("chip", "unknown"),
    }
