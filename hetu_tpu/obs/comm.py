"""Bytes-on-wire analyzer: count collectives in a compiled step's HLO.

The TPU tunnel being down must not make a comm optimization unverifiable:
this module walks the POST-OPTIMIZATION HLO text of a compiled train step
(available on any backend, incl. the 8-device CPU test mesh) and reports,
per collective opcode —  all-reduce / reduce-scatter / all-gather /
all-to-all / collective-permute — the op count and the bytes each puts on
the wire per participant under the standard ring algorithms:

    all-reduce          2 (n-1)/n * payload
    all-gather            (n-1)/n * gathered output
    reduce-scatter        (n-1)   * scattered output   (= (n-1)/n * input)
    all-to-all            (n-1)/n * local buffer
    collective-permute              output             (one hop)

(the same formulas comm/wire.py prices analytically — the
cross-validation test pins the two together).  `n` is parsed from each
op's replica_groups.

Scanned layers: a collective inside a `while` body (scan-over-layers,
grad-accumulation) executes TRIP-COUNT times per step, not once.  The
analyzer resolves each while's trip count from its condition computation
(`compare(induction, constant), direction=LT` — the 0-based unit-step
form every lax.scan lowers to) and multiplies the enclosed collectives'
count and bytes through, nested whiles composing multiplicatively.  When
the comparison bound is NOT a literal constant the enclosed rows are
counted once and the report carries `dynamic_trip_count: true` — lower
with `use_scan=False` for exact accounting in that case.

Predicted comm time prices all-reduce-class ops at the profile's
`ici_allreduce_gbps` bus bandwidth and permutes at `ici_p2p_gbps`.  When
the profile carries a `topology` section (comm/topology.py), each
collective's replica group is CLASSIFIED: groups confined to one slice
ride `topology.intra_gbps`, groups spanning slices ride the (slower)
`topology.inter_gbps` — so a flat ring over the whole pod is priced at
the inter rate while a two-level schedule's intra stages keep the fast
rate, and the report splits `predicted_comm_s_intra` / `_inter`.

Consumers: Trainer compile run-events (RunLog `comm_bytes`), bench.py
(`comm_bytes_per_step` even when the backend is unreachable, via the
analytic twin in comm/wire.py), tools_comm_report.py (the per-collective
and per-path tables), and the ZeRO-1 HLO-assertion test (reduce-scatter
+ all-gather tripwire for GSPMD regressions).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from hetu_tpu.comm.wire import analytic_dp_sync  # noqa: F401  (re-export)

#: collective opcodes we account (async "-start" forms fold into these)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1, "c64": 8,
                "c128": 16}

# `%x = <shapes> opcode(...)` — same output-section anchoring as
# utils.profiling.phase_breakdown: shapes AFTER '=' and BEFORE the opcode
# token; operand shapes (inside the parens) must not count
_LINE_PAT = re.compile(r'=\s*(?P<out>.*?)\s*(?P<op>[a-z][a-z0-9_.-]*)\(')
_SHAPE_PAT = re.compile(r'\b([a-z][a-z0-9]*)\[([0-9,]*)\]')
_GROUPS_PAT = re.compile(r'replica_groups=\{(\{[0-9,{} ]*\})\}')
_IOTA_GROUPS_PAT = re.compile(
    r'replica_groups=\[(\d+),(\d+)\]<=(?:\[[\d,]+\])(T\([\d,]+\))?')

# computation structure (while-loop trip counts)
_COMP_HEAD_PAT = re.compile(
    r'^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{')
_WHILE_PAT = re.compile(r'=\s*[^=]*\bwhile\(')
_COND_REF_PAT = re.compile(r'condition=%?([\w.\-]+)')
_BODY_REF_PAT = re.compile(r'body=%?([\w.\-]+)')
_CONST_PAT = re.compile(
    r'%?([\w.\-]+)\s*=\s*[su]\d+\[\]\s+constant\((\d+)\)')
_COMPARE_PAT = re.compile(
    r'compare\(\s*\S+\s+%?([\w.\-]+),\s*\S+\s+%?([\w.\-]+)\s*\)')
_DIRECTION_PAT = re.compile(r'direction=(\w+)')


def _component_bytes(section: str):
    out = []
    for dt, dims in _SHAPE_PAT.findall(section):
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        out.append(numel * _DTYPE_BYTES.get(dt, 4))
    return out


def _payload_bytes(section: str, is_start: bool) -> int:
    """Payload of one collective from its output-shape section.

    Sync forms: the output IS the payload (sum tuple components — a tuple
    all-to-all's components add up to the local buffer).  Async "-start"
    forms output a tuple carrying the OPERAND buffer(s) too —
    (operand, result, context...) — so summing would double-count; the
    largest component is the full transfer buffer for every async
    collective (result for all-gather, operand for reduce-scatter, either
    for all-reduce/permute), and `_wire_bytes` applies full-buffer
    formulas for starts."""
    comps = _component_bytes(section)
    if not comps:
        return 0
    return max(comps) if is_start else sum(comps)


def _first_group(line: str, default_world: int
                 ) -> Tuple[int, Optional[Tuple[int, ...]]]:
    """(group size, first group's rank list when recoverable) of a
    collective instruction."""
    m = _GROUPS_PAT.search(line)
    if m:
        first = m.group(1).split("}")[0].lstrip("{")
        ranks = tuple(int(t) for t in first.split(",") if t.strip())
        return max(len(ranks), 1), (ranks or None)
    m = _IOTA_GROUPS_PAT.search(line)
    if m:  # iota form [num_groups, group_size]<=[world](T(perm))?
        g, s = int(m.group(1)), int(m.group(2))
        if m.group(3):  # transposed iota: group 0 strides by num_groups
            ranks = tuple(range(0, g * s, g))[:s]
        else:           # contiguous iota: group 0 = [0, s)
            ranks = tuple(range(s))
        return max(s, 1), ranks
    return max(default_world, 1), None


def _wire_bytes(op: str, payload: int, n: int, is_start: bool) -> float:
    """Per-participant ring wire bytes.  `payload` is the output-section
    payload (_payload_bytes): for sync reduce-scatter that is the SHARD
    (output), for async starts it is the FULL buffer — hence the two
    reduce-scatter formulas."""
    if op == "collective-permute":
        # point-to-point: one hop, group size does not apply (the op
        # carries source_target_pairs, not replica_groups)
        return float(payload)
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * payload
    if op == "all-gather":
        return (n - 1) / n * payload
    if op == "reduce-scatter":
        if is_start:  # payload = full input buffer
            return (n - 1) / n * payload
        return float(n - 1) * payload  # payload = the output shard
    if op == "all-to-all":
        return (n - 1) / n * payload
    return 0.0


# ---------------------------------------------------------------------------
# computation structure: while-loop trip counts
# ---------------------------------------------------------------------------

def _split_computations(txt: str) -> Dict[str, List[str]]:
    """HLO text -> {computation name: its instruction lines}.  Text with
    no computation headers (synthetic snippets) maps to one anonymous
    computation holding every line."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    loose: List[str] = []
    for line in txt.splitlines():
        m = _COMP_HEAD_PAT.match(line)
        if m:
            cur = m.group(1)
            comps[cur] = []
            continue
        if line.strip() == "}":
            cur = None
            continue
        (comps[cur] if cur is not None else loose).append(line)
    if loose:
        comps[""] = loose
    return comps


def _cond_trip_count(lines: List[str]) -> Optional[int]:
    """Trip count from a while condition computation: the
    `compare(induction, constant), direction=LT` form lax.scan lowers to
    (0-based, unit step).  Non-zero-start loops (fori_loop(2, 10, ...))
    are safe too: XLA's while canonicalization rebases the induction to
    0 and folds the start into the bound BEFORE the post-optimization
    text this module parses (regression-pinned in test_comm).  None =
    not statically recoverable."""
    consts = {name: int(val)
              for name, val in (_CONST_PAT.search(ln).groups()
                                for ln in lines if _CONST_PAT.search(ln))}
    for ln in lines:
        cm = _COMPARE_PAT.search(ln)
        if cm is None:
            continue
        dm = _DIRECTION_PAT.search(ln)
        direction = dm.group(1) if dm else ""
        lhs, rhs = cm.group(1), cm.group(2)
        if direction == "LT" and rhs in consts:
            return consts[rhs]
        if direction == "GT" and lhs in consts:
            return consts[lhs]
    return None


def _comp_multipliers(comps: Dict[str, List[str]]
                      ) -> Dict[str, Tuple[int, bool]]:
    """{computation: (effective trip multiplier, dynamic?)} — body
    computations inherit their parent's multiplier times their while's
    trip count; nested whiles compose.  dynamic=True marks an enclosing
    while whose trip could not be resolved (multiplier stays 1 for it)."""
    parent: Dict[str, Tuple[str, Optional[int]]] = {}
    for cname, lines in comps.items():
        for ln in lines:
            if " while(" not in ln and not _WHILE_PAT.search(ln):
                continue
            bm = _BODY_REF_PAT.search(ln)
            cm = _COND_REF_PAT.search(ln)
            if bm is None:
                continue
            trip = None
            if cm is not None and cm.group(1) in comps:
                trip = _cond_trip_count(comps[cm.group(1)])
            parent[bm.group(1)] = (cname, trip)

    memo: Dict[str, Tuple[int, bool]] = {}

    def mult(name: str, seen=()) -> Tuple[int, bool]:
        if name in memo:
            return memo[name]
        if name not in parent or name in seen:
            return (1, False)
        pname, trip = parent[name]
        pm, pdyn = mult(pname, seen + (name,))
        out = (pm * (trip if trip else 1), pdyn or trip is None)
        memo[name] = out
        return out

    return {name: mult(name) for name in comps}


# ---------------------------------------------------------------------------
# the table / report
# ---------------------------------------------------------------------------

def collective_table(compiled_or_text, default_world: int = 1
                     ) -> List[Dict[str, Any]]:
    """One row per collective instruction in the optimized HLO:
    {op, out_bytes, group_size, wire_bytes, trip_count, dynamic_trip,
    group_ranks, line}.  wire_bytes is PER EXECUTION; multiply by
    trip_count for per-step totals (collective_report does).  Accepts a
    compiled executable (as_text()) or the HLO text itself."""
    txt = (compiled_or_text if isinstance(compiled_or_text, str)
           else compiled_or_text.as_text())
    comps = _split_computations(txt)
    mults = _comp_multipliers(comps)
    rows = []
    for cname, lines in comps.items():
        trip, dynamic = mults.get(cname, (1, False))
        for line in lines:
            # cheap prefilter before the regex work
            if "all-" not in line and "reduce-scatter" not in line \
                    and "collective-permute" not in line:
                continue
            m = _LINE_PAT.search(line)
            if m is None:
                continue
            op = m.group("op")
            if op.endswith("-done"):
                continue  # the -start carries the payload
            is_start = op.endswith("-start")
            base = op[:-6] if is_start else op
            if base not in COLLECTIVE_OPS:
                continue
            out_bytes = _payload_bytes(m.group("out"), is_start)
            n, ranks = _first_group(line, default_world)
            rows.append({
                "op": base,
                "out_bytes": out_bytes,
                "group_size": n,
                "wire_bytes": _wire_bytes(base, out_bytes, n, is_start),
                "trip_count": trip,
                "dynamic_trip": dynamic,
                "group_ranks": ranks,
                "line": line.strip()[:200],
            })
    return rows


def _row_rate_class(row, topo) -> str:
    """"intra" | "inter" | "p2p" — which bandwidth prices this row."""
    if row["op"] == "collective-permute":
        return "p2p"
    if topo is None:
        return "intra"
    ranks = row.get("group_ranks")
    if not ranks:
        return "intra"
    return topo.classify_group(ranks)


def collective_report(compiled_or_text, *, hw: Optional[Dict] = None,
                      default_world: int = 1) -> Dict[str, Any]:
    """Aggregate bytes-on-wire report for one compiled step.

    {collectives: {op: {count, wire_bytes}}, num_collectives,
     total_wire_bytes, predicted_comm_s, predicted_comm_s_intra,
     predicted_comm_s_inter, dynamic_trip_count, chip} — counts and bytes
    include while-loop trip multipliers; predicted_comm_s is the serial
    ring-time estimate over the profile's rates (an upper bound: real
    collectives overlap compute), with slice-spanning groups priced at
    the topology's inter-slice rate when the profile declares one."""
    rows = collective_table(compiled_or_text, default_world)
    if hw is None:
        from hetu_tpu.obs.mfu import load_hardware_profile
        hw = load_hardware_profile()
    from hetu_tpu.comm.topology import Topology
    topo = Topology.from_profile(hw)
    ar_bw = float(hw.get("ici_allreduce_gbps", 45.0)) * 1e9
    p2p_bw = float(hw.get("ici_p2p_gbps", 90.0)) * 1e9
    intra_bw = topo.intra_gbps * 1e9 if topo else ar_bw
    inter_bw = topo.inter_gbps * 1e9 if topo else ar_bw
    per_op: Dict[str, Dict[str, float]] = {}
    t_intra = t_inter = t_p2p = 0.0
    total = 0.0
    dynamic = False
    for r in rows:
        trip = max(int(r["trip_count"]), 1)
        dynamic = dynamic or r["dynamic_trip"]
        wb = r["wire_bytes"] * trip
        rec = per_op.setdefault(r["op"], {"count": 0, "wire_bytes": 0.0})
        rec["count"] += trip
        rec["wire_bytes"] += wb
        total += wb
        cls = _row_rate_class(r, topo)
        if cls == "p2p":
            t_p2p += wb / p2p_bw
        elif cls == "inter":
            t_inter += wb / inter_bw
        else:
            t_intra += wb / intra_bw
    report: Dict[str, Any] = {
        "collectives": per_op,
        "num_collectives": sum(int(rec["count"])
                               for rec in per_op.values()),
        "total_wire_bytes": total,
        "predicted_comm_s": t_intra + t_inter + t_p2p,
        "predicted_comm_s_intra": t_intra,
        "predicted_comm_s_inter": t_inter,
        "chip": hw.get("chip", "unknown"),
    }
    if dynamic:
        report["dynamic_trip_count"] = True
    return report
