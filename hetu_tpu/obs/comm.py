"""Bytes-on-wire analyzer: count collectives in a compiled step's HLO.

The TPU tunnel being down must not make a comm optimization unverifiable:
this module walks the POST-OPTIMIZATION HLO text of a compiled train step
(available on any backend, incl. the 8-device CPU test mesh) and reports,
per collective opcode —  all-reduce / reduce-scatter / all-gather /
all-to-all / collective-permute — the op count and the bytes each puts on
the wire per participant under the standard ring algorithms:

    all-reduce          2 (n-1)/n * payload
    all-gather            (n-1)/n * gathered output
    reduce-scatter        (n-1)   * scattered output   (= (n-1)/n * input)
    all-to-all            (n-1)/n * local buffer
    collective-permute              output             (one hop)

(the same formulas comm/wire.py prices analytically — the
cross-validation test pins the two together).  `n` is parsed from each
op's replica_groups.

ALL text parsing lives in `hetu_tpu.obs.hlo_text` — the one tokenizer
shared with the step profiler (obs/hlo_profile.py) and the
graph-contract linter (hetu_tpu/analysis/): line anatomy, payload
resolution (sync vs async "-start" forms), replica_groups (explicit and
iota), and the while-trip machinery below.  This module owns only the
aggregation and the topology-aware pricing.

Scanned layers: a collective inside a `while` body (scan-over-layers,
grad-accumulation) executes TRIP-COUNT times per step, not once.  The
analyzer resolves each while's trip count from its condition computation
(`compare(induction, constant), direction=LT` — the 0-based unit-step
form every lax.scan lowers to) and multiplies the enclosed collectives'
count and bytes through, nested whiles composing multiplicatively.  When
the comparison bound is NOT a literal constant the enclosed rows are
counted once and the report carries `dynamic_trip_count: true` — lower
with `use_scan=False` for exact accounting in that case.

Predicted comm time prices all-reduce-class ops at the profile's
`ici_allreduce_gbps` bus bandwidth and permutes at `ici_p2p_gbps`.  When
the profile carries a `topology` section (comm/topology.py), each
collective's replica group is CLASSIFIED: groups confined to one slice
ride `topology.intra_gbps`, groups spanning slices ride the (slower)
`topology.inter_gbps` — so a flat ring over the whole pod is priced at
the inter rate while a two-level schedule's intra stages keep the fast
rate, and the report splits `predicted_comm_s_intra` / `_inter`.

Consumers: Trainer compile run-events (RunLog `comm_bytes`), bench.py
(`comm_bytes_per_step` even when the backend is unreachable, via the
analytic twin in comm/wire.py), tools_comm_report.py (the per-collective
and per-path tables), and the ZeRO-1 HLO-assertion test (reduce-scatter
+ all-gather tripwire for GSPMD regressions).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from hetu_tpu.comm.wire import analytic_dp_sync  # noqa: F401  (re-export)
from hetu_tpu.obs.hlo_text import (COLLECTIVE_OPS,  # noqa: F401 (re-export)
                                   as_hlo_text, first_group,
                                   maybe_collective, payload_bytes,
                                   ring_wire_bytes, split_computations,
                                   while_multipliers)


# ---------------------------------------------------------------------------
# the table / report
# ---------------------------------------------------------------------------

def collective_table(compiled_or_text, default_world: int = 1
                     ) -> List[Dict[str, Any]]:
    """One row per collective instruction in the optimized HLO:
    {op, out_bytes, group_size, wire_bytes, trip_count, dynamic_trip,
    group_ranks, line}.  wire_bytes is PER EXECUTION; multiply by
    trip_count for per-step totals (collective_report does).  Accepts a
    compiled executable (as_text()) or the HLO text itself."""
    txt = as_hlo_text(compiled_or_text)
    comps = split_computations(txt)
    mults = while_multipliers(comps)
    rows = []
    for cname, lines in comps.items():
        trip, dynamic = mults.get(cname, (1, False))
        for line in lines:
            found = maybe_collective(line)
            if found is None:
                continue
            base, is_start, m = found
            out_bytes = payload_bytes(m.group("out"), is_start)
            n, ranks = first_group(line, default_world)
            rows.append({
                "op": base,
                "out_bytes": out_bytes,
                "group_size": n,
                "wire_bytes": ring_wire_bytes(base, out_bytes, n, is_start),
                "trip_count": trip,
                "dynamic_trip": dynamic,
                "group_ranks": ranks,
                "line": line.strip()[:200],
            })
    return rows


def _row_rate_class(row, topo) -> str:
    """"intra" | "inter" | "p2p" — which bandwidth prices this row."""
    if row["op"] == "collective-permute":
        return "p2p"
    if topo is None:
        return "intra"
    ranks = row.get("group_ranks")
    if not ranks:
        return "intra"
    return topo.classify_group(ranks)


def collective_report(compiled_or_text, *, hw: Optional[Dict] = None,
                      default_world: int = 1) -> Dict[str, Any]:
    """Aggregate bytes-on-wire report for one compiled step.

    {collectives: {op: {count, wire_bytes}}, num_collectives,
     total_wire_bytes, predicted_comm_s, predicted_comm_s_intra,
     predicted_comm_s_inter, dynamic_trip_count, chip} — counts and bytes
    include while-loop trip multipliers; predicted_comm_s is the serial
    ring-time estimate over the profile's rates (an upper bound: real
    collectives overlap compute), with slice-spanning groups priced at
    the topology's inter-slice rate when the profile declares one."""
    rows = collective_table(compiled_or_text, default_world)
    if hw is None:
        from hetu_tpu.obs.mfu import load_hardware_profile
        hw = load_hardware_profile()
    from hetu_tpu.comm.topology import Topology
    topo = Topology.from_profile(hw)
    ar_bw = float(hw.get("ici_allreduce_gbps", 45.0)) * 1e9
    p2p_bw = float(hw.get("ici_p2p_gbps", 90.0)) * 1e9
    intra_bw = topo.intra_gbps * 1e9 if topo else ar_bw
    inter_bw = topo.inter_gbps * 1e9 if topo else ar_bw
    per_op: Dict[str, Dict[str, float]] = {}
    t_intra = t_inter = t_p2p = 0.0
    b_inter = 0.0
    total = 0.0
    dynamic = False
    for r in rows:
        trip = max(int(r["trip_count"]), 1)
        dynamic = dynamic or r["dynamic_trip"]
        wb = r["wire_bytes"] * trip
        rec = per_op.setdefault(r["op"], {"count": 0, "wire_bytes": 0.0})
        rec["count"] += trip
        rec["wire_bytes"] += wb
        total += wb
        cls = _row_rate_class(r, topo)
        if cls == "p2p":
            t_p2p += wb / p2p_bw
        elif cls == "inter":
            t_inter += wb / inter_bw
            b_inter += wb
        else:
            t_intra += wb / intra_bw
    report: Dict[str, Any] = {
        "collectives": per_op,
        "num_collectives": sum(int(rec["count"])
                               for rec in per_op.values()),
        "total_wire_bytes": total,
        "predicted_comm_s": t_intra + t_inter + t_p2p,
        "predicted_comm_s_intra": t_intra,
        "predicted_comm_s_inter": t_inter,
        # the intra/inter BYTE split (p2p rows count as intra here):
        # a flat slice-spanning collective lands its whole payload in
        # wire_bytes_inter, a two-level schedule only its 1/slice
        # exchange — the measurable half of the HetCCL/HAllToAll claim
        "wire_bytes_intra": total - b_inter,
        "wire_bytes_inter": b_inter,
        "chip": hw.get("chip", "unknown"),
    }
    if dynamic:
        report["dynamic_trip_count"] = True
    return report
