"""Chrome-trace (chrome://tracing / Perfetto JSON) exporter.

Three sources feed one timeline format:

1. **Schedule renders** — the pipeline engines' own schedule structures
   (`parallel.pipeline_1f1b.schedule_validity`,
   `parallel.pipeline.gpipe_schedule_validity`) drawn as per-stage lanes
   with fwd/bwd/bubble events, so "what does my 1F1B schedule look like"
   is answerable without hardware (the reference draws the same picture
   from its per-op event records, SURVEY §5.1).
2. **Run events** — RunLog records (steps, hot-switch phases, elastic
   re-mesh epochs) converted into wall-clock spans.
3. **Serving flight-recorder traces** — `span` RunLog records
   (HETU_TPU_SERVE_TRACE, obs/spans.py) rendered as one lane per decode
   slot showing request occupancy, a queue lane, counter lanes for
   queue depth / page utilization, and instants for
   admissions/evictions/reshards (`serving_trace`).  Serving records
   also ride `merge_runlogs`, so a serving worker's lifecycle merges
   into the same cluster timeline as training RunLogs.

Open the saved JSON at https://ui.perfetto.dev or chrome://tracing.

Format: the Trace Event JSON array form — each event carries at least
`name`, `ph`, `ts` (microseconds), `pid`; complete events ("ph": "X") add
`dur`; instant events use "ph": "i"; counter lanes use "ph": "C".
"""
from __future__ import annotations

import contextlib
import json
import math
import time
from typing import Any, Dict, Iterable, List, Optional


class ChromeTrace:
    """Accumulates trace events; `save()`/`to_json()` emit the JSON array
    form that chrome://tracing and Perfetto accept directly."""

    def __init__(self):
        self.events: List[Dict[str, Any]] = []
        self._t0 = time.perf_counter()

    # ------------------------------------------------------------------
    def add_complete(self, name: str, ts_us: float, dur_us: float, *,
                     pid: Any = 0, tid: Any = 0, cat: str = "",
                     args: Optional[Dict] = None):
        ev = {"name": name, "ph": "X", "ts": float(ts_us),
              "dur": float(dur_us), "pid": pid, "tid": tid}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self.events.append(ev)

    def add_instant(self, name: str, ts_us: float, *, pid: Any = 0,
                    tid: Any = 0, cat: str = "",
                    args: Optional[Dict] = None):
        ev = {"name": name, "ph": "i", "ts": float(ts_us), "pid": pid,
              "tid": tid, "s": "p"}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self.events.append(ev)

    def add_flow(self, name: str, flow_id: Any, *, start_ts_us: float,
                 finish_ts_us: float, start_pid: Any = 0,
                 start_tid: Any = 0, finish_pid: Any = 0,
                 finish_tid: Any = 0, cat: str = "flow"):
        """Flow-event pair ("ph": "s" / "f") — Perfetto draws an arrow
        from the slice enclosing the start point to the slice enclosing
        the finish point, connecting lanes (tiers) causally.  The pair
        is matched by (cat, id); `bp: "e"` binds the finish to the
        ENCLOSING slice rather than the next one."""
        self.events.append({"name": name, "ph": "s", "id": str(flow_id),
                            "ts": float(start_ts_us), "pid": start_pid,
                            "tid": start_tid, "cat": cat})
        self.events.append({"name": name, "ph": "f", "bp": "e",
                            "id": str(flow_id),
                            "ts": float(finish_ts_us), "pid": finish_pid,
                            "tid": finish_tid, "cat": cat})

    def add_counter(self, name: str, ts_us: float, values: Dict[str, float],
                    *, pid: Any = 0):
        """Counter event ("ph": "C") — Perfetto draws each series of
        `values` as a stacked area lane under `name`."""
        self.events.append({"name": name, "ph": "C", "ts": float(ts_us),
                            "pid": pid,
                            "args": {k: float(v)
                                     for k, v in values.items()}})

    def name_thread(self, pid: Any, tid: Any, name: str):
        self.events.append({"name": "thread_name", "ph": "M", "pid": pid,
                            "tid": tid, "ts": 0,
                            "args": {"name": name}})

    def name_process(self, pid: Any, name: str):
        self.events.append({"name": "process_name", "ph": "M", "pid": pid,
                            "tid": 0, "ts": 0, "args": {"name": name}})

    @contextlib.contextmanager
    def span(self, name: str, *, pid: Any = 0, tid: Any = 0, cat: str = "",
             args: Optional[Dict] = None):
        """Wall-clock complete event over the with-block (ts relative to
        trace construction)."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            t1 = time.perf_counter()
            self.add_complete(name, (t0 - self._t0) * 1e6,
                              (t1 - t0) * 1e6, pid=pid, tid=tid, cat=cat,
                              args=args)

    # ------------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(self.events)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json())
        return path


# ---------------------------------------------------------------------------
# schedule renders
# ---------------------------------------------------------------------------

def pipeline_schedule_trace(pp: int, n_micro: int, *,
                            schedule: str = "1f1b",
                            fwd_us: float = 1000.0,
                            bwd_us: float = 2000.0) -> ChromeTrace:
    """Render a micro-batch pipeline schedule as per-stage timeline lanes.

    Lanes come from the engines' OWN schedule structures, so the picture is
    the executed schedule, not a diagram: 1F1B uses
    pipeline_1f1b.schedule_validity (lockstep rounds, fwd half + bwd half),
    GPipe uses pipeline.gpipe_schedule_validity (fill/steady forwards, then
    the autodiff-reversed backwards).  `fwd_us`/`bwd_us` are per-micro
    nominal durations (B ~ 2F by default); feed measured values for a
    to-scale render.
    """
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"schedule must be '1f1b' or 'gpipe', "
                         f"got {schedule!r}")
    tr = ChromeTrace()
    pid = f"pipeline/{schedule}"
    tr.name_process(pid, f"{schedule} pp={pp} n_micro={n_micro}")
    for s in range(pp):
        tr.name_thread(pid, s, f"stage {s}")

    def lane(stage, t0, dur, kind, micro=None):
        if kind == "bubble":
            tr.add_complete("bubble", t0, dur, pid=pid, tid=stage,
                            cat="bubble")
        else:
            tr.add_complete(f"{'F' if kind == 'fwd' else 'B'}{micro}",
                            t0, dur, pid=pid, tid=stage, cat=kind,
                            args={"micro": int(micro), "stage": int(stage)})

    if schedule == "1f1b":
        from hetu_tpu.parallel.pipeline_1f1b import schedule_validity
        fwd, bwd = schedule_validity(pp, n_micro)
        round_us = fwd_us + bwd_us
        for r in range(fwd.shape[0]):
            t0 = r * round_us
            for s in range(pp):
                if fwd[r, s]:
                    lane(s, t0, fwd_us, "fwd", r - s)
                else:
                    lane(s, t0, fwd_us, "bubble")
                if bwd[r, s]:
                    lane(s, t0 + fwd_us, bwd_us, "bwd",
                         r - 2 * (pp - 1) + s)
                else:
                    lane(s, t0 + fwd_us, bwd_us, "bubble")
    else:
        from hetu_tpu.parallel.pipeline import gpipe_schedule_validity
        valid = gpipe_schedule_validity(pp, n_micro)
        T = valid.shape[0]
        for t in range(T):
            for s in range(pp):
                if valid[t, s]:
                    lane(s, t * fwd_us, fwd_us, "fwd", t - s)
                else:
                    lane(s, t * fwd_us, fwd_us, "bubble")
        # the GPipe backward is scan autodiff: ticks replay in REVERSE
        bwd_base = T * fwd_us
        for k, t in enumerate(reversed(range(T))):
            for s in range(pp):
                if valid[t, s]:
                    lane(s, bwd_base + k * bwd_us, bwd_us, "bwd", t - s)
                else:
                    lane(s, bwd_base + k * bwd_us, bwd_us, "bubble")
    return tr


def schedule_bubble_fraction(pp: int, n_micro: int,
                             schedule: str = "1f1b",
                             fwd_us: float = 1.0,
                             bwd_us: float = 2.0) -> float:
    """Fraction of lane time spent idle in the rendered schedule — the
    analytic pipeline-bubble overhead ((pp-1)/(n_micro+pp-1) for GPipe)."""
    tr = pipeline_schedule_trace(pp, n_micro, schedule=schedule,
                                 fwd_us=fwd_us, bwd_us=bwd_us)
    busy = sum(e["dur"] for e in tr.events
               if e.get("ph") == "X" and e.get("cat") in ("fwd", "bwd"))
    idle = sum(e["dur"] for e in tr.events
               if e.get("ph") == "X" and e.get("cat") == "bubble")
    total = busy + idle
    return idle / total if total else 0.0


# ---------------------------------------------------------------------------
# run-event conversion (RunLog -> timeline)
# ---------------------------------------------------------------------------

def _name_run_lanes(tr: ChromeTrace, pid: Any, title: str,
                    serving: bool = False):
    tr.name_process(pid, title)
    tr.name_thread(pid, "train", "train steps")
    tr.name_thread(pid, "switch", "hot switches")
    tr.name_thread(pid, "elastic", "elastic epochs")
    tr.name_thread(pid, "health", "anomalies / faults / stragglers")
    if serving:
        tr.name_thread(pid, "serving", "serving requests / spans")


def _has_serving(recs: Iterable[Dict[str, Any]]) -> bool:
    return any(r.get("kind") in ("serve", "span") for r in recs)


def _driver_to_wall_offset(recs: List[Dict[str, Any]]) -> Optional[float]:
    """Wall seconds to add to a serving record's DRIVER-clock stamp
    (`span` t0/t1, `serve` now) to land it on this log's wall timeline.
    Estimated once from the first stamped record: the engine's virtual
    clock idle-skips and compresses wall time, so per-record anchoring
    would overlap spans — one run-level offset keeps the serving lane
    internally consistent (and exact for live servers, where the driver
    clock IS wall time)."""
    for r in recs:
        if r.get("kind") == "span" and r.get("t1") is not None:
            return float(r["t"]) - float(r["t1"])
        if r.get("kind") == "serve" and r.get("now") is not None:
            return float(r["t"]) - float(r["now"])
    return None


def _emit_run_events(tr: ChromeTrace, recs: List[Dict[str, Any]],
                     pid: Any, t0: float, offset_s: float = 0.0):
    """Draw RunLog records into `tr` under process `pid`; each record's
    wall time is shifted by `offset_s` (a worker-clock -> reference-clock
    correction) before being made relative to `t0`."""
    drv_off = _driver_to_wall_offset(recs)
    for r in recs:
        ts = (float(r["t"]) + offset_s - t0) * 1e6
        kind = r.get("kind")
        if kind == "step":
            dur = float(r.get("step_time_s") or 0.0) * 1e6
            # RunLog stamps t at record time (step END); draw from start
            tr.add_complete(f"step {r.get('step')}", ts - dur, dur,
                            pid=pid, tid="train", cat="step",
                            args={k: r[k] for k in
                                  ("loss", "tokens_per_s", "plan",
                                   "device_mem_bytes")
                                  if r.get(k) is not None})
        elif kind == "switch":
            dur = float(r.get("wall_s") or 0.0) * 1e6
            tr.add_complete(
                f"switch {r.get('from_id')}->{r.get('to_id')}", ts - dur,
                dur, pid=pid, tid="switch", cat="switch",
                args={k: r[k] for k in ("moved_bytes", "total_bytes")
                      if r.get(k) is not None})
        elif kind == "elastic_epoch":
            tr.add_instant(f"epoch {r.get('epoch')}", ts, pid=pid,
                           tid="elastic", cat="elastic",
                           args={"alive": r.get("alive")})
        elif kind == "compile":
            dur = float(r.get("compile_s") or 0.0) * 1e6
            tr.add_complete(f"compile {r.get('name')}", ts - dur, dur,
                            pid=pid, tid="train", cat="compile")
        elif kind == "anomaly":
            tr.add_instant(f"anomaly {r.get('anomaly')}", ts, pid=pid,
                           tid="health", cat="anomaly",
                           args={k: r[k] for k in ("step", "value",
                                                   "baseline")
                                 if r.get(k) is not None})
        elif kind == "fault":
            tr.add_instant(f"fault {r.get('fault')}", ts, pid=pid,
                           tid="health", cat="fault",
                           args={k: r[k] for k in ("step", "detail",
                                                   "error", "generation")
                                 if r.get(k) is not None})
        elif kind == "straggler":
            tr.add_instant("straggler report", ts, pid=pid, tid="health",
                           cat="straggler",
                           args={"stragglers": r.get("stragglers")})
        elif kind == "span" and r.get("t0") is not None \
                and drv_off is not None:
            # serving flight-recorder spans in the MERGED view: driver
            # stamps mapped onto the wall timeline through the ONE
            # run-level offset (per-record anchoring would overlap
            # spans whenever the virtual clock idle-skipped).  The
            # per-slot driver-clock picture is `serving_trace`'s job.
            s0 = (float(r["t0"]) + drv_off + offset_s - t0) * 1e6
            dur = max(0.0, float(r.get("t1", r["t0"]))
                      - float(r["t0"])) * 1e6
            tr.add_complete(f"r{r.get('req')} {r.get('span')}", s0,
                            dur, pid=pid, tid="serving",
                            cat=f"span:{r.get('span')}",
                            args={k: r[k] for k in
                                  ("slot", "slo_class", "reason",
                                   "tokens", "chunk", "segment")
                                  if r.get(k) is not None})
        elif kind == "numerics":
            # one counter lane per scope (ph "C"): Perfetto draws each
            # scope's scalar stats as stacked area over the run — the
            # numerics observatory's timeline view (underflow ramps and
            # SNR collapses are visible as shape, not just as the
            # detector firings on the health lane)
            for scope, stats in sorted((r.get("scopes") or {}).items()):
                vals = {name: float(v) for name, v in stats.items()
                        if isinstance(v, (int, float))
                        and math.isfinite(float(v))
                        and name in ("rms", "absmax", "underflow_frac",
                                     "overflow_frac", "snr_db",
                                     "entropy", "load_max", "dropped")}
                if vals:
                    tr.add_counter(f"numerics/{scope}", ts, vals, pid=pid)
        elif kind == "scaler":
            tr.add_instant(f"scaler {r.get('event')}", ts, pid=pid,
                           tid="health", cat="scaler",
                           args={k: r[k] for k in ("scale", "prev", "step")
                                 if r.get(k) is not None})
        elif kind == "serve":
            ev = r.get("event")
            if ev in ("admit", "done", "reshard"):
                if r.get("now") is not None and drv_off is not None:
                    ts = (float(r["now"]) + drv_off + offset_s - t0) * 1e6
                tr.add_instant(f"serve {ev} r{r.get('req')}"
                               if r.get("req") is not None
                               else f"serve {ev}", ts, pid=pid,
                               tid="serving", cat=f"serve:{ev}",
                               args={k: r[k] for k in
                                     ("slot", "reason", "tier", "tenant",
                                      "queue_depth", "page_util")
                                     if r.get(k) is not None})


def trace_from_runlog(records: Iterable[Dict[str, Any]]) -> ChromeTrace:
    """Convert RunLog records into a wall-clock timeline: step spans on a
    'train' lane, hot-switch phases on a 'switch' lane, elastic epochs as
    instants on an 'elastic' lane, anomalies/faults/straggler reports on
    a 'health' lane."""
    recs = [r for r in records if isinstance(r, dict) and "t" in r]
    tr = ChromeTrace()
    if not recs:
        return tr
    t0 = min(float(r["t"]) for r in recs)
    pid = "run"
    _name_run_lanes(tr, pid, "training run", serving=_has_serving(recs))
    _emit_run_events(tr, recs, pid, t0)
    return tr


def numerics_trace(records: Iterable[Dict[str, Any]]) -> ChromeTrace:
    """Standalone numerics timeline: ONLY the per-scope counter lanes
    (plus scaler transitions and anomaly instants for context) from a
    RunLog — what ``tools_numerics.py --chrome-trace`` writes.  The full
    run view (steps/compiles/serving interleaved) is
    :func:`trace_from_runlog`'s job; this one stays readable when a long
    run's step lane would drown the counters."""
    recs = [r for r in records if isinstance(r, dict) and "t" in r
            and r.get("kind") in ("numerics", "scaler", "anomaly")]
    tr = ChromeTrace()
    if not recs:
        return tr
    t0 = min(float(r["t"]) for r in recs)
    pid = "numerics"
    tr.name_process(pid, "numerics observatory")
    _emit_run_events(tr, recs, pid, t0)
    return tr


def merge_runlogs(runlogs: Dict[Any, Iterable[Dict[str, Any]]],
                  offsets_s: Optional[Dict[Any, float]] = None
                  ) -> ChromeTrace:
    """Merge several workers' RunLogs into ONE cluster timeline: pid per
    worker, the same lanes per worker as `trace_from_runlog`, timestamps
    aligned onto a common (server) clock via per-worker offsets.

    `runlogs` maps worker id -> records (e.g. ``RunLog.read(path)`` per
    worker); `offsets_s` maps worker id -> that worker's clock offset in
    seconds (server_time ~= worker_time + offset).  The coordinator
    estimates offsets from heartbeat-RTT-corrected telemetry pushes —
    take them from a ClusterSnapshot with
    ``obs.aggregate.merge_offsets(snapshot)``.  Missing offsets default
    to 0 (same-host workers)."""
    offsets = offsets_s or {}
    per: Dict[Any, List[Dict[str, Any]]] = {}
    for worker, records in runlogs.items():
        per[worker] = [r for r in records
                       if isinstance(r, dict) and "t" in r]
    tr = ChromeTrace()
    all_t = [float(r["t"]) + float(offsets.get(w, 0.0))
             for w, recs in per.items() for r in recs]
    if not all_t:
        return tr
    t0 = min(all_t)
    for worker in sorted(per, key=str):
        off = float(offsets.get(worker, 0.0))
        pid = f"worker {worker}"
        _name_run_lanes(tr, pid, f"worker {worker}",
                        serving=_has_serving(per[worker]))
        _emit_run_events(tr, per[worker], pid, t0, offset_s=off)
    return tr


# ---------------------------------------------------------------------------
# serving flight-recorder render (span records -> per-slot lanes)
# ---------------------------------------------------------------------------

def serving_trace(records: Iterable[Dict[str, Any]], *,
                  pid: Any = "serving") -> ChromeTrace:
    """Render a serving run's flight-recorder records as the per-slot
    timeline (driver-clock basis, so a replayed virtual-clock run draws
    deterministically):

    * one lane per decode slot — each request's prefill chunks, decode
      segments and reshard pauses drawn where the slot actually spent
      its time (`r<rid> <kind>` complete events, cat = span kind),
    * a ``queue`` lane with every request's queued span (args carry the
      no_slot/no_pages stall attribution),
    * an ``events`` lane with admission / eviction(done) / reshard
      instants (from the ``serve`` events' driver-clock ``now`` stamp),
    * counter lanes ``queue_depth`` and ``page_util`` sampled at every
      serve event.

    Open at https://ui.perfetto.dev.  Records come straight from
    ``RunLog.read``; non-serving records are ignored, so a mixed log
    renders its serving slice."""
    from hetu_tpu.obs.spans import collect_traces
    recs = [r for r in records if isinstance(r, dict)]
    traces = collect_traces(recs)
    tr = ChromeTrace()
    tr.name_process(pid, "serving engine")
    tr.name_thread(pid, "queue", "queue (stall attribution)")
    tr.name_thread(pid, "events", "admissions / evictions / reshards")
    slots = sorted({s.slot for t in traces.values() for s in t.spans
                    if s.slot is not None})
    for s in slots:
        tr.name_thread(pid, f"slot {s}", f"decode slot {s}")

    for rid in sorted(traces):
        t = traces[rid]
        for sp in t.spans:
            args = dict(sp.attrs, slo_class=sp.slo_class, trace=sp.trace)
            ts, dur = sp.t0 * 1e6, sp.dur_s * 1e6
            if sp.kind == "queued":
                tr.add_complete(f"r{rid} queued", ts, dur, pid=pid,
                                tid="queue", cat="queued", args=args)
            elif sp.kind in ("prefill", "decode", "reshard_pause"):
                tid = f"slot {sp.slot}" if sp.slot is not None else "queue"
                tr.add_complete(f"r{rid} {sp.kind}", ts, dur, pid=pid,
                                tid=tid, cat=sp.kind, args=args)
            else:   # terminal: a zero-duration marker on the slot lane
                tid = f"slot {sp.slot}" if sp.slot is not None else "events"
                tr.add_instant(f"r{rid} {sp.kind}", ts, pid=pid, tid=tid,
                               cat=sp.kind, args=args)

    for r in recs:
        if r.get("kind") != "serve" or r.get("now") is None:
            continue
        ts = float(r["now"]) * 1e6
        ev = r.get("event")
        if ev in ("admit", "done", "reshard"):
            tr.add_instant(f"{ev} r{r.get('req')}"
                           if r.get("req") is not None else ev,
                           ts, pid=pid, tid="events", cat=f"serve:{ev}",
                           args={k: r[k] for k in
                                 ("slot", "reason", "tier", "slo_class",
                                  "tenant")
                                 if r.get(k) is not None})
        counters = {k: r[k] for k in ("queue_depth", "page_util")
                    if r.get(k) is not None}
        for name, v in counters.items():
            tr.add_counter(name, ts, {name: v}, pid=pid)
    return tr


# ---------------------------------------------------------------------------
# stitched fleet render (FleetTrace DAGs -> flow-connected tier lanes)
# ---------------------------------------------------------------------------

def stitched_trace(fleet_traces, *, pid: Any = "fleet") -> ChromeTrace:
    """Render stitched :class:`obs.spans.FleetTrace` DAGs as ONE
    flow-connected multi-tier timeline (what ``tools_fleet.py
    --chrome-trace`` writes when the run was traced):

    * one lane per fleet hop identity (``prefill/0``, ``decode/1``, a
      bare ``decode`` for unstamped single-engine runs) plus a
      ``frontend`` lane — each hop's spans drawn as complete events,
      terminals as instants,
    * every causal edge drawn as a **flow arrow** ("ph": "s"/"f" pairs,
      matched by id, finish bound to the enclosing slice via
      ``bp: "e"``) connecting the lanes: dispatch (frontend -> hop
      queued), ship/adopt (prefill -> decode), hedge fork/win/withdraw,
      replay re-admissions and dead-tier fallbacks,

    so the cross-tier causality that `FleetTrace.validate` checks
    numerically is *visible* — follow the arrows from frontend through
    prefill and shipment into the decode lane that produced the client
    result.  Accepts the dict `FleetTrace.stitch` returns (or any
    iterable of FleetTraces); timestamps are the spans' own (driver)
    clock basis, so replayed virtual-clock runs draw deterministically."""
    from hetu_tpu.obs.spans import TERMINAL_KINDS
    tr = ChromeTrace()
    tr.name_process(pid, "fleet (stitched)")
    tr.name_thread(pid, "frontend", "frontend / client")
    fts = (fleet_traces.values() if isinstance(fleet_traces, dict)
           else list(fleet_traces))
    fts = sorted(fts, key=lambda ft: ft.rid)
    lanes: Dict[str, str] = {}      # hop trace id -> lane tid
    for ft in fts:
        for h in ft.hops:
            lanes.setdefault(h.trace, ft.hop_label(h))
    for tid in sorted(set(lanes.values())):
        tr.name_thread(pid, tid, f"{tid} hop")

    def lane_of(trace_id: Any) -> str:
        return lanes.get(trace_id, "frontend")

    def enclosing_ts(trace_id: Any, t_us: float) -> float:
        """Nudge a flow endpoint inside the hop's span coverage so the
        arrow binds to a slice (edges stamp the boundary instant, which
        can fall exactly between two slices)."""
        hop = hop_by_trace.get(trace_id)
        if hop is None or not hop.spans:
            return t_us
        lo, hi = hop.spans[0].t0 * 1e6, hop.spans[-1].t1 * 1e6
        return min(max(t_us, lo), hi)

    flow_id = 0
    for ft in fts:
        hop_by_trace = {h.trace: h for h in ft.hops}
        for h in ft.hops:
            tid = lanes[h.trace]
            for sp in h.spans:
                args = dict(sp.attrs, slo_class=sp.slo_class,
                            trace=sp.trace)
                ts = sp.t0 * 1e6
                if sp.kind in TERMINAL_KINDS:
                    tr.add_instant(f"r{ft.rid} {sp.kind}", ts, pid=pid,
                                   tid=tid, cat=sp.kind, args=args)
                else:
                    tr.add_complete(f"r{ft.rid} {sp.kind}", ts,
                                    max(0.0, sp.t1 - sp.t0) * 1e6,
                                    pid=pid, tid=tid, cat=sp.kind,
                                    args=args)
        for e in ft.edges:
            t_us = float(e.get("t", 0.0)) * 1e6
            src, dst = lane_of(e.get("src")), lane_of(e.get("dst"))
            flow_id += 1
            tr.add_flow(f"r{ft.rid} {e['kind']}", f"r{ft.rid}.{flow_id}",
                        start_ts_us=enclosing_ts(e.get("src"), t_us),
                        finish_ts_us=enclosing_ts(e.get("dst"), t_us),
                        start_pid=pid, start_tid=src,
                        finish_pid=pid, finish_tid=dst,
                        cat=f"edge:{e['kind']}")
    return tr
