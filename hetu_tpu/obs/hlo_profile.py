"""Analytic step profiler: per-layer HLO attribution + peak-HBM accounting.

The TPU tunnel being down must not stop perf attribution at coarse
phases: this module walks the POST-OPTIMIZATION HLO of a compiled train
step (any backend, incl. the 8-device CPU test mesh) and attributes
FLOPs, HBM traffic (output bytes), and bytes-on-wire **per named
layer/op-group** — the `jax.named_scope` names the model stack emits
(`layer_3/attn`, `layer_3/mlp`, `embed`, `lm_head`, `optimizer`,
`grad_sync`; scanned stacks collapse to one `layer/...` group whose
while-loop trip count multiplies through).  Three measurements, one
text walk:

* **per-group attribution** (`layer_table`) — the same line scan
  `utils.profiling.phase_breakdown` does, refined to full scope paths
  and extended with parsed dot FLOPs (2 * out_elems * contraction from
  each `dot(...)` line's operand shapes) and the ring wire bytes of any
  collective in the group (`obs.comm`'s formulas — ONE byte model).
  Sums reconcile with the coarse phases by construction: both walks
  count the same `op_name=` lines (tested).

* **roofline per group** (`layer_profile`) — each group bounded by
  max(flops/compute_rate, out_bytes/hbm_rate) + wire_bytes/ici_rate
  over the hardware profile, rendered as an **analytic flame graph**
  (`flame_trace` — a Chrome-trace lane of predicted per-group times
  next to the schedule traces obs.trace already draws).

ALL HLO-text parsing primitives (line anatomy, shapes, collectives,
while-trip/call-graph multipliers, dot FLOPs, donation contracts) live
in `hetu_tpu.obs.hlo_text` — one tokenizer shared with the bytes-on-wire
analyzer (obs/comm.py) and the graph-contract linter
(hetu_tpu/analysis/).  This module owns only the attribution, roofline
and liveness ACCOUNTING layered on top.

* **peak-HBM estimate** (`peak_hbm_estimate`) — a liveness sweep over
  the HLO: every non-parameter instruction's output buffer is live from
  its definition to its last use; while bodies contribute their own
  internal peak (buffers REUSED across trips — which is exactly why a
  remat'd scanned stack peaks at one layer's working set, not L of
  them); fusion internals never materialize.  peak = entry argument
  bytes (params + optimizer state + batch) + the sweep's max live set,
  cross-checked against `compiled.memory_analysis()` when the backend
  exposes it (the `search/calibrate.py` source of truth).  The analytic
  twin (`analytic_peak_hbm`) prices params + Adam moments + grads +
  remat-aware activations from a model config alone — the bench
  fallback when nothing can even lower, and the cost model's
  feasibility term (`search/cost_model.py` `fits_hbm`).

Consumers: Trainer compile run-events (`HETU_TPU_PROFILE=1` -> a
schema-versioned `profile` RunLog record, `profile_record`),
`Trainer.profile_report`, bench.py (`detail.profile`: top-k groups +
peak HBM), tools_obs_report.py (the `profile` section), and the
regression sentinel (`obs/budget.py` + tools_bench_diff.py) that diffs
these numbers across rounds against declared budgets.

Known limits: GSPMD-inserted collectives (the implicit DP grad
all-reduce) carry the scope of the op that PRODUCED their operand, so
the explicit-comm paths (`grad_sync`) attribute exactly while implicit
ones attribute to their producing layer; `dynamic_trip_count` loops
count once (same caveat as obs/comm, surfaced in the report).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from hetu_tpu.obs.hlo_text import (BRANCH_PAT, DEF_PAT, OP_NAME_PAT,
                                   OUT_PAT, REF_PAT, as_hlo_text,
                                   call_multipliers, dot_flops,
                                   entry_computation, line_wire_bytes,
                                   shape_bytes, split_computations)
from hetu_tpu.utils.profiling import PHASES

#: version stamp of the `profile` RunLog record / BENCH detail.profile
#: payload (the same stability contract as obs.runlog.SCHEMA_VERSION:
#: new optional fields may be added within a version, none renamed)
PROFILE_SCHEMA = 1

#: scope names that form an op-group on their own (next to the
#: per-layer `layer_<i>` scopes and the model phases)
EXTRA_GROUPS = ("optimizer", "grad_sync")

#: every dispatcher in the fused-kernel layer enters its Pallas call
#: under a `pallas_<kernel>` named scope (ops/pallas, docs/kernels.md);
#: instructions under one — the custom-call on TPU, the interpreted
#: kernel body on the CPU test mesh — are attributed to that kernel
#: group: `layer_3/attn/pallas_flash_attention` rows in `layer_table`,
#: aggregated across groups by `kernel_table`
KERNEL_SCOPE_PREFIX = "pallas_"

# scope-path patterns (the profiler's own layer — everything below the
# line/shape level comes from obs.hlo_text)
_LAYER_SEG_PAT = re.compile(r'^layer(_\d+)?$')
_TRANSFORM_PAT = re.compile(r'^[\w.\-]+\((.*)\)$')


# ---------------------------------------------------------------------------
# scope-path parsing
# ---------------------------------------------------------------------------

def scope_segments(op_name: str) -> List[str]:
    """`jit(f)/jit(main)/transpose(jvp(layer_1))/attn/dot_general` ->
    ["f", "main", "layer_1", "attn", "dot_general"]: each '/'-separated
    token unwrapped of its transform wrappers (jvp/transpose/jit/remat
    ...), so forward AND backward instructions land in the same group."""
    out = []
    for tok in op_name.split("/"):
        while True:
            m = _TRANSFORM_PAT.match(tok)
            if m is None or not m.group(1):
                break
            tok = m.group(1)
        if tok:
            out.append(tok)
    return out


def group_of(op_name: str, phases: Tuple[str, ...] = PHASES) -> str:
    """The attribution group of one instruction's scope path:
    `layer_<i>/<phase>` when both a layer scope and a phase scope are
    present, the layer alone, the phase alone (embed / lm_head /
    optimizer / grad_sync live outside layers), else "other".  A
    `pallas_<kernel>` scope (the fused-kernel layer's dispatchers)
    appends its kernel name, so the kernel's instructions form their own
    row WITHIN their layer/phase (`layer_0/attn/pallas_flash_attention`)
    instead of blending into the surrounding group."""
    segs = scope_segments(op_name)
    layer = next((s for s in reversed(segs)
                  if _LAYER_SEG_PAT.match(s)), None)
    known = (*phases, *EXTRA_GROUPS)
    phase = next((s for s in reversed(segs) if s in known), None)
    kernel = next((s for s in reversed(segs)
                   if s.startswith(KERNEL_SCOPE_PREFIX)), None)
    if layer and phase:
        base = f"{layer}/{phase}"
    elif layer:
        base = layer
    elif phase:
        base = phase
    elif kernel:
        return kernel
    else:
        return "other"
    return f"{base}/{kernel}" if kernel else base


# ---------------------------------------------------------------------------
# per-group attribution
# ---------------------------------------------------------------------------

def layer_table(compiled_or_text, *, phases: Tuple[str, ...] = PHASES,
                default_world: int = 1,
                apply_multipliers: bool = True
                ) -> Dict[str, Dict[str, float]]:
    """{group: {"instructions", "dots", "flops", "out_bytes",
    "wire_bytes"}} over the optimized HLO, execution multipliers
    applied (scanned layers count trip-count times).  Groups are
    `group_of` keys; an extra "_meta" entry carries
    {"dynamic_trip_count"} when some loop's trip was unresolvable.

    apply_multipliers=False counts each instruction ONCE (static) —
    exactly `utils.profiling.phase_breakdown`'s accounting (same lines,
    same output-shape anchoring), so per-group sums reconcile with the
    coarse per-phase totals; with multipliers on, wire-byte sums
    reconcile with `obs.comm.collective_report` instead (which resolves
    the same trip counts) — both are the attribution-consistency
    contract the tests pin."""
    txt = as_hlo_text(compiled_or_text)
    comps = split_computations(txt)
    mults = (call_multipliers(comps) if apply_multipliers
             else {name: (1.0, False) for name in comps})
    out: Dict[str, Dict[str, float]] = {}
    dynamic = False
    conv_unparsed = False

    def new_row():
        return {"instructions": 0.0, "dots": 0.0, "flops": 0.0,
                "out_bytes": 0.0, "wire_bytes": 0.0}
    for cname, lines in comps.items():
        mult, dyn = mults.get(cname, (1.0, False))
        for line in lines:
            m = OP_NAME_PAT.search(line)
            if m is None:
                # instructions without op_name metadata are outside the
                # phase accounting (phase_breakdown skips them too — the
                # static-sum contract), but a GSPMD-inserted collective
                # without metadata still moves real bytes: count its
                # wire bytes into "other" so wire sums reconcile with
                # obs.comm.collective_report on EVERY program
                wb = line_wire_bytes(line, default_world)
                if wb > 0:
                    out.setdefault("other", new_row())["wire_bytes"] += \
                        wb * mult
                    dynamic = dynamic or dyn
                continue
            dynamic = dynamic or dyn
            rec = out.setdefault(group_of(m.group(1), phases), new_row())
            rec["instructions"] += mult
            if " dot(" in line or " convolution(" in line:
                rec["dots"] += mult
                rec["flops"] += dot_flops(line) * mult
                if " convolution(" in line:
                    # conv FLOPs are not statically parsed (no conv in
                    # the model zoo today) — surface the undercount
                    # instead of silently attributing 0
                    conv_unparsed = True
            om = OUT_PAT.search(line)
            if om is not None:
                rec["out_bytes"] += shape_bytes(om.group(1)) * mult
            rec["wire_bytes"] += line_wire_bytes(line, default_world) * mult
    meta = {}
    if dynamic:
        meta["dynamic_trip_count"] = True
    if conv_unparsed:
        meta["conv_flops_unparsed"] = True
    if meta:
        out["_meta"] = meta
    return out


def kernel_table(compiled_or_text, *, phases: Tuple[str, ...] = PHASES,
                 default_world: int = 1) -> Dict[str, Dict[str, float]]:
    """Aggregate `layer_table` rows by Pallas kernel: every group whose
    path carries a `pallas_<kernel>` segment contributes to that
    kernel's totals ({kernel: {"instructions", "dots", "flops",
    "out_bytes", "wire_bytes", "groups"}}).  Empty when the program has
    no routed Pallas kernels — e.g. with HETU_TPU_PALLAS=0, which is
    exactly what the flag-off identity test leans on."""
    table = layer_table(compiled_or_text, phases=phases,
                        default_world=default_world)
    out: Dict[str, Dict[str, float]] = {}
    for group, row in table.items():
        if group == "_meta":
            continue
        kern = next((seg for seg in group.split("/")
                     if seg.startswith(KERNEL_SCOPE_PREFIX)), None)
        if kern is None:
            continue
        rec = out.setdefault(kern, {"instructions": 0.0, "dots": 0.0,
                                    "flops": 0.0, "out_bytes": 0.0,
                                    "wire_bytes": 0.0, "groups": []})
        for k in ("instructions", "dots", "flops", "out_bytes",
                  "wire_bytes"):
            rec[k] += row[k]
        rec["groups"].append(group)
    return out


def _layer_sort_key(group: str):
    """Model order: embed, layer_0..layer_n (or the scanned "layer"),
    lm_head, grad_sync, optimizer, unknown scopes, other."""
    head = group.split("/")[0]
    m = re.match(r'layer_(\d+)$', head)
    if m:
        return (1, int(m.group(1)), group)
    if head == "layer":
        return (1, -1, group)
    order = {"embed": 0, "lm_head": 2, "grad_sync": 3,
             "optimizer": 4, "other": 6}
    return (order.get(head, 5), 0, group)


def layer_profile(compiled_or_text, *, hw: Optional[Dict] = None,
                  phases: Tuple[str, ...] = PHASES,
                  default_world: int = 1) -> Dict[str, Any]:
    """Roofline-price the per-group attribution: each group's predicted
    time is max(flops/compute, out_bytes/hbm) + wire_bytes/ici over the
    hardware profile's rates.  Returns {"groups": {group: {...,
    "time_s", "bound"}}, "totals", "estimated_step_s", "top"} with
    groups in model order (embed, layer_0..n / scanned layer, lm_head,
    grad_sync, optimizer, other)."""
    from hetu_tpu.obs.mfu import _rates, load_hardware_profile
    hw = hw if hw is not None else load_hardware_profile()
    compute, hbm, _peak = _rates(hw)
    ici = float(hw.get("ici_allreduce_gbps", 45.0)) * 1e9
    table = layer_table(compiled_or_text, phases=phases,
                        default_world=default_world)
    meta = table.pop("_meta", None)
    groups: Dict[str, Dict[str, float]] = {}
    totals = {"instructions": 0.0, "dots": 0.0, "flops": 0.0,
              "out_bytes": 0.0, "wire_bytes": 0.0}
    t_total = 0.0
    for g in sorted(table, key=_layer_sort_key):
        rec = dict(table[g])
        t_c = rec["flops"] / compute
        t_m = rec["out_bytes"] / hbm
        t_w = rec["wire_bytes"] / ici
        rec["time_s"] = max(t_c, t_m) + t_w
        rec["bound"] = ("wire" if t_w > max(t_c, t_m)
                        else "memory" if t_m > t_c else "compute")
        groups[g] = rec
        t_total += rec["time_s"]
        for k in totals:
            totals[k] += rec[k]
    top = sorted(groups.items(), key=lambda kv: -kv[1]["time_s"])
    report: Dict[str, Any] = {
        "groups": groups,
        "totals": totals,
        "estimated_step_s": t_total,
        "top": [{"group": g, "time_s": r["time_s"], "flops": r["flops"],
                 "out_bytes": r["out_bytes"], "bound": r["bound"]}
                for g, r in top],
        "chip": hw.get("chip", "unknown"),
    }
    if meta:
        report.update(meta)
    return report


# ---------------------------------------------------------------------------
# peak-HBM accounting
# ---------------------------------------------------------------------------

#: opcodes whose output ALIASES their operands' storage 1:1 — counting
#: them as new buffers would double every while carry (tuple in,
#: get-tuple-element out) and inflate the liveness peak severalfold
_ALIAS_OPS = ("get-tuple-element", "tuple", "bitcast", "while",
              "optimization-barrier")


def _comp_peak(comps: Dict[str, List[str]], name: str,
               memo: Dict[str, float], seen: Tuple[str, ...] = (),
               donated: bool = False) -> float:
    """Liveness peak (bytes) of one computation's internal buffers —
    the analytic twin of XLA buffer assignment's temp arena, which
    packs buffers with disjoint live ranges into shared offsets:

    * each real def is live [def line, last use of it or any alias];
    * structural aliases (`_ALIAS_OPS` — gte/tuple/bitcast/while) add
      no storage and extend their roots' lifetimes;
    * in-place sharing: when a def's byte size equals a root that DIES
      at that very line, XLA's elementwise/fusion in-place reuse writes
      the output over the operand — modeled by extending the dying
      root's lifetime instead of allocating; with `donated=True` (the
      module declares input_output_alias) a dying entry PARAMETER's
      storage is reusable the same way — how a donated train step
      writes new params over old ones;
    * a `while` line additionally holds its body's peak while it runs
      (the body REUSES its buffers across trips — exactly why a
      remat'd scanned stack peaks at ONE layer's working set, not L);
      conditionals hold the max branch; fusion internals never
      materialize."""
    if name in memo:
        return memo[name]
    if name in seen or name not in comps:
        return 0.0
    lines = comps[name]
    parsed: List[Optional[Tuple[str, int, str, List[str]]]] = []
    roots: Dict[str, Tuple[str, ...]] = {}   # name -> storage roots
    transient: Dict[int, float] = {}         # line -> callee peak bytes
    persistent: Dict[str, int] = {}          # donated entry params

    def root_of(nm: str) -> Tuple[str, ...]:
        return roots.get(nm, (nm,))

    for i, ln in enumerate(lines):
        m = DEF_PAT.search(ln)
        if m is None:
            parsed.append(None)
            continue
        nm, op = m.group(1), m.group(3)
        operands = [r for r in REF_PAT.findall(ln) if r != nm]
        b = 0 if op in ("parameter",) + _ALIAS_OPS \
            else shape_bytes(m.group(2))
        if op == "parameter" and donated:
            persistent[nm] = shape_bytes(m.group(2))
        if op in _ALIAS_OPS:
            rs: Tuple[str, ...] = ()
            for o in operands:
                rs += root_of(o)
            roots[nm] = tuple(dict.fromkeys(rs)) or (nm,)
        parsed.append((nm, b, op, operands))
        if op == "while":
            bm = re.search(r'body=%?([\w.\-]+)', ln)
            if bm is not None:
                transient[i] = _comp_peak(comps, bm.group(1), memo,
                                          seen + (name,))
        elif op == "conditional":
            bm = BRANCH_PAT.search(ln)
            branches = (REF_PAT.findall(bm.group(1)) if bm else [])
            for cm in re.finditer(r'(?:true|false)_computation='
                                  r'%?([\w.\-]+)', ln):
                branches.append(cm.group(1))
            if branches:
                transient[i] = max(
                    _comp_peak(comps, b_, memo, seen + (name,))
                    for b_ in branches)
        elif op in ("call", "custom-call"):
            cm = re.search(r'to_apply=%?([\w.\-]+)', ln)
            if cm is not None:
                # the callee's ROOT buffer is the call's output — the
                # caller already counts it as this def, so the callee
                # peak contributes only its EXCESS over the output
                transient[i] = max(
                    _comp_peak(comps, cm.group(1), memo,
                               seen + (name,)) - b, 0.0)

    bytes_of: Dict[str, int] = {}
    def_line: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    for i, rec in enumerate(parsed):
        if rec is None:
            continue
        nm, b, op, operands = rec
        if b > 0:
            bytes_of[nm] = b
            def_line[nm] = i
            last_use[nm] = i
        for o in operands:
            for r in root_of(o):
                last_use[r] = i

    # sequential sweep with the in-place sharing heuristic
    events: List[Tuple[int, float]] = []
    for i, rec in enumerate(parsed):
        if rec is None:
            continue
        nm, b, op, operands = rec
        if b <= 0:
            continue
        reused = None
        if op not in ("constant", "iota", "parameter"):
            for o in operands:
                for r in root_of(o):
                    if ((bytes_of.get(r) == b or persistent.get(r) == b)
                            and last_use.get(r) == i and r != nm):
                        reused = r
                        break
                if reused:
                    break
        if reused is not None:
            # output takes over the dying operand's storage: fold this
            # def into the operand's buffer (alias) instead of a fresh
            # allocation, and let the operand's lifetime carry on
            roots[nm] = (reused,)
            last_use[reused] = max(last_use.get(reused, i),
                                   last_use.get(nm, i))
            bytes_of.pop(nm, None)
    for nm, b in bytes_of.items():
        events.append((def_line[nm], float(b)))
        events.append((last_use.get(nm, 0) + 1, -float(b)))
    for i, b in transient.items():
        if b > 0:
            events.append((i, float(b)))
            events.append((i + 1, -float(b)))
    events.sort(key=lambda e: (e[0], -e[1]))
    live = peak = 0.0
    for _, d in events:
        live += d
        peak = max(peak, live)
    memo[name] = peak
    return peak


def peak_hbm_estimate(compiled_or_text, *,
                      hw: Optional[Dict] = None,
                      text: Optional[str] = None) -> Dict[str, Any]:
    """Liveness-based peak-HBM estimate of one compiled step.

    peak_bytes = entry argument bytes (params + optimizer state + batch;
    donated args alias outputs, so they are NOT double-counted) + the
    liveness sweep's max concurrent non-parameter buffer set.  When the
    executable exposes `memory_analysis()` the XLA buffer-assignment
    numbers ride along as the cross-check (`xla_peak_bytes`,
    `vs_xla` ratio — the acceptance gate pins it within 20% on the
    tier-1 models).  `headroom_frac` prices the estimate against the
    profile's `hbm_gbytes` (>1.0 = the step does not fit).  `text` lets
    a caller that already materialized as_text() (profile_record) skip
    a second stringification of a large module."""
    txt = text if text is not None else (
        compiled_or_text if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text())
    comps = split_computations(txt)
    entry = entry_computation(txt, comps)
    args_bytes = 0.0
    for ln in comps.get(entry, []):
        m = DEF_PAT.search(ln)
        if m is not None and m.group(3) == "parameter":
            args_bytes += shape_bytes(m.group(2))
    # a module that declares input_output_alias writes (some) outputs
    # over its donated argument buffers — the entry sweep may model
    # in-place reuse of dying parameter storage
    donated = "input_output_alias" in txt
    memo: Dict[str, float] = {}
    temp_peak = _comp_peak(comps, entry, memo, donated=donated)
    report: Dict[str, Any] = {
        "args_bytes": args_bytes,
        "temp_peak_bytes": temp_peak,
        "peak_bytes": args_bytes + temp_peak,
        "donated": donated,
    }
    ma = None
    if not isinstance(compiled_or_text, str):
        try:
            ma = compiled_or_text.memory_analysis()
        except Exception:
            ma = None
    if ma is not None:
        try:
            # XLA's live peak: arguments + the temp arena + outputs that
            # do NOT alias (donate into) an argument buffer
            xla_args = float(ma.argument_size_in_bytes)
            xla_temp = float(ma.temp_size_in_bytes)
            xla_out = float(getattr(ma, "output_size_in_bytes", 0.0) or 0.0)
            xla_alias = float(getattr(ma, "alias_size_in_bytes", 0.0) or 0.0)
            report["xla_args_bytes"] = xla_args
            report["xla_temp_bytes"] = xla_temp
            report["xla_peak_bytes"] = (xla_args + xla_temp
                                        + max(xla_out - xla_alias, 0.0))
            if report["xla_peak_bytes"] > 0:
                report["vs_xla"] = (report["peak_bytes"]
                                    / report["xla_peak_bytes"])
        except Exception:
            pass
    from hetu_tpu.obs.mfu import load_hardware_profile
    hw = hw if hw is not None else load_hardware_profile()
    hbm = float(hw.get("hbm_gbytes", 0.0) or 0.0) * 1e9
    if hbm > 0:
        report["hbm_gbytes"] = hw["hbm_gbytes"]
        report["headroom_frac"] = report["peak_bytes"] / hbm
    return report


def analytic_peak_hbm(num_params: float, *, batch: int, seq: int,
                      hidden: int, num_layers: int, vocab: int,
                      dp: int = 1, tp: int = 1, pp: int = 1, cp: int = 1,
                      zero: bool = False, remat: bool = True,
                      sequence_parallel: bool = False,
                      act_boundary_units: float = 1.0,
                      act_full_units: float = 12.0,
                      param_bytes: int = 4) -> Dict[str, float]:
    """Jax-free per-device peak-HBM model: master params + grads at
    `param_bytes` each (4 = the fp32-master default matching
    `search/cost_model.py.per_device_memory`; 2 prices bf16-weight
    training), Adam m/v always fp32 (dp-sharded under ZeRO),
    remat-aware activations (boundary buffers only under remat, the
    calibrated full working set otherwise) + fp32 logits.  This is the
    bench fallback when nothing can even lower, and the term the
    searcher's feasibility gate rejects OOM plans by."""
    shard = max(tp * pp, 1)
    params = float(param_bytes) * num_params / shard
    opt = 8.0 * num_params / shard
    if zero and dp > 1:
        opt /= dp
    grads = float(param_bytes) * num_params / shard
    b_local = batch / max(dp * cp, 1)
    seq_local = seq / max(cp, 1)
    layers_local = num_layers / max(pp, 1)
    act_per_layer = b_local * seq_local * hidden * 2.0
    if sequence_parallel and tp > 1:
        act_per_layer /= tp
    units = act_boundary_units if remat else act_full_units
    acts = act_per_layer * layers_local * units
    logits = b_local * seq_local * vocab * 4.0 / max(tp, 1)
    total = params + opt + grads + acts + logits
    return {"params_bytes": params, "opt_state_bytes": opt,
            "grads_bytes": grads, "activation_bytes": acts,
            "logits_bytes": logits, "peak_bytes": total,
            "param_bytes": float(param_bytes), "remat": bool(remat)}


# ---------------------------------------------------------------------------
# the schema-versioned profile record + the flame graph
# ---------------------------------------------------------------------------

def profile_record(compiled_or_text, *, hw: Optional[Dict] = None,
                   top_k: int = 8, default_world: int = 1,
                   profile: Optional[Dict[str, Any]] = None,
                   text: Optional[str] = None) -> Dict[str, Any]:
    """The `profile` RunLog payload (and BENCH `detail.profile` shape):
    {"profile_schema": 1, "top": top-k groups by predicted time,
    "groups": <count>, "estimated_step_s", "total_flops",
    "total_wire_bytes", "peak_hbm_bytes", "peak_hbm_vs_xla",
    "hbm_headroom_frac"} — small enough to ride every fresh compile.

    The HLO text is materialized ONCE and shared by the attribution and
    peak walks; callers that already hold a `layer_profile` report
    and/or the text (the trainer's flame-graph path) pass them in to
    skip the re-walk."""
    txt = text if text is not None else (
        compiled_or_text if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text())
    prof = profile if profile is not None else layer_profile(
        txt, hw=hw, default_world=default_world)
    peak = peak_hbm_estimate(compiled_or_text, hw=hw, text=txt)
    rec: Dict[str, Any] = {
        "profile_schema": PROFILE_SCHEMA,
        "groups": len(prof["groups"]),
        "top": [
            {k: (round(v, 9) if isinstance(v, float) else v)
             for k, v in row.items()}
            for row in prof["top"][:max(top_k, 1)]],
        "estimated_step_s": prof["estimated_step_s"],
        "total_flops": prof["totals"]["flops"],
        "total_out_bytes": prof["totals"]["out_bytes"],
        "total_wire_bytes": prof["totals"]["wire_bytes"],
        "peak_hbm_bytes": peak["peak_bytes"],
    }
    for caveat in ("dynamic_trip_count", "conv_flops_unparsed"):
        if prof.get(caveat):
            rec[caveat] = True
    if "vs_xla" in peak:
        rec["peak_hbm_vs_xla"] = peak["vs_xla"]
    if "headroom_frac" in peak:
        rec["hbm_headroom_frac"] = peak["headroom_frac"]
    return rec


def flame_trace(profile: Dict[str, Any]) -> "ChromeTrace":
    """Render a `layer_profile` report as an analytic flame graph: one
    Chrome-trace lane of per-group predicted roofline times in model
    order (compute/memory/wire bound in the args), openable next to the
    schedule traces at https://ui.perfetto.dev."""
    from hetu_tpu.obs.trace import ChromeTrace
    tr = ChromeTrace()
    pid = "analytic step"
    tr.name_process(pid, "analytic step profile "
                         f"({profile.get('chip', 'unknown')})")
    tr.name_thread(pid, "roofline", "predicted per-group time")
    t = 0.0
    for g, rec in profile["groups"].items():
        dur = float(rec.get("time_s", 0.0)) * 1e6
        if dur <= 0:
            continue
        tr.add_complete(g, t, dur, pid=pid, tid="roofline",
                        cat=rec.get("bound", ""),
                        args={"flops": rec.get("flops"),
                              "out_bytes": rec.get("out_bytes"),
                              "wire_bytes": rec.get("wire_bytes"),
                              "bound": rec.get("bound")})
        t += dur
    return tr
