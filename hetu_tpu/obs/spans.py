"""Request-scoped span model: the serving flight recorder's data shape.

A serving request's lifecycle is a sequence of typed **spans** that tile
the driver-clock interval from arrival to completion, the way Hetu's
RunLog gives every training step a record:

    queued         arrival -> admission, carrying the stall-attribution
                   reason the scheduler's reserve-on-admit decision
                   produced (``none`` = admitted without waiting,
                   ``no_slot`` = every decode slot was live,
                   ``no_pages`` = the full page reservation was short,
                   ``quota_exceeded`` = the tenant was over its
                   admission quota)
    prefill        one span per prefill chunk (the disaggregated chunk
                   program); the last chunk's span ends at TTFT
    decode         a decode segment — split at evictions and reshard
                   pauses, so batch-composition changes are visible as
                   segment boundaries; carries the tokens emitted in it
    reshard_pause  the window a LoadAdaptiveMesh reshard froze decode
    done/evicted/deadline_exceeded
                   the zero-duration terminal span (exactly one per
                   request): ``done`` carries the finish reason and
                   token count, ``evicted`` marks a terminal eviction
                   (a retry budget exhausted after a replica loss, a
                   brownout shed), ``deadline_exceeded`` marks an SLO
                   deadline expiry (HETU_TPU_SERVE_DEADLINE)

Spans are recorded as schema-versioned ``span`` RunLog records
(``span_schema`` field; see obs/runlog.py) by
`serving/tracing.RequestTracer` under the ``HETU_TPU_SERVE_TRACE``
flag.  Timestamps ``t0``/``t1`` are **driver-clock** seconds (virtual
in `ServingEngine.run`/tests, wall in a live server), so a replayed
trace is deterministic; the standard RunLog ``t`` wall stamp rides
along for cross-log merging.

Because consecutive spans share boundaries (each opens where the
previous closed), the span durations of a finished request sum to its
recorded ``e2e_s`` — `reconcile()` checks that, and the tier-1 property
test holds every request to within one engine-step quantum.

This module is pure host-side bookkeeping: no jax, no serving imports —
the one span vocabulary `serving/tracing.py` (writer),
`serving/slo_report.py` (reader) and `obs/trace.py` (renderer) share.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, Iterable, List, Optional

#: bump when the `span` record shape changes incompatibly
SPAN_SCHEMA = 1

SPAN_KINDS = ("queued", "prefill", "decode", "reshard_pause",
              "done", "evicted", "deadline_exceeded")
TERMINAL_KINDS = ("done", "evicted", "deadline_exceeded")
#: ``preempted`` marks a RE-queued span: the request was evicted by a
#: higher-priority admission (HETU_TPU_SERVE_PREEMPT) and waits again —
#: same trace, so the tiling/reconciliation contract still holds.
#: ``replica_lost`` is the failover twin: the serving engine (replica)
#: died mid-flight (chaos ``engine_kill``) and the request re-entered
#: the queue under its retry budget (HETU_TPU_SERVE_RETRY) — same
#: trace, new ``attempt``.
#: ``quota_exceeded`` means the head request's TENANT was over its
#: admission quota (slots or pages; HETU_TPU_SERVE_QUOTAS) even though
#: the pool itself could have served it.
#: ``brownout_shed`` stamps the final queued span of a request the
#: sustained-pressure brownout policy shed (HETU_TPU_SERVE_BROWNOUT) —
#: lowest-priority tenants first; the terminal span is ``evicted``
#: with ``reason="brownout_shed"``.
#: ``prefill_tier_down`` is the disaggregated-serving degradation stamp
#: (HETU_TPU_SERVE_DISAGG, serving/disagg.py): the request's prefill
#: tier was dead, so it queued for COLOCATED chunked prefill on the
#: decode replica instead — sticky, like the other fault stamps.
#: ``shipment_wait`` marks a queued span that waited on a prefill-tier
#: KV shipment (a dropped/delayed wire exchange under chaos) rather
#: than on decode capacity.
STALL_REASONS = ("none", "no_slot", "no_pages", "preempted",
                 "quota_exceeded", "replica_lost", "brownout_shed",
                 "prefill_tier_down", "shipment_wait")

#: span-record fields that are structure, not attrs
_CORE_FIELDS = ("schema", "kind", "t", "span_schema", "span", "trace",
                "req", "slot", "slo_class", "t0", "t1")

_trace_counter = itertools.count()


def new_trace_id(rid: int) -> str:
    """A process-unique trace id for request `rid` (stable ordering, no
    RNG — deterministic under a replayed virtual clock)."""
    return f"tr{next(_trace_counter):x}.{rid}"


@dataclasses.dataclass
class Span:
    """One typed interval of a request's lifecycle (driver-clock secs)."""
    kind: str
    t0: float
    t1: float
    rid: int
    trace: str
    slot: Optional[int] = None
    slo_class: str = "default"
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in SPAN_KINDS:
            raise ValueError(f"unknown span kind {self.kind!r}; "
                             f"known: {SPAN_KINDS}")

    @property
    def dur_s(self) -> float:
        return self.t1 - self.t0

    def record(self) -> Dict[str, Any]:
        """The RunLog ``span`` record payload (everything but the
        writer-stamped schema/kind/t)."""
        out = {"span_schema": SPAN_SCHEMA, "span": self.kind,
               "trace": self.trace, "req": self.rid, "slot": self.slot,
               "slo_class": self.slo_class,
               "t0": self.t0, "t1": self.t1}
        out.update(self.attrs)
        return out

    @staticmethod
    def from_record(rec: Dict[str, Any]) -> "Span":
        attrs = {k: v for k, v in rec.items() if k not in _CORE_FIELDS}
        return Span(kind=rec["span"], t0=float(rec["t0"]),
                    t1=float(rec["t1"]), rid=int(rec["req"]),
                    trace=str(rec.get("trace", "")),
                    slot=rec.get("slot"),
                    slo_class=str(rec.get("slo_class", "default")),
                    attrs=attrs)


@dataclasses.dataclass
class RequestTrace:
    """All spans of one request, in emission order."""
    rid: int
    trace: str
    slo_class: str = "default"
    spans: List[Span] = dataclasses.field(default_factory=list)

    # ------------------------------------------------------------ views
    def by_kind(self, kind: str) -> List[Span]:
        return [s for s in self.spans if s.kind == kind]

    @property
    def terminal(self) -> Optional[Span]:
        term = [s for s in self.spans if s.kind in TERMINAL_KINDS]
        return term[-1] if term else None

    @property
    def stall_reason(self) -> Optional[str]:
        q = self.by_kind("queued")
        return q[0].attrs.get("reason") if q else None

    def duration_s(self, kind: str) -> float:
        return sum(s.dur_s for s in self.by_kind(kind))

    @property
    def total_s(self) -> float:
        """Sum of all non-terminal span durations — should reconcile
        with the request's recorded ``e2e_s``."""
        return sum(s.dur_s for s in self.spans
                   if s.kind not in TERMINAL_KINDS)

    @property
    def tokens(self) -> Optional[int]:
        t = self.terminal
        return t.attrs.get("tokens") if t is not None else None

    # ------------------------------------------------------- invariants
    def validate(self, *, eps: float = 1e-9):
        """The span-event contract the fuzz test drives:

        * at least one span, the first being ``queued`` with a
          stall-attribution reason from STALL_REASONS,
        * exactly one terminal span (done | evicted), and it is last,
        * spans are ordered and non-overlapping: each span opens no
          earlier than the previous closed (shared boundaries allowed),
        * every span has t1 >= t0 and carries this trace's ids.

        Raises AssertionError naming the violated invariant."""
        if not self.spans:
            raise AssertionError(f"request {self.rid}: empty trace")
        first = self.spans[0]
        if first.kind != "queued":
            raise AssertionError(
                f"request {self.rid}: first span is {first.kind!r}, "
                "not 'queued'")
        if first.attrs.get("reason") not in STALL_REASONS:
            raise AssertionError(
                f"request {self.rid}: queued span carries stall reason "
                f"{first.attrs.get('reason')!r}, not one of "
                f"{STALL_REASONS}")
        terms = [s for s in self.spans if s.kind in TERMINAL_KINDS]
        if len(terms) != 1:
            raise AssertionError(
                f"request {self.rid}: {len(terms)} terminal spans "
                f"({[s.kind for s in terms]}); want exactly one")
        if self.spans[-1].kind not in TERMINAL_KINDS:
            raise AssertionError(
                f"request {self.rid}: terminal span is not last "
                f"(last is {self.spans[-1].kind!r})")
        prev_t1 = None
        for s in self.spans:
            if s.rid != self.rid or s.trace != self.trace:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} carries foreign "
                    f"ids (req={s.rid}, trace={s.trace!r})")
            if s.t1 < s.t0 - eps:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} runs backwards "
                    f"({s.t0} -> {s.t1})")
            if prev_t1 is not None and s.t0 < prev_t1 - eps:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} at {s.t0} "
                    f"overlaps the previous span ending {prev_t1}")
            prev_t1 = s.t1

    def reconcile(self, e2e_s: Optional[float]) -> Optional[float]:
        """Residual between the span tiling and the recorded end-to-end
        latency: ``|sum(span durations) - e2e_s|``.  None when either
        side is missing.  The acceptance property holds this within one
        engine-step quantum."""
        if e2e_s is None or self.terminal is None:
            return None
        return abs(self.total_s - float(e2e_s))


def collect_traces(records: Iterable[Dict[str, Any]]
                   ) -> Dict[int, RequestTrace]:
    """Group RunLog ``span`` records into per-request RequestTraces
    (rid-keyed, spans in record order) — THE reader every consumer
    (slo_report, trace renderer, tests) shares."""
    out: Dict[int, RequestTrace] = {}
    for rec in records:
        if rec.get("kind") != "span" or "span" not in rec:
            continue
        sp = Span.from_record(rec)
        tr = out.get(sp.rid)
        if tr is None or tr.trace != sp.trace:
            # a rid reused across engine incarnations starts a fresh
            # trace; the latest wins (report surfaces completed ones)
            tr = out[sp.rid] = RequestTrace(rid=sp.rid, trace=sp.trace,
                                           slo_class=sp.slo_class)
        tr.spans.append(sp)
    return out
