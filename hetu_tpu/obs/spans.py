"""Request-scoped span model: the serving flight recorder's data shape.

A serving request's lifecycle is a sequence of typed **spans** that tile
the driver-clock interval from arrival to completion, the way Hetu's
RunLog gives every training step a record:

    queued         arrival -> admission, carrying the stall-attribution
                   reason the scheduler's reserve-on-admit decision
                   produced (``none`` = admitted without waiting,
                   ``no_slot`` = every decode slot was live,
                   ``no_pages`` = the full page reservation was short,
                   ``quota_exceeded`` = the tenant was over its
                   admission quota)
    prefill        one span per prefill chunk (the disaggregated chunk
                   program); the last chunk's span ends at TTFT
    decode         a decode segment — split at evictions and reshard
                   pauses, so batch-composition changes are visible as
                   segment boundaries; carries the tokens emitted in it
    reshard_pause  the window a LoadAdaptiveMesh reshard froze decode
    done/evicted/deadline_exceeded/hedge_withdrawn
                   the zero-duration terminal span (exactly one per
                   request): ``done`` carries the finish reason and
                   token count, ``evicted`` marks a terminal eviction
                   (a retry budget exhausted after a replica loss, a
                   brownout shed), ``deadline_exceeded`` marks an SLO
                   deadline expiry (HETU_TPU_SERVE_DEADLINE), and
                   ``hedge_withdrawn`` closes the LOSING copy of a
                   hedged request (serving/frontend.py) so fleet-wide
                   span accounting includes the discarded work

Every span additionally carries its **hop identity** — the trace
context ``(rid, attempt, tier, replica)`` of the distributed fleet:
``tier`` names which stage of the disaggregated pipeline emitted it
(``prefill`` | ``decode``; unset means a single colocated engine),
``replica`` the engine index behind a routing frontend, and ``attempt``
(an attr, stamped from 2 up) the failover/requeue incarnation.  A
``clock`` basis field (``driver`` | ``wall``) declares which clock the
``t0``/``t1`` stamps were taken on; `FleetTrace.stitch` refuses to mix
bases rather than silently producing garbage durations.

Spans are recorded as schema-versioned ``span`` RunLog records
(``span_schema`` field; see obs/runlog.py) by
`serving/tracing.RequestTracer` under the ``HETU_TPU_SERVE_TRACE``
flag.  Timestamps ``t0``/``t1`` are **driver-clock** seconds (virtual
in `ServingEngine.run`/tests, wall in a live server), so a replayed
trace is deterministic; the standard RunLog ``t`` wall stamp rides
along for cross-log merging.

Because consecutive spans share boundaries (each opens where the
previous closed), the span durations of a finished request sum to its
recorded ``e2e_s`` — `reconcile()` checks that, and the tier-1 property
test holds every request to within one engine-step quantum.

This module is pure host-side bookkeeping: no jax, no serving imports —
the one span vocabulary `serving/tracing.py` (writer),
`serving/slo_report.py` (reader) and `obs/trace.py` (renderer) share.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, Iterable, List, Optional

#: bump when the `span` record shape changes incompatibly
SPAN_SCHEMA = 1

SPAN_KINDS = ("queued", "prefill", "decode", "reshard_pause",
              "done", "evicted", "deadline_exceeded", "hedge_withdrawn")
TERMINAL_KINDS = ("done", "evicted", "deadline_exceeded",
                  "hedge_withdrawn")

#: span timestamp bases — ``driver`` is the engine's virtual clock
#: (deterministic under replay; what every tier-1 test runs on),
#: ``wall`` is host wall time (a live server).  Durations from
#: different bases must never be stitched together.
CLOCK_BASES = ("driver", "wall")
#: ``preempted`` marks a RE-queued span: the request was evicted by a
#: higher-priority admission (HETU_TPU_SERVE_PREEMPT) and waits again —
#: same trace, so the tiling/reconciliation contract still holds.
#: ``replica_lost`` is the failover twin: the serving engine (replica)
#: died mid-flight (chaos ``engine_kill``) and the request re-entered
#: the queue under its retry budget (HETU_TPU_SERVE_RETRY) — same
#: trace, new ``attempt``.
#: ``quota_exceeded`` means the head request's TENANT was over its
#: admission quota (slots or pages; HETU_TPU_SERVE_QUOTAS) even though
#: the pool itself could have served it.
#: ``brownout_shed`` stamps the final queued span of a request the
#: sustained-pressure brownout policy shed (HETU_TPU_SERVE_BROWNOUT) —
#: lowest-priority tenants first; the terminal span is ``evicted``
#: with ``reason="brownout_shed"``.
#: ``prefill_tier_down`` is the disaggregated-serving degradation stamp
#: (HETU_TPU_SERVE_DISAGG, serving/disagg.py): the request's prefill
#: tier was dead, so it queued for COLOCATED chunked prefill on the
#: decode replica instead — sticky, like the other fault stamps.
#: ``shipment_wait`` marks a queued span that waited on a prefill-tier
#: KV shipment (a dropped/delayed wire exchange under chaos) rather
#: than on decode capacity.
STALL_REASONS = ("none", "no_slot", "no_pages", "preempted",
                 "quota_exceeded", "replica_lost", "brownout_shed",
                 "prefill_tier_down", "shipment_wait")

#: span-record fields that are structure, not attrs
_CORE_FIELDS = ("schema", "kind", "t", "span_schema", "span", "trace",
                "req", "slot", "slo_class", "t0", "t1", "clock",
                "tier", "replica")

_trace_counter = itertools.count()


def new_trace_id(rid: int) -> str:
    """A process-unique trace id for request `rid` (stable ordering, no
    RNG — deterministic under a replayed virtual clock)."""
    return f"tr{next(_trace_counter):x}.{rid}"


@dataclasses.dataclass
class Span:
    """One typed interval of a request's lifecycle (driver-clock secs)."""
    kind: str
    t0: float
    t1: float
    rid: int
    trace: str
    slot: Optional[int] = None
    slo_class: str = "default"
    clock: str = "driver"
    tier: Optional[str] = None       # prefill|decode; None = colocated
    replica: Optional[int] = None    # engine index behind a frontend
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in SPAN_KINDS:
            raise ValueError(f"unknown span kind {self.kind!r}; "
                             f"known: {SPAN_KINDS}")
        if self.clock not in CLOCK_BASES:
            raise ValueError(f"unknown clock basis {self.clock!r}; "
                             f"known: {CLOCK_BASES}")

    @property
    def dur_s(self) -> float:
        return self.t1 - self.t0

    @property
    def attempt(self) -> int:
        return int(self.attrs.get("attempt", 1))

    def record(self) -> Dict[str, Any]:
        """The RunLog ``span`` record payload (everything but the
        writer-stamped schema/kind/t).  ``clock`` is always stamped;
        the hop-identity fields ride only when set, so a single
        colocated engine's records keep their pre-fleet shape."""
        out = {"span_schema": SPAN_SCHEMA, "span": self.kind,
               "trace": self.trace, "req": self.rid, "slot": self.slot,
               "slo_class": self.slo_class,
               "t0": self.t0, "t1": self.t1, "clock": self.clock}
        if self.tier is not None:
            out["tier"] = self.tier
        if self.replica is not None:
            out["replica"] = self.replica
        out.update(self.attrs)
        return out

    @staticmethod
    def from_record(rec: Dict[str, Any]) -> "Span":
        attrs = {k: v for k, v in rec.items() if k not in _CORE_FIELDS}
        return Span(kind=rec["span"], t0=float(rec["t0"]),
                    t1=float(rec["t1"]), rid=int(rec["req"]),
                    trace=str(rec.get("trace", "")),
                    slot=rec.get("slot"),
                    slo_class=str(rec.get("slo_class", "default")),
                    clock=str(rec.get("clock", "driver")),
                    tier=rec.get("tier"),
                    replica=rec.get("replica"),
                    attrs=attrs)


@dataclasses.dataclass
class RequestTrace:
    """All spans of one request, in emission order."""
    rid: int
    trace: str
    slo_class: str = "default"
    spans: List[Span] = dataclasses.field(default_factory=list)

    # ------------------------------------------------------------ views
    def by_kind(self, kind: str) -> List[Span]:
        return [s for s in self.spans if s.kind == kind]

    @property
    def tier(self) -> str:
        """The hop's pipeline tier (``decode`` when unstamped — a
        colocated single engine)."""
        for s in self.spans:
            if s.tier is not None:
                return s.tier
        return "decode"

    @property
    def replica(self) -> Optional[int]:
        for s in self.spans:
            if s.replica is not None:
                return s.replica
        return None

    @property
    def clock(self) -> str:
        return self.spans[0].clock if self.spans else "driver"

    def attempts(self) -> Dict[int, List[Span]]:
        """Spans grouped by failover/requeue attempt (1-based; the
        ``attempt`` attr is only stamped from 2 up)."""
        out: Dict[int, List[Span]] = {}
        for s in self.spans:
            out.setdefault(s.attempt, []).append(s)
        return out

    @property
    def lifetime_s(self) -> float:
        """Wall of this hop: first span open -> terminal close (0 for
        an empty trace)."""
        if not self.spans:
            return 0.0
        return self.spans[-1].t1 - self.spans[0].t0

    @property
    def terminal(self) -> Optional[Span]:
        term = [s for s in self.spans if s.kind in TERMINAL_KINDS]
        return term[-1] if term else None

    @property
    def stall_reason(self) -> Optional[str]:
        q = self.by_kind("queued")
        return q[0].attrs.get("reason") if q else None

    def duration_s(self, kind: str) -> float:
        return sum(s.dur_s for s in self.by_kind(kind))

    @property
    def total_s(self) -> float:
        """Sum of all non-terminal span durations — should reconcile
        with the request's recorded ``e2e_s``."""
        return sum(s.dur_s for s in self.spans
                   if s.kind not in TERMINAL_KINDS)

    @property
    def tokens(self) -> Optional[int]:
        t = self.terminal
        return t.attrs.get("tokens") if t is not None else None

    # ------------------------------------------------------- invariants
    def validate(self, *, eps: float = 1e-9):
        """The span-event contract the fuzz test drives:

        * at least one span, the first being ``queued`` with a
          stall-attribution reason from STALL_REASONS,
        * exactly one terminal span (done | evicted), and it is last,
        * spans are ordered and non-overlapping: each span opens no
          earlier than the previous closed (shared boundaries allowed),
        * every span has t1 >= t0 and carries this trace's ids.

        Raises AssertionError naming the violated invariant."""
        if not self.spans:
            raise AssertionError(f"request {self.rid}: empty trace")
        first = self.spans[0]
        if first.kind != "queued":
            raise AssertionError(
                f"request {self.rid}: first span is {first.kind!r}, "
                "not 'queued'")
        if first.attrs.get("reason") not in STALL_REASONS:
            raise AssertionError(
                f"request {self.rid}: queued span carries stall reason "
                f"{first.attrs.get('reason')!r}, not one of "
                f"{STALL_REASONS}")
        terms = [s for s in self.spans if s.kind in TERMINAL_KINDS]
        if len(terms) != 1:
            raise AssertionError(
                f"request {self.rid}: {len(terms)} terminal spans "
                f"({[s.kind for s in terms]}); want exactly one")
        if self.spans[-1].kind not in TERMINAL_KINDS:
            raise AssertionError(
                f"request {self.rid}: terminal span is not last "
                f"(last is {self.spans[-1].kind!r})")
        prev_t1 = None
        for s in self.spans:
            if s.rid != self.rid or s.trace != self.trace:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} carries foreign "
                    f"ids (req={s.rid}, trace={s.trace!r})")
            if s.t1 < s.t0 - eps:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} runs backwards "
                    f"({s.t0} -> {s.t1})")
            if prev_t1 is not None and s.t0 < prev_t1 - eps:
                raise AssertionError(
                    f"request {self.rid}: span {s.kind} at {s.t0} "
                    f"overlaps the previous span ending {prev_t1}")
            prev_t1 = s.t1

    def reconcile(self, e2e_s: Optional[float]) -> Optional[float]:
        """Residual between the span tiling and the recorded end-to-end
        latency: ``|sum(span durations) - e2e_s|``.  None when either
        side is missing.  The acceptance property holds this within one
        engine-step quantum."""
        if e2e_s is None or self.terminal is None:
            return None
        return abs(self.total_s - float(e2e_s))


def collect_traces(records: Iterable[Dict[str, Any]]
                   ) -> Dict[int, RequestTrace]:
    """Group RunLog ``span`` records into per-request RequestTraces
    (rid-keyed, spans in record order) — THE reader every consumer
    (slo_report, trace renderer, tests) shares."""
    out: Dict[int, RequestTrace] = {}
    for rec in records:
        if rec.get("kind") != "span" or "span" not in rec:
            continue
        sp = Span.from_record(rec)
        tr = out.get(sp.rid)
        if tr is None or tr.trace != sp.trace:
            # a rid reused across engine incarnations starts a fresh
            # trace; the latest wins (report surfaces completed ones)
            tr = out[sp.rid] = RequestTrace(rid=sp.rid, trace=sp.trace,
                                           slo_class=sp.slo_class)
        tr.spans.append(sp)
    return out


# --------------------------------------------------------------- fleet
#: terminal kinds that produce a CLIENT-visible result (a hedge loser's
#: ``hedge_withdrawn`` closes its hop but never reaches the client)
CLIENT_TERMINALS = ("done", "evicted", "deadline_exceeded")

#: serve events the stitcher consumes as causal-edge endpoints
_EDGE_EVENTS = ("dispatch", "hedge", "hedge_win", "hedge_dupe",
                "ship", "retry", "admit")


def _ev_t(ev: Dict[str, Any]) -> float:
    for k in ("now", "t"):
        if ev.get(k) is not None:
            return float(ev[k])
    return 0.0


def _ev_rid(ev: Dict[str, Any]) -> Optional[int]:
    rid = ev.get("req", ev.get("rid"))
    return int(rid) if rid is not None else None


@dataclasses.dataclass
class FleetTrace:
    """One request's CAUSAL DAG across the disaggregated fleet.

    ``hops`` are the per-engine `RequestTrace`s that carried the rid —
    the decode replica(s), hedged copies, and prefill-tier incarnations
    — each stamped with its hop identity (tier, replica, clock).
    ``events`` are the frontend/shipment serve records for the rid, and
    ``edges`` the explicit causal links the stitcher derived from them:

        dispatch        frontend routing -> a hop's queued span
        hedge_fork      the primary copy forks a hedged duplicate
        hedge_win       the hedge copy produced the client result
        hedge_withdraw  the losing copy's terminal (discarded work)
        ship            prefill tier -> decode (the KV shipment)
        adopt           the shipment's apply/admit on the decode tier
        replay          a kill's requeue re-admission (attempt n -> n+1)
        fallback        a dead prefill tier colocated the request

    `validate` is the fleet-scope tiling contract: every hop tiles per
    attempt, exactly one hop carries the client terminal, no hop is an
    orphan (unreachable from the edges), and the primary hop's union
    covers arrival -> terminal with zero residual (<= one step quantum
    per attempt boundary) under one shared clock basis.
    """
    rid: int
    hops: List[RequestTrace] = dataclasses.field(default_factory=list)
    events: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    edges: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    clock: str = "driver"

    # ------------------------------------------------------------ views
    @property
    def primary(self) -> Optional[RequestTrace]:
        """The hop that produced the CLIENT result: a decode-tier hop
        whose terminal is done/evicted/deadline_exceeded.  A hedge
        loser that ran to completion (``hedge_dupe``) is excluded; ties
        go to the earliest terminal (the copy that won the race)."""
        wins = [h for h in self.hops
                if h.tier != "prefill" and h.terminal is not None
                and h.terminal.kind in CLIENT_TERMINALS]
        if len(wins) > 1:
            dupes = {ev.get("replica") for ev in self.events
                     if ev.get("event") == "hedge_dupe"}
            filt = [h for h in wins if h.replica not in dupes]
            wins = filt or wins
        if not wins:
            return None
        return min(wins, key=lambda h: h.terminal.t1)

    @property
    def slo_class(self) -> str:
        p = self.primary
        return p.slo_class if p is not None else (
            self.hops[0].slo_class if self.hops else "default")

    @property
    def span_seconds(self) -> float:
        """Total non-terminal span-seconds across ALL hops — the
        fleet-wide work ledger, discarded hedge/prefill work included."""
        return sum(h.total_s for h in self.hops)

    @property
    def lifetime_seconds(self) -> float:
        """Sum of per-hop lifetimes (first open -> terminal).  Because
        every hop tiles contiguously, this equals `span_seconds` — the
        satellite accounting identity fleet tests pin."""
        return sum(h.lifetime_s for h in self.hops)

    @property
    def e2e_s(self) -> Optional[float]:
        p = self.primary
        return p.lifetime_s if p is not None else None

    def hop_label(self, hop: RequestTrace) -> str:
        rep = "" if hop.replica is None else f"/{hop.replica}"
        return f"{hop.tier}{rep}"

    # ------------------------------------------------------- invariants
    def residual_s(self) -> float:
        """Uncovered time inside the primary hop's [arrival, terminal]
        interval: the sum of positive gaps between consecutive spans.
        Zero means the stitched union tiles the lifetime exactly."""
        p = self.primary
        if p is None or not p.spans:
            return 0.0
        gap = 0.0
        prev_t1 = p.spans[0].t0
        for s in p.spans:
            gap += max(0.0, s.t0 - prev_t1)
            prev_t1 = max(prev_t1, s.t1)
        return gap

    def validate(self, *, eps: float = 1e-9,
                 step_quantum: float = 0.0):
        """Fleet-scope stitch contract (AssertionError on violation):

        * every hop individually satisfies the `RequestTrace` contract
          and tiles CONTIGUOUSLY within each attempt (gaps only at
          attempt boundaries, each <= one step quantum),
        * exactly one hop carries the client terminal (hedge dupes
          discounted via their ``hedge_dupe`` event),
        * no orphan hops: every non-primary hop is referenced by at
          least one causal edge,
        * the primary hop's union covers arrival -> terminal with
          residual <= one step quantum per attempt boundary."""
        if not self.hops:
            raise AssertionError(f"rid {self.rid}: no hops to stitch")
        for h in self.hops:
            h.validate(eps=eps)
            prev_t1: Optional[float] = None
            prev_attempt = None
            boundaries = 0
            for s in h.spans:
                if prev_t1 is not None:
                    allow = eps
                    if s.attempt != prev_attempt:
                        boundaries += 1
                        allow = step_quantum + eps
                    if s.t0 - prev_t1 > allow:
                        raise AssertionError(
                            f"rid {self.rid} hop {self.hop_label(h)}: "
                            f"span {s.kind} at {s.t0} leaves a "
                            f"{s.t0 - prev_t1:.3g}s hole after "
                            f"{prev_t1} (attempt {s.attempt})")
                prev_t1 = s.t1
                prev_attempt = s.attempt
        wins = [h for h in self.hops
                if h.tier != "prefill" and h.terminal is not None
                and h.terminal.kind in CLIENT_TERMINALS]
        dupes = {ev.get("replica") for ev in self.events
                 if ev.get("event") == "hedge_dupe"}
        effective = [h for h in wins
                     if not dupes or h.replica not in dupes] or wins
        if len(effective) != 1:
            raise AssertionError(
                f"rid {self.rid}: {len(effective)} client-terminal "
                f"hops ({[self.hop_label(h) for h in effective]}); "
                "want exactly one")
        prim = self.primary
        for h in self.hops:
            if h is prim:
                continue
            if not any(e.get("src") == h.trace or e.get("dst") == h.trace
                       for e in self.edges):
                raise AssertionError(
                    f"rid {self.rid}: orphan hop "
                    f"{self.hop_label(h)} ({h.trace}) — no causal edge "
                    "reaches it")
        attempts = len(prim.attempts()) if prim is not None else 1
        allow = eps + step_quantum * max(0, attempts - 1)
        resid = self.residual_s()
        if resid > allow:
            raise AssertionError(
                f"rid {self.rid}: stitched union leaves "
                f"{resid:.3g}s uncovered (> {allow:.3g})")

    # ------------------------------------------------------------ stitch
    @staticmethod
    def stitch(records: Optional[Iterable[Dict[str, Any]]] = None, *,
               traces: Optional[Iterable[RequestTrace]] = None,
               events: Optional[Iterable[Dict[str, Any]]] = None,
               eps: float = 1e-9) -> Dict[int, "FleetTrace"]:
        """Assemble per-rid `FleetTrace`s from RunLog records and/or
        in-memory traces + serve events.  Unlike `collect_traces`
        (latest trace wins) the stitcher keeps EVERY (rid, trace) hop —
        hedge losers and prefill-tier incarnations included.  Raises
        ValueError on mixed clock bases."""
        hops: Dict[int, Dict[str, RequestTrace]] = {}
        evs: Dict[int, List[Dict[str, Any]]] = {}
        clocks = set()

        def add_span(sp: Span):
            clocks.add(sp.clock)
            per = hops.setdefault(sp.rid, {})
            tr = per.get(sp.trace)
            if tr is None:
                tr = per[sp.trace] = RequestTrace(
                    rid=sp.rid, trace=sp.trace, slo_class=sp.slo_class)
            tr.spans.append(sp)

        def add_event(ev: Dict[str, Any]):
            if ev.get("clock") is not None:
                clocks.add(str(ev["clock"]))
            rid = _ev_rid(ev)
            if rid is None or ev.get("event") not in _EDGE_EVENTS:
                return
            evs.setdefault(rid, []).append(ev)

        for rec in records or ():
            if rec.get("kind") == "span" and "span" in rec:
                add_span(Span.from_record(rec))
            elif rec.get("kind") == "serve" and "event" in rec:
                add_event(rec)
        for tr in traces or ():
            for sp in tr.spans:
                add_span(sp)
        for ev in events or ():
            if "event" in ev:
                add_event(ev)
        if len(clocks) > 1:
            raise ValueError(
                "FleetTrace.stitch: mixed clock bases "
                f"{sorted(clocks)} — driver-clock and wall-clock "
                "records cannot be stitched into one timeline; "
                "re-record with a single basis")
        clock = next(iter(clocks)) if clocks else "driver"

        out: Dict[int, FleetTrace] = {}
        for rid, per in hops.items():
            hlist = sorted(
                per.values(),
                key=lambda h: (h.spans[0].t0 if h.spans else 0.0,
                               h.trace))
            ft = FleetTrace(rid=rid, hops=hlist,
                            events=sorted(evs.get(rid, ()), key=_ev_t),
                            clock=clock)
            ft.edges = _build_edges(ft, eps=eps)
            out[rid] = ft
        return out


def _hop_for(hops: List[RequestTrace], *, tier: Optional[str] = None,
             replica: Optional[int] = None,
             at: Optional[float] = None,
             eps: float = 1e-9) -> Optional[RequestTrace]:
    """The hop matching a tier/replica stamp, preferring the latest one
    already open at time ``at`` (re-prefills make several hops per
    tier)."""
    cand = [h for h in hops if h.spans
            and (tier is None or h.tier == tier)
            and (replica is None or h.replica == replica)]
    if not cand:
        return None
    if at is not None:
        started = [h for h in cand if h.spans[0].t0 <= at + eps]
        if started:
            return started[-1]
    return cand[-1]


def _build_edges(ft: FleetTrace, *, eps: float = 1e-9
                 ) -> List[Dict[str, Any]]:
    """Derive the causal edges of one rid's DAG from its serve events
    and hop terminals (see `FleetTrace` for the edge vocabulary)."""
    edges: List[Dict[str, Any]] = []
    prim = ft.primary
    prim_trace = prim.trace if prim is not None else "decode"
    for ev in ft.events:
        kind = ev.get("event")
        t = _ev_t(ev)
        if kind == "dispatch":
            dst = _hop_for(ft.hops, tier=ev.get("tier"),
                           replica=ev.get("replica"), at=t, eps=eps)
            edges.append({"kind": "dispatch", "t": t, "src": "frontend",
                          "dst": dst.trace if dst is not None
                          else str(ev.get("tier") or "decode")})
        elif kind == "hedge":
            p = _hop_for(ft.hops, replica=ev.get("primary"), at=t,
                         eps=eps)
            h = _hop_for(ft.hops, replica=ev.get("hedge"), eps=eps)
            edges.append({"kind": "hedge_fork", "t": t,
                          "src": p.trace if p is not None
                          else "frontend",
                          "dst": h.trace if h is not None else "hedge"})
        elif kind == "hedge_win":
            h = _hop_for(ft.hops, replica=ev.get("hedge"), at=t,
                         eps=eps)
            edges.append({"kind": "hedge_win", "t": t,
                          "src": h.trace if h is not None else "hedge",
                          "dst": "client"})
        elif kind == "ship":
            src = _hop_for(ft.hops, tier="prefill", at=t, eps=eps)
            edges.append({"kind": "ship", "t": t,
                          "src": src.trace if src is not None
                          else "prefill",
                          "dst": prim_trace,
                          **({"seq": ev["seq"]} if "seq" in ev else {})})
        elif kind == "retry":
            att = ev.get("attempt")
            edges.append({"kind": "replay", "t": t, "src": prim_trace,
                          "dst": prim_trace,
                          **({"attempt": att} if att is not None
                             else {})})
        elif kind == "admit" and ev.get("disagg"):
            edges.append({"kind": "adopt", "t": t, "src": "wire",
                          "dst": prim_trace})
    for h in ft.hops:
        term = h.terminal
        if term is None:
            continue
        if term.kind == "hedge_withdrawn":
            edges.append({"kind": "hedge_withdraw", "t": term.t1,
                          "src": h.trace, "dst": "frontend"})
        elif h.tier == "prefill":
            if term.kind == "done":
                if not any(e["kind"] == "ship" and e["src"] == h.trace
                           for e in edges):
                    edges.append({"kind": "ship", "t": term.t1,
                                  "src": h.trace, "dst": prim_trace})
            else:
                edges.append({"kind": "fallback", "t": term.t1,
                              "src": h.trace, "dst": prim_trace})
    return edges
