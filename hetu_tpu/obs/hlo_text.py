"""The ONE post-optimization-HLO text tokenizer.

Three consumers walk compiled HLO text in this repo — the bytes-on-wire
analyzer (`obs/comm.py`), the per-layer step profiler
(`obs/hlo_profile.py`), and the graph-contract linter
(`hetu_tpu/analysis/hlo_lints.py`).  They used to each carry their own
regex set; a parse fix (tuple outputs, iota replica_groups, async
`-start` payloads, nested while trips) had to land three times or the
byte models silently drifted apart.  This module owns the shared layer:

* **line anatomy** — `parse_def` splits `%name = <shapes> opcode(...)`
  into (name, output-shape section, opcode); `shape_bytes` /
  `component_bytes` price a shape section (operand shapes live INSIDE
  the call parens and must never count — summing them overcounts
  traffic by the instruction fan-in);
* **collectives** — `first_group` parses `replica_groups` (both the
  explicit `{{0,1},{2,3}}` and iota `[2,2]<=[4]` forms),
  `payload_bytes` resolves sync vs async `-start` payloads,
  `ring_wire_bytes` prices one op under the standard ring algorithms,
  `line_wire_bytes` composes all three for one instruction line;
* **structure** — `split_computations` maps the module into
  {computation: lines}, `entry_computation` finds the ENTRY,
  `cond_trip_count` recovers a while's static trip count from its
  condition computation, `while_multipliers` (while bodies only — the
  comm accounting) and `call_multipliers` (EVERY call edge: fusions,
  calls, conditional branches — the profiler's accounting) turn those
  into per-computation execution multipliers;
* **FLOPs** — `dot_flops` prices one `dot(...)` line from its operand
  shapes x `lhs_contracting_dims`;
* **module contracts** — `donated_parameters` parses
  `input_output_alias`, `entry_parameters` lists the entry computation's
  parameter buffers — what the donation lint checks against liveness.

Behavioral contracts (pinned by tests/test_comm.py,
tests/test_hlo_profile.py and tests/test_hlo_text.py): the wire
formulas match `comm/wire.py` analytically; static per-group sums match
`utils.profiling.phase_breakdown`; while-trip resolution follows the
`compare(induction, constant), direction=LT` form every lax.scan lowers
to, with `dynamic=True` surfaced when a bound is not a literal.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

#: collective opcodes accounted by every consumer (async "-start" forms
#: fold into these; "-done" lines carry no payload)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute")

DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1,
               "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
               "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1, "c64": 8,
               "c128": 16}

# `%x = <shapes> opcode(...)` — output-section anchoring: shapes AFTER
# '=' and BEFORE the opcode token; operand shapes (inside the parens)
# must not count.  Tuple outputs `(f32[..], f32[..])` and tiled layouts
# `{1,0:T(8,128)}` stay in the group: `T(` starts uppercase, dtype
# tokens are followed by `[` not `(`.
LINE_PAT = re.compile(r'=\s*(?P<out>.*?)\s*(?P<op>[a-z][a-z0-9_.-]*)\(')
DEF_PAT = re.compile(r'%([\w.\-]+)\s*=\s*(.*?)\s*([a-z][a-z0-9_.-]*)\(')
SHAPE_PAT = re.compile(r'\b([a-z][a-z0-9]*)\[([0-9,]*)\]')
OUT_PAT = re.compile(r'=\s*(.*?)\s*[a-z][a-z0-9_.-]*\(')
REF_PAT = re.compile(r'%([\w.\-]+)')
OP_NAME_PAT = re.compile(r'op_name="([^"]+)"')
GROUPS_PAT = re.compile(r'replica_groups=\{(\{[0-9,{} ]*\})\}')
IOTA_GROUPS_PAT = re.compile(
    r'replica_groups=\[(\d+),(\d+)\]<=(?:\[[\d,]+\])(T\([\d,]+\))?')
#: the raw replica_groups attribute text (either form) — what the
#: replication lint compares across conditional branches
GROUPS_ATTR_PAT = re.compile(r'replica_groups=(\{[0-9,{} ]*\}|'
                             r'\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)')

# computation structure
COMP_HEAD_PAT = re.compile(
    r'^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{')
WHILE_PAT = re.compile(r'=\s*[^=]*\bwhile\(')
COND_REF_PAT = re.compile(r'condition=%?([\w.\-]+)')
BODY_REF_PAT = re.compile(r'body=%?([\w.\-]+)')
CONST_PAT = re.compile(r'%?([\w.\-]+)\s*=\s*[su]\d+\[\]\s+constant\((\d+)\)')
COMPARE_PAT = re.compile(
    r'compare\(\s*\S+\s+%?([\w.\-]+),\s*\S+\s+%?([\w.\-]+)\s*\)')
DIRECTION_PAT = re.compile(r'direction=(\w+)')
CALLEE_PAT = re.compile(r'(?:calls|body|condition|to_apply)=%?([\w.\-]+)')
BRANCH_PAT = re.compile(r'branch_computations=\{([^}]*)\}')
ENTRY_PAT = re.compile(r'^ENTRY\s+%?([\w.\-]+)', re.M)
DOT_CONTRACT_PAT = re.compile(r'lhs_contracting_dims=\{([0-9,]*)\}')
ALIAS_ENTRY_PAT = re.compile(r'\(\s*(\d+)\s*,')


def as_hlo_text(compiled_or_text) -> str:
    """The post-optimization HLO text of a compiled executable, or the
    argument itself when it is already text — every consumer's first
    line, so large modules stringify once per caller, not per helper."""
    return (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())


# ---------------------------------------------------------------------------
# shapes / payloads
# ---------------------------------------------------------------------------

def component_bytes(section: str) -> List[int]:
    """Byte size of each shape component in one output-shape section."""
    out = []
    for dt, dims in SHAPE_PAT.findall(section):
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        out.append(numel * DTYPE_BYTES.get(dt, 4))
    return out


def shape_bytes(section: str) -> int:
    """Total bytes of one output-shape section (tuple components sum)."""
    return sum(component_bytes(section))


def payload_bytes(section: str, is_start: bool) -> int:
    """Payload of one collective from its output-shape section.

    Sync forms: the output IS the payload (sum tuple components — a tuple
    all-to-all's components add up to the local buffer).  Async "-start"
    forms output a tuple carrying the OPERAND buffer(s) too —
    (operand, result, context...) — so summing would double-count; the
    largest component is the full transfer buffer for every async
    collective (result for all-gather, operand for reduce-scatter, either
    for all-reduce/permute), and `ring_wire_bytes` applies full-buffer
    formulas for starts."""
    comps = component_bytes(section)
    if not comps:
        return 0
    return max(comps) if is_start else sum(comps)


def first_group(line: str, default_world: int
                ) -> Tuple[int, Optional[Tuple[int, ...]]]:
    """(group size, first group's rank list when recoverable) of a
    collective instruction."""
    m = GROUPS_PAT.search(line)
    if m:
        first = m.group(1).split("}")[0].lstrip("{")
        ranks = tuple(int(t) for t in first.split(",") if t.strip())
        return max(len(ranks), 1), (ranks or None)
    m = IOTA_GROUPS_PAT.search(line)
    if m:  # iota form [num_groups, group_size]<=[world](T(perm))?
        g, s = int(m.group(1)), int(m.group(2))
        if m.group(3):  # transposed iota: group 0 strides by num_groups
            ranks = tuple(range(0, g * s, g))[:s]
        else:           # contiguous iota: group 0 = [0, s)
            ranks = tuple(range(s))
        return max(s, 1), ranks
    return max(default_world, 1), None


def ring_wire_bytes(op: str, payload: int, n: int, is_start: bool) -> float:
    """Per-participant ring wire bytes.  `payload` is the output-section
    payload (payload_bytes): for sync reduce-scatter that is the SHARD
    (output), for async starts it is the FULL buffer — hence the two
    reduce-scatter formulas."""
    if op == "collective-permute":
        # point-to-point: one hop, group size does not apply (the op
        # carries source_target_pairs, not replica_groups)
        return float(payload)
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * payload
    if op == "all-gather":
        return (n - 1) / n * payload
    if op == "reduce-scatter":
        if is_start:  # payload = full input buffer
            return (n - 1) / n * payload
        return float(n - 1) * payload  # payload = the output shard
    if op == "all-to-all":
        return (n - 1) / n * payload
    return 0.0


def maybe_collective(line: str
                     ) -> Optional[Tuple[str, bool, "re.Match"]]:
    """(base opcode, is_start, LINE_PAT match) when the line defines a
    collective that carries payload, else None ("-done" forms carry
    none).  The cheap substring prefilter runs before any regex work;
    the match rides along so callers read the payload group without a
    second LINE_PAT scan of the same line."""
    if ("all-" not in line and "reduce-scatter" not in line
            and "collective-permute" not in line):
        return None
    m = LINE_PAT.search(line)
    if m is None:
        return None
    op = m.group("op")
    if op.endswith("-done"):
        return None
    is_start = op.endswith("-start")
    base = op[:-6] if is_start else op
    if base not in COLLECTIVE_OPS:
        return None
    return base, is_start, m


def line_wire_bytes(line: str, default_world: int) -> float:
    """Ring wire bytes of one instruction line (0 for non-collectives)."""
    found = maybe_collective(line)
    if found is None:
        return 0.0
    base, is_start, m = found
    payload = payload_bytes(m.group("out"), is_start)
    n, _ranks = first_group(line, default_world)
    return ring_wire_bytes(base, payload, n, is_start)


# ---------------------------------------------------------------------------
# computation structure
# ---------------------------------------------------------------------------

def split_computations(txt: str) -> Dict[str, List[str]]:
    """HLO text -> {computation name: its instruction lines}.  Text with
    no computation headers (synthetic snippets) maps to one anonymous
    computation holding every line."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    loose: List[str] = []
    for line in txt.splitlines():
        m = COMP_HEAD_PAT.match(line)
        if m:
            cur = m.group(1)
            comps[cur] = []
            continue
        if line.strip() == "}":
            cur = None
            continue
        (comps[cur] if cur is not None else loose).append(line)
    if loose:
        comps[""] = loose
    return comps


def entry_computation(txt: str, comps: Optional[Dict[str, List[str]]] = None
                      ) -> str:
    """Name of the ENTRY computation (first computation as fallback for
    synthetic snippets without an ENTRY marker)."""
    m = ENTRY_PAT.search(txt)
    if m is not None:
        return m.group(1)
    if comps is None:
        comps = split_computations(txt)
    return next(iter(comps), "")


def cond_trip_count(lines: List[str]) -> Optional[int]:
    """Trip count from a while condition computation: the
    `compare(induction, constant), direction=LT` form lax.scan lowers to
    (0-based, unit step).  Non-zero-start loops (fori_loop(2, 10, ...))
    are safe too: XLA's while canonicalization rebases the induction to
    0 and folds the start into the bound BEFORE the post-optimization
    text this module parses (regression-pinned in test_comm).  None =
    not statically recoverable."""
    consts = {name: int(val)
              for name, val in (CONST_PAT.search(ln).groups()
                                for ln in lines if CONST_PAT.search(ln))}
    for ln in lines:
        cm = COMPARE_PAT.search(ln)
        if cm is None:
            continue
        dm = DIRECTION_PAT.search(ln)
        direction = dm.group(1) if dm else ""
        lhs, rhs = cm.group(1), cm.group(2)
        if direction == "LT" and rhs in consts:
            return consts[rhs]
        if direction == "GT" and lhs in consts:
            return consts[lhs]
    return None


def while_multipliers(comps: Dict[str, List[str]]
                      ) -> Dict[str, Tuple[int, bool]]:
    """{computation: (effective trip multiplier, dynamic?)} — body
    computations inherit their parent's multiplier times their while's
    trip count; nested whiles compose.  dynamic=True marks an enclosing
    while whose trip could not be resolved (multiplier stays 1 for it).
    Only while-body edges count — the bytes-on-wire accounting, where a
    collective inside a fusion is still top-level in its computation."""
    parent: Dict[str, Tuple[str, Optional[int]]] = {}
    for cname, lines in comps.items():
        for ln in lines:
            if " while(" not in ln and not WHILE_PAT.search(ln):
                continue
            bm = BODY_REF_PAT.search(ln)
            cm = COND_REF_PAT.search(ln)
            if bm is None:
                continue
            trip = None
            if cm is not None and cm.group(1) in comps:
                trip = cond_trip_count(comps[cm.group(1)])
            parent[bm.group(1)] = (cname, trip)

    memo: Dict[str, Tuple[int, bool]] = {}

    def mult(name: str, seen=()) -> Tuple[int, bool]:
        if name in memo:
            return memo[name]
        if name not in parent or name in seen:
            return (1, False)
        pname, trip = parent[name]
        pm, pdyn = mult(pname, seen + (name,))
        out = (pm * (trip if trip else 1), pdyn or trip is None)
        memo[name] = out
        return out

    return {name: mult(name) for name in comps}


def call_multipliers(comps: Dict[str, List[str]]
                     ) -> Dict[str, Tuple[float, bool]]:
    """{computation: (execution multiplier, dynamic?)} — like
    `while_multipliers` but following EVERY call edge (fusion `calls=`,
    `to_apply=`, conditional branches at x1; while bodies at their
    resolved trip count), so a dot inside a fusion inside a scanned
    layer still multiplies by the layer count — the profiler's
    accounting."""
    parent: Dict[str, Tuple[str, Optional[float]]] = {}
    for cname, lines in comps.items():
        for ln in lines:
            is_while = " while(" in ln
            trip: Optional[float] = 1.0
            if is_while:
                cm = COND_REF_PAT.search(ln)
                trip = None
                if cm is not None and cm.group(1) in comps:
                    t = cond_trip_count(comps[cm.group(1)])
                    trip = float(t) if t else None
            for m in CALLEE_PAT.finditer(ln):
                callee = m.group(1)
                if callee not in comps:
                    continue
                # while body multiplies by trip; its condition (and any
                # plain call/fusion) executes with the caller's cadence
                t = trip if (is_while and ln[m.start():m.start() + 4]
                             == "body") else 1.0
                # first caller wins; HLO computations have one caller
                parent.setdefault(callee, (cname, t))
            bm = BRANCH_PAT.search(ln)
            if bm:
                for callee in REF_PAT.findall(bm.group(1)):
                    if callee in comps:
                        parent.setdefault(callee, (cname, 1.0))

    memo: Dict[str, Tuple[float, bool]] = {}

    def mult(name: str, seen=()) -> Tuple[float, bool]:
        if name in memo:
            return memo[name]
        if name not in parent or name in seen:
            return (1.0, False)
        pname, trip = parent[name]
        pm, pdyn = mult(pname, seen + (name,))
        out = (pm * (trip if trip else 1.0), pdyn or trip is None)
        memo[name] = out
        return out

    return {name: mult(name) for name in comps}


# ---------------------------------------------------------------------------
# FLOPs
# ---------------------------------------------------------------------------

def dot_flops(line: str) -> float:
    """FLOPs of one `dot(...)` line: 2 * out_elems * contraction size,
    contraction parsed from the FIRST operand shape (inside the parens)
    and `lhs_contracting_dims`.  0.0 when not statically parseable."""
    om = OUT_PAT.search(line)
    if om is None:
        return 0.0
    out_elems = 0
    for dt, dims in SHAPE_PAT.findall(om.group(1)):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out_elems += n
    paren = line.find(" dot(")
    if paren < 0:
        return 0.0
    operands = line[paren + 5:]
    lhs = SHAPE_PAT.search(operands)
    cm = DOT_CONTRACT_PAT.search(line)
    if lhs is None or cm is None:
        return 0.0
    lhs_dims = [int(d) for d in lhs.group(2).split(",") if d]
    contract = 1
    for idx in cm.group(1).split(","):
        if idx and int(idx) < len(lhs_dims):
            contract *= lhs_dims[int(idx)]
    return 2.0 * out_elems * contract


# ---------------------------------------------------------------------------
# module contracts (donation / entry parameters) — the linter's surface
# ---------------------------------------------------------------------------

def alias_attribute_body(txt: str) -> Optional[str]:
    """The input_output_alias attribute's body (inside its outer
    braces), or None when the module declares no alias.  Extracted by
    brace balancing, NOT a line regex: TPU module headers put
    entry_computation_layout (with tiled layouts like `{1,0:T(8,128)}`)
    after the alias attribute on the same line, and a greedy or
    line-anchored match would capture far past the alias body —
    harvesting `T(8,` as a bogus donated parameter 8.  ONE extractor
    shared by `donated_parameters` and the donation lint's
    aliased-output scan so the two sides of the attribute can never
    parse differently."""
    marker = "input_output_alias={"
    start = txt.find(marker)
    if start < 0:
        return None
    i = start + len(marker)
    depth, j = 1, i
    while j < len(txt) and depth:
        c = txt[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        j += 1
    return txt[i:j - 1]


def donated_parameters(txt: str) -> Tuple[bool, frozenset]:
    """(module declares input_output_alias?, donated entry-parameter
    numbers).  The attribute prints as
    `input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}) }` —
    each value tuple leads with the parameter number."""
    body = alias_attribute_body(txt)
    if body is None:
        return False, frozenset()
    return True, frozenset(int(p) for p in ALIAS_ENTRY_PAT.findall(body))


def entry_parameters(lines: List[str]) -> List[Dict[str, object]]:
    """The entry computation's parameter buffers:
    [{"name", "number", "bytes", "line"}] in definition order."""
    out: List[Dict[str, object]] = []
    num_pat = re.compile(r'parameter\((\d+)\)')
    for i, ln in enumerate(lines):
        m = DEF_PAT.search(ln)
        if m is None or m.group(3) != "parameter":
            continue
        nm = num_pat.search(ln)
        out.append({"name": m.group(1), "number":
                    int(nm.group(1)) if nm else len(out),
                    "bytes": shape_bytes(m.group(2)), "line": i})
    return out
