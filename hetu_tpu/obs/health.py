"""Health monitors: online anomaly detection for training AND serving.

The reference's observability surface stops at recording costs; nothing
watches the run.  ``HealthMonitor`` closes that for training: per-step
EWMA+MAD detectors for the failure shapes that silently waste TPU-days —

    loss_spike             loss jumps far above its EWMA baseline
    nan_loss / nan_grad    non-finite loss / grad norm (an AMP overflow
                           cascade, a data corruption, a bad kernel)
    grad_blowup            grad-norm explosion above baseline
    step_time_regression   step time regresses (a straggling host, a
                           silent recompile, thermal throttling)
    data_stall             the gap BETWEEN steps (host/input time) blows
                           up — the data pipeline, not the device

``NumericsHealthMonitor`` watches the numerics observatory's per-step
stats pytree (obs/numerics.py, HETU_TPU_NUMERICS) for the failure
shapes of aggressive precision reduction — underflow_creep,
quant_snr_collapse, ef_residual_blowup, router_collapse — all invisible
to the scalar monitor until the loss diverges.

``ServingHealthMonitor`` is the serving engine's twin (same EWMA
machinery, same ``anomaly`` record shape, same ``HETU_TPU_HEALTH``
gate), watching the failure shapes of a continuous-batching front end:

    ttft_regression            TTFT far above its EWMA baseline (a
                               compile storm, a straggling reshard, a
                               saturated prefill path)
    queue_depth_blowup         the admission queue grows far past its
                               baseline — arrival rate has outrun
                               decode throughput
    page_exhaustion_imminent   KV page-pool utilization pinned at the
                               high watermark while requests queue —
                               the next admissions will all stall on
                               ``no_pages``

Each firing increments a ``health.<kind>`` counter, emits an ``anomaly``
RunLog event, rides the telemetry push to the coordinator (via the
TelemetrySource, when one is attached), and — for the severe training
kinds — can invoke the emergency-checkpoint hook (PR 3's bank-state-now
path) so a dying run leaves a fresh checkpoint behind.

Detectors use an EWMA mean plus an EWMA absolute deviation (the online
stand-in for median/MAD — robust enough for thresholds, O(1) state) and
fire only after ``warmup`` observations; a per-kind cooldown stops one
regime shift from spamming hundreds of events while the EWMA
re-baselines.  Gated by ``HETU_TPU_HEALTH`` (unset = the trainer/engine
does zero per-step health work); thresholds are constructor knobs,
documented in docs/observability.md.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

from hetu_tpu.utils.logging import get_logger

logger = get_logger("obs.health")

#: MAD -> sigma consistency constant (same convention as the straggler
#: scoring in obs.aggregate)
_MAD_SIGMA = 1.4826


class Ewma:
    """EWMA mean + EWMA absolute deviation, with a sample count."""

    __slots__ = ("alpha", "mean", "dev", "n")

    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.mean: Optional[float] = None
        self.dev = 0.0
        self.n = 0

    def update(self, v: float):
        if self.mean is None:
            self.mean = v
        else:
            a = self.alpha
            self.dev = (1 - a) * self.dev + a * abs(v - self.mean)
            self.mean = (1 - a) * self.mean + a * v
        self.n += 1


class _MonitorBase:
    """The shared detector chassis: EWMA spike rule, per-kind cooldown,
    and the one firing path (counter + ``anomaly`` RunLog record +
    telemetry event + optional emergency hook) both the training and
    serving monitors use — one record shape, one counter namespace."""

    def __init__(self, runlog=None, registry=None, source=None,
                 warmup: int = 8, cooldown_steps: int = 16):
        from hetu_tpu.obs.metrics import get_registry
        self.runlog = runlog
        self.registry = registry if registry is not None else get_registry()
        self.source = source          # optional obs.aggregate.TelemetrySource
        self.warmup = warmup
        self.cooldown_steps = cooldown_steps
        self.emergency_hook = None
        self.emergency_kinds: frozenset = frozenset()
        self._cooldown_until: Dict[str, int] = {}
        self.anomalies_total = 0

    def _spike(self, ewma: Ewma, v: float, k: float,
               ratio: Optional[float] = None) -> bool:
        """v far above the EWMA baseline.  Two independent rules, either
        fires: the additive `mean + k*MAD-sigma` (catches spikes in noisy
        signals, where sigma is meaningful) OR the multiplicative
        `mean * ratio` (carries the decision on steady signals whose
        deviation converged to ~0 — and stays live while a sustained
        regression is inflating the deviation, where the additive
        threshold chases the anomaly)."""
        if ewma.n < self.warmup or ewma.mean is None:
            return False
        if v > ewma.mean + k * (_MAD_SIGMA * ewma.dev
                                + 1e-3 * abs(ewma.mean) + 1e-12):
            return True
        return ratio is not None and v > ewma.mean * ratio

    def _sag(self, ewma: Ewma, v: float, k: float,
             floor: Optional[float] = None) -> bool:
        """v far BELOW the EWMA baseline — the mirror of :meth:`_spike`
        for signals whose failure direction is down (quantization SNR,
        router entropy).  Fires on the additive `mean - k*MAD-sigma`
        rule OR on crossing an absolute `floor` (a level no healthy run
        should visit, baseline notwithstanding); both wait out
        ``warmup`` so the first observations can't self-fire."""
        if ewma.n < self.warmup or ewma.mean is None:
            return False
        if v < ewma.mean - k * (_MAD_SIGMA * ewma.dev
                                + 1e-3 * abs(ewma.mean) + 1e-12):
            return True
        return floor is not None and v < floor

    def _fire(self, kind: str, step: int, value: float,
              baseline: Optional[float], t: float,
              out: List[Dict[str, Any]]):
        if step < self._cooldown_until.get(kind, -1):
            return
        self._cooldown_until[kind] = step + self.cooldown_steps
        self.anomalies_total += 1
        self.registry.inc(f"health.{kind}")
        self.registry.inc("health.anomalies")
        rec = {"kind": "anomaly", "t": t, "anomaly": kind, "step": step,
               "value": value, "baseline": baseline}
        logger.warning(f"anomaly[{kind}] at step {step}: value={value!r} "
                       f"baseline={baseline!r}")
        if self.runlog is not None:
            written = self.runlog.log("anomaly", anomaly=kind, step=step,
                                      value=value, baseline=baseline)
            rec = written or rec
        if self.source is not None:
            self.source.note_event(rec)
        out.append(rec)
        if self.emergency_hook is not None and kind in self.emergency_kinds:
            try:
                self.emergency_hook()
                self.registry.inc("health.emergency_saves")
            except Exception as e:   # telemetry never kills a step
                self.registry.inc("health.emergency_save_failures")
                logger.error(f"emergency hook for {kind} failed: {e!r}")


class HealthMonitor(_MonitorBase):
    """Per-step anomaly detection for a training loop.

    Call :meth:`observe_step` once per completed step.  Returns the list
    of anomalies fired on that step (empty almost always) — the caller
    never needs to look at it; counters/RunLog carry the signal.

    ``emergency_hook`` (no-arg callable, e.g. a bound ``save``) runs on
    kinds in ``emergency_kinds`` — best-effort, never raises into the
    training loop.
    """

    KINDS = ("loss_spike", "nan_loss", "nan_grad", "grad_blowup",
             "step_time_regression", "data_stall")

    def __init__(self, runlog=None, registry=None, source=None,
                 emergency_hook=None,
                 emergency_kinds=("nan_loss", "nan_grad"),
                 warmup: int = 8, alpha: float = 0.1,
                 loss_k: float = 6.0, grad_k: float = 8.0,
                 step_time_k: float = 6.0, step_time_ratio: float = 2.0,
                 stall_ratio: float = 5.0, stall_min_s: float = 1.0,
                 cooldown_steps: int = 16):
        super().__init__(runlog=runlog, registry=registry, source=source,
                         warmup=warmup, cooldown_steps=cooldown_steps)
        self.emergency_hook = emergency_hook
        self.emergency_kinds = frozenset(emergency_kinds)
        self.loss_k, self.grad_k = loss_k, grad_k
        self.step_time_k, self.step_time_ratio = step_time_k, step_time_ratio
        self.stall_ratio, self.stall_min_s = stall_ratio, stall_min_s
        self._loss = Ewma(alpha)
        self._grad = Ewma(alpha)
        self._step_time = Ewma(alpha)
        self._fetch = Ewma(alpha)
        self._last_t: Optional[float] = None

    # ------------------------------------------------------------------
    def observe_step(self, step: int, step_time_s: float, *,
                     loss: Optional[float] = None,
                     grad_norm: Optional[float] = None,
                     t: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one completed step; returns anomalies fired (usually [])."""
        t = time.time() if t is None else t
        fired: List[Dict[str, Any]] = []

        # data stall: host/input time = inter-observe gap minus the step
        # itself.  The device can be perfectly healthy while the input
        # pipeline starves it — that shows up HERE and nowhere else.
        if self._last_t is not None:
            fetch = max(0.0, (t - self._last_t) - step_time_s)
            if self._fetch.n >= self.warmup and fetch > max(
                    self.stall_min_s,
                    (self._fetch.mean or 0.0) * self.stall_ratio):
                self._fire("data_stall", step, fetch, self._fetch.mean,
                           t, fired)
            self._fetch.update(fetch)
        self._last_t = t

        if loss is not None:
            if not math.isfinite(loss):
                self._fire("nan_loss", step, loss, self._loss.mean, t, fired)
            else:
                if self._spike(self._loss, loss, self.loss_k):
                    self._fire("loss_spike", step, loss, self._loss.mean,
                               t, fired)
                self._loss.update(loss)

        if grad_norm is not None:
            if not math.isfinite(grad_norm):
                self._fire("nan_grad", step, grad_norm, self._grad.mean,
                           t, fired)
            else:
                if self._spike(self._grad, grad_norm, self.grad_k):
                    self._fire("grad_blowup", step, grad_norm,
                               self._grad.mean, t, fired)
                self._grad.update(grad_norm)

        if self._spike(self._step_time, step_time_s, self.step_time_k,
                       ratio=self.step_time_ratio):
            self._fire("step_time_regression", step, step_time_s,
                       self._step_time.mean, t, fired)
        self._step_time.update(step_time_s)
        return fired


def maybe_health_monitor(runlog=None, source=None, emergency_hook=None,
                         **kw) -> Optional[HealthMonitor]:
    """A HealthMonitor when HETU_TPU_HEALTH is set, else None — the one
    gate every training loop uses, so 'flag unset' provably means zero
    per-step health work (a single None check)."""
    from hetu_tpu.utils import flags
    if not flags.bool_flag("HETU_TPU_HEALTH"):
        return None
    return HealthMonitor(runlog=runlog, source=source,
                         emergency_hook=emergency_hook, **kw)


class ServingHealthMonitor(_MonitorBase):
    """Per-engine-step anomaly detection for the serving front end.

    The engine calls :meth:`observe_ttft` once per first token and
    :meth:`observe_step` once per engine step (docs/serving.md); all
    clocks are the DRIVER's (virtual in replayed traces), so detector
    firings are deterministic under a simulated timeline.

    Detectors (thresholds are constructor knobs):

    * ``ttft_regression`` — TTFT above the EWMA additive threshold OR
      ``ttft_ratio`` x baseline (the same two-rule spike the training
      step-time detector uses).
    * ``queue_depth_blowup`` — queue depth >= ``queue_min`` AND above
      baseline by the spike rule with ``queue_ratio``: arrivals have
      outrun decode throughput, latency is compounding.
    * ``page_exhaustion_imminent`` — page-pool utilization at or above
      ``page_high`` for ``page_streak`` consecutive steps while
      requests queue: the next admissions will all stall ``no_pages``.
    * ``brownout_shed`` — not a detector: the engine's brownout policy
      (HETU_TPU_SERVE_BROWNOUT) reports each shed through
      :meth:`note_brownout`, so load-shedding rides the same anomaly
      stream, counters, and cooldown as the organic detectors.
    """

    KINDS = ("ttft_regression", "queue_depth_blowup",
             "page_exhaustion_imminent", "brownout_shed")

    def __init__(self, runlog=None, registry=None, source=None,
                 warmup: int = 8, alpha: float = 0.2,
                 ttft_k: float = 6.0, ttft_ratio: float = 3.0,
                 queue_k: float = 8.0, queue_ratio: float = 4.0,
                 queue_min: int = 4,
                 page_high: float = 0.95, page_streak: int = 4,
                 cooldown_steps: int = 16):
        super().__init__(runlog=runlog, registry=registry, source=source,
                         warmup=warmup, cooldown_steps=cooldown_steps)
        self.ttft_k, self.ttft_ratio = ttft_k, ttft_ratio
        self.queue_k, self.queue_ratio = queue_k, queue_ratio
        self.queue_min = queue_min
        self.page_high, self.page_streak = page_high, page_streak
        self._ttft = Ewma(alpha)
        self._queue = Ewma(alpha)
        self._page_hot = 0

    # ------------------------------------------------------------------
    def observe_ttft(self, ttft_s: float, *, step: int,
                     t: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one request's TTFT (engine-step `step` for cooldown)."""
        t = time.time() if t is None else t
        fired: List[Dict[str, Any]] = []
        if self._spike(self._ttft, ttft_s, self.ttft_k,
                       ratio=self.ttft_ratio):
            self._fire("ttft_regression", step, ttft_s, self._ttft.mean,
                       t, fired)
        self._ttft.update(ttft_s)
        return fired

    def observe_step(self, step: int, *, queue_depth: int,
                     page_util: float,
                     t: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one completed engine step's load signals."""
        t = time.time() if t is None else t
        fired: List[Dict[str, Any]] = []
        if queue_depth >= self.queue_min and self._spike(
                self._queue, float(queue_depth), self.queue_k,
                ratio=self.queue_ratio):
            self._fire("queue_depth_blowup", step, float(queue_depth),
                       self._queue.mean, t, fired)
        self._queue.update(float(queue_depth))

        # exhaustion-imminent is a level rule, not a spike rule: a pool
        # DESIGNED to run hot only fires when the queue shows demand the
        # pool can no longer absorb
        if page_util >= self.page_high and queue_depth > 0:
            self._page_hot += 1
            if self._page_hot >= self.page_streak:
                self._fire("page_exhaustion_imminent", step,
                           float(page_util), self.page_high, t, fired)
        else:
            self._page_hot = 0
        return fired

    def note_brownout(self, step: int, *, shed: int, page_util: float,
                      t: Optional[float] = None) -> List[Dict[str, Any]]:
        """The engine's brownout policy shed `shed` queued requests at
        engine step `step` (HETU_TPU_SERVE_BROWNOUT) — meter it as a
        ``brownout_shed`` anomaly (value = requests shed, baseline =
        the page utilization that tripped the policy).  Per-kind
        cooldown applies like any detector, so a sustained brownout
        logs at the cooldown cadence, not every step."""
        t = time.time() if t is None else t
        fired: List[Dict[str, Any]] = []
        self._fire("brownout_shed", step, float(shed), float(page_util),
                   t, fired)
        return fired


class NumericsHealthMonitor(_MonitorBase):
    """Detectors over the numerics observatory's per-step stats pytree
    (obs/numerics.py, HETU_TPU_NUMERICS) — the failure shapes of
    aggressive precision reduction, caught while the loss still looks
    healthy:

    * ``underflow_creep`` — a scope's bf16-underflow fraction is both
      above ``underflow_min`` AND spiking vs its own EWMA baseline
      (weights/grads/activations sliding below the smallest normal:
      silent signal loss long before NaNs).
    * ``quant_snr_collapse`` — a compressed path's measured SNR sags
      far below its baseline or under ``snr_floor_db`` (a bad scale, a
      distribution shift the int8 grid can no longer represent).
    * ``ef_residual_blowup`` — the error-feedback residual RMS spikes
      (the compressor is systematically behind; convergence is next).
    * ``router_collapse`` — max expert load at/above
      ``router_load_max`` for ``router_streak`` consecutive records, or
      router entropy sagging below baseline (one expert is eating the
      batch; the rest are dying).

    Same chassis as the other monitors: per-kind cooldown, health.*
    counters, ``anomaly`` RunLog events, telemetry ride-along — and the
    same ``HETU_TPU_HEALTH`` gate (one switch, whole health surface).

    Call :meth:`observe` once per recorded numerics step with the
    (host-fetched) ``{scope: {stat: value}}`` dict.
    """

    KINDS = ("underflow_creep", "quant_snr_collapse",
             "ef_residual_blowup", "router_collapse")

    def __init__(self, runlog=None, registry=None, source=None,
                 warmup: int = 8, alpha: float = 0.2,
                 underflow_min: float = 0.05, underflow_k: float = 6.0,
                 snr_k: float = 6.0, snr_floor_db: float = 10.0,
                 ef_k: float = 8.0,
                 router_load_max: float = 0.7, router_streak: int = 2,
                 entropy_k: float = 6.0,
                 cooldown_steps: int = 16):
        super().__init__(runlog=runlog, registry=registry, source=source,
                         warmup=warmup, cooldown_steps=cooldown_steps)
        self.alpha = alpha
        self.underflow_min, self.underflow_k = underflow_min, underflow_k
        self.snr_k, self.snr_floor_db = snr_k, snr_floor_db
        self.ef_k = ef_k
        self.router_load_max, self.router_streak = (router_load_max,
                                                    router_streak)
        self.entropy_k = entropy_k
        self._ewma: Dict[tuple, Ewma] = {}
        self._router_hot = 0

    def _e(self, *key) -> Ewma:
        e = self._ewma.get(key)
        if e is None:
            e = self._ewma[key] = Ewma(self.alpha)
        return e

    def observe(self, step: int, scopes: Dict[str, Dict[str, Any]],
                *, t: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one recorded numerics step; returns anomalies fired."""
        t = time.time() if t is None else t
        fired: List[Dict[str, Any]] = []
        for scope, stats in sorted((scopes or {}).items()):
            uf = stats.get("underflow_frac")
            if uf is not None and math.isfinite(uf):
                e = self._e("uf", scope)
                if uf >= self.underflow_min and self._spike(
                        e, uf, self.underflow_k, ratio=3.0):
                    self._fire("underflow_creep", step, uf, e.mean, t,
                               fired)
                e.update(uf)
            snr = stats.get("snr_db")
            if snr is not None and math.isfinite(snr):
                e = self._e("snr", scope)
                if self._sag(e, snr, self.snr_k,
                             floor=self.snr_floor_db):
                    self._fire("quant_snr_collapse", step, snr, e.mean,
                               t, fired)
                e.update(snr)
            if scope == "ef":
                rms = stats.get("rms")
                if rms is not None and math.isfinite(rms):
                    e = self._e("ef", scope)
                    if self._spike(e, rms, self.ef_k, ratio=4.0):
                        self._fire("ef_residual_blowup", step, rms,
                                   e.mean, t, fired)
                    e.update(rms)
            if scope == "moe":
                lm = stats.get("load_max")
                if lm is not None and math.isfinite(lm):
                    # level rule with a streak: a router pinned on one
                    # expert is collapsed NOW, whatever the baseline
                    # was.  `load` is token-denominated (a balanced
                    # top-k router sits at k/E), so the threshold rises
                    # to 2x balanced for high-k/E configs — a fixed
                    # 0.7 would alarm permanently on e.g. E=4, k=3
                    # (balanced load_max 0.75); past 1.0 the level
                    # rule is unreachable and the entropy sag carries
                    # the detection alone.
                    load = stats.get("load")
                    thresh = self.router_load_max
                    if load is not None and len(load):
                        # load may be a list (RunLog) or ndarray (the
                        # raw device_get pytree) — take plain floats
                        ksum = float(sum(float(v) for v in load))
                        thresh = max(thresh, 2.0 * ksum / len(load))
                    if thresh <= 1.0 + 1e-9 and lm >= thresh - 1e-6:
                        self._router_hot += 1
                        if self._router_hot >= self.router_streak:
                            self._fire("router_collapse", step, lm,
                                       thresh, t, fired)
                    else:
                        self._router_hot = 0
                ent = stats.get("entropy")
                if ent is not None and math.isfinite(ent):
                    e = self._e("entropy", scope)
                    if self._sag(e, ent, self.entropy_k):
                        self._fire("router_collapse", step, ent, e.mean,
                                   t, fired)
                    e.update(ent)
        return fired


def maybe_numerics_health_monitor(runlog=None, source=None, **kw
                                  ) -> Optional[NumericsHealthMonitor]:
    """A NumericsHealthMonitor when HETU_TPU_HEALTH is set, else None —
    the numerics observatory's single-None-check gate (same flag as the
    scalar training monitor: one switch turns the whole health surface
    on; the stats themselves additionally need HETU_TPU_NUMERICS)."""
    from hetu_tpu.utils import flags
    if not flags.bool_flag("HETU_TPU_HEALTH"):
        return None
    return NumericsHealthMonitor(runlog=runlog, source=source, **kw)


def maybe_serving_health_monitor(runlog=None, source=None, **kw
                                 ) -> Optional[ServingHealthMonitor]:
    """A ServingHealthMonitor when HETU_TPU_HEALTH is set, else None —
    the serving engine's single-None-check gate (same flag as training:
    one switch turns the whole health surface on)."""
    from hetu_tpu.utils import flags
    if not flags.bool_flag("HETU_TPU_HEALTH"):
        return None
    return ServingHealthMonitor(runlog=runlog, source=source, **kw)
