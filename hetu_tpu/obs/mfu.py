"""Hardware-free MFU / roofline reporter.

The TPU tunnel being down must not make perf unverifiable: this module
estimates MFU for a compiled train step WITHOUT running it, by combining

  * XLA's own FLOP count — `jit(...).lower().compile().cost_analysis()`
    (exact for the compiled program, available on any backend incl. CPU),
  * the chip peaks in `hardware_profile_v5e.json` (bf16 TFLOP/s, HBM GB/s,
    plus the measured ceilings recorded when hardware WAS reachable),
  * the per-phase HLO attribution from `utils.profiling.phase_breakdown`
    (dots ~ MXU work share, out_bytes ~ HBM traffic share).

Per phase, the roofline bound is
    t_phase = max(flops_phase / compute_rate, bytes_phase / hbm_rate)
and the estimated step time is the sum over phases (TPU phases serialize on
the single compute stream).  Estimated MFU = flops / (peak * t_est) — an
UPPER BOUND on achievable MFU for this program on this chip: it prices
compute and HBM traffic but not ICI collectives or host stalls.  BENCH
records carry it as `estimated_mfu` next to (or in lieu of) measured MFU.

When not even a compile is possible (e.g. bench's unreachable-backend
path before jax device init), `analytic_transformer_estimate` computes the
same report from a model config's analytic FLOPs and a parameter/activation
traffic model — pure python, no jax.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

#: fallback chip numbers when no profile file is on disk (v5e)
_DEFAULT_HW = {
    "chip": "v5e",
    "bf16_tflops": 197.0,
    "hbm_gbytes": 16.0,
    "hbm_gbps": 820.0,
    "measured": {},
}


#: required top-level keys of a hardware profile (value must be a
#: positive number unless noted) — obs.mfu and obs.comm read these
#: unconditionally, so a profile missing one must fail LOUDLY at load,
#: not as a KeyError deep in a report
_REQUIRED_KEYS = ("bf16_tflops", "hbm_gbytes", "hbm_gbps",
                  "ici_allreduce_gbps", "ici_p2p_gbps")
_TOPOLOGY_KEYS = ("slice_devices", "intra_gbps", "inter_gbps")


def validate_hardware_profile(hw: Dict[str, Any],
                              source: str = "<dict>") -> Dict[str, Any]:
    """Schema-check a hardware profile, naming the offending key.

    Required: `chip` (string) plus positive numbers for each of
    {bf16_tflops, hbm_gbytes, hbm_gbps, ici_allreduce_gbps,
    ici_p2p_gbps}.  Optional: `dcn_gbps` (positive number), `measured`
    (dict of numbers), and `topology` — which, when present, must carry
    positive {slice_devices (integer), intra_gbps, inter_gbps} and may
    carry `slice_shape` (list of positive ints whose product equals
    slice_devices).  Returns `hw` unchanged on success."""
    def fail(key, why):
        raise ValueError(
            f"invalid hardware profile ({source}): key {key!r} {why}")

    if not isinstance(hw, dict):
        raise ValueError(
            f"invalid hardware profile ({source}): expected a JSON "
            f"object, got {type(hw).__name__}")
    if not isinstance(hw.get("chip"), str) or not hw.get("chip"):
        fail("chip", "must be a non-empty string")
    for k in _REQUIRED_KEYS:
        if k not in hw:
            fail(k, "is missing")
        v = hw[k]
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
            fail(k, f"must be a positive number, got {v!r}")
    if "dcn_gbps" in hw:
        v = hw["dcn_gbps"]
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
            fail("dcn_gbps", f"must be a positive number, got {v!r}")
    meas = hw.get("measured", {})
    if meas is not None and not isinstance(meas, dict):
        fail("measured", f"must be an object, got {type(meas).__name__}")
    for k, v in (meas or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            fail(f"measured.{k}", f"must be a number, got {v!r}")
    topo = hw.get("topology")
    if topo is not None:
        if not isinstance(topo, dict):
            fail("topology", f"must be an object, got {type(topo).__name__}")
        for k in _TOPOLOGY_KEYS:
            if k not in topo:
                fail(f"topology.{k}", "is missing")
            v = topo[k]
            if (not isinstance(v, (int, float)) or isinstance(v, bool)
                    or v <= 0):
                fail(f"topology.{k}", f"must be a positive number, got {v!r}")
        if topo["slice_devices"] != int(topo["slice_devices"]):
            fail("topology.slice_devices",
                 f"must be an integer, got {topo['slice_devices']!r}")
        shape = topo.get("slice_shape")
        if shape is not None:
            if (not isinstance(shape, (list, tuple)) or not shape
                    or any(not isinstance(d, int) or isinstance(d, bool)
                           or d <= 0 for d in shape)):
                fail("topology.slice_shape",
                     f"must be a list of positive integers, got {shape!r}")
            prod = 1
            for d in shape:
                prod *= d
            if prod != int(topo["slice_devices"]):
                fail("topology.slice_shape",
                     f"product {prod} != slice_devices "
                     f"{topo['slice_devices']}")
    return hw


def load_hardware_profile(path: Optional[str] = None) -> Dict[str, Any]:
    """Load a hardware profile JSON.  Resolution: explicit `path` ->
    HETU_TPU_HW_PROFILE env -> repo-root hardware_profile_v5e.json ->
    built-in v5e constants.  A file that OPENS but fails to parse or
    validate raises loudly (naming the file and the offending key) —
    silently falling through to defaults would let a typo'd profile
    skew every MFU/comm estimate."""
    candidates = []
    if path:
        candidates.append(path)
    from hetu_tpu.utils import flags
    env = flags.str_flag("HETU_TPU_HW_PROFILE")
    if env:
        candidates.append(env)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates.append(os.path.join(root, "hardware_profile_v5e.json"))
    for c in candidates:
        try:
            with open(c) as f:
                raw = f.read()
        except OSError:
            continue
        try:
            hw = json.loads(raw)
        except ValueError as e:
            raise ValueError(
                f"invalid hardware profile ({c}): not valid JSON: {e}"
            ) from None
        return validate_hardware_profile(hw, source=c)
    return dict(_DEFAULT_HW)


def _rates(hw: Dict[str, Any]):
    """(compute FLOP/s ceiling, HBM byte/s ceiling, peak FLOP/s).

    The MFU denominator is always the datasheet peak; the roofline TIME
    uses the measured ceilings when the profile carries them (what the
    chip actually sustains)."""
    peak = float(hw.get("bf16_tflops", _DEFAULT_HW["bf16_tflops"])) * 1e12
    meas = hw.get("measured") or {}
    compute = float(meas.get("matmul_tflops") or 0.0) * 1e12 or peak
    hbm = (float(meas.get("hbm_gbps") or 0.0) or
           float(hw.get("hbm_gbps", _DEFAULT_HW["hbm_gbps"]))) * 1e9
    return compute, hbm, peak


def flops_of_compiled(compiled) -> float:
    """XLA's FLOP estimate for a compiled executable (0.0 if the backend
    does not report one).  cost_analysis() is a dict on current jax and a
    per-device list-of-dict on older releases."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return 0.0
    return float(ca.get("flops", 0.0) or 0.0)


def estimate_mfu(flops_per_step: float, *,
                 hw: Optional[Dict[str, Any]] = None,
                 phases: Optional[Dict[str, Dict[str, float]]] = None,
                 total_bytes: Optional[float] = None,
                 measured_step_s: Optional[float] = None) -> Dict[str, Any]:
    """Roofline-estimate MFU for one train step.

    phases: `phase_breakdown` output ({phase: {dots, out_bytes, ...}});
    step FLOPs are apportioned to phases by their dot-count share and each
    phase is bounded by max(compute, memory) time.  Without phases, a
    single-bucket roofline over `total_bytes` (or pure compute) is used.
    measured_step_s, when available, adds the measured MFU alongside.
    """
    flops = float(flops_per_step)
    hw = hw if hw is not None else load_hardware_profile()
    compute, hbm, peak = _rates(hw)
    report: Dict[str, Any] = {
        "flops_per_step": flops,
        "peak_flops": peak,
        "chip": hw.get("chip", "unknown"),
    }
    if flops <= 0:
        report.update(estimated_step_s=None, estimated_mfu=0.0)
        return report

    if phases:
        total_dots = sum(p.get("dots", 0) for p in phases.values()) or 1
        per_phase = {}
        t_est = 0.0
        for name, p in phases.items():
            f_p = flops * p.get("dots", 0) / total_dots
            b_p = float(p.get("out_bytes", 0))
            t_c = f_p / compute
            t_m = b_p / hbm
            t_p = max(t_c, t_m)
            if t_p <= 0:
                continue
            per_phase[name] = {
                "flops": f_p, "bytes": b_p, "time_s": t_p,
                "bound": "memory" if t_m > t_c else "compute",
            }
            t_est += t_p
        report["phases"] = per_phase
    else:
        t_c = flops / compute
        t_m = (float(total_bytes) / hbm) if total_bytes else 0.0
        t_est = max(t_c, t_m)
        report["bound"] = "memory" if t_m > t_c else "compute"

    report["estimated_step_s"] = t_est
    report["estimated_mfu"] = (flops / (peak * t_est)) if t_est > 0 else 0.0
    if measured_step_s:
        report["measured_step_s"] = float(measured_step_s)
        report["measured_mfu"] = flops / (peak * float(measured_step_s))
    return report


def kernel_roofline(traffic: Dict[str, Dict[str, Any]], *,
                    hw: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Dict[str, Any]]:
    """Per-kernel roofline attribution for the fused-kernel layer: price
    each kernel's fused vs unfused analytic HBM bytes
    (ops/pallas/traffic.py) at the profiled chip's HBM rate.

    These chains are memory-bound by construction (elementwise /
    reduction work per byte is far below the ridge point), so the
    roofline time IS bytes / hbm_rate and the per-kernel efficiency win
    is the byte reduction itself: `speedup` = unfused_s / fused_s.
    Hardware-free like every bench claim while the tunnel is down."""
    hw = hw if hw is not None else load_hardware_profile()
    _, hbm, _ = _rates(hw)
    out: Dict[str, Dict[str, Any]] = {}
    for name, rec in traffic.items():
        fused_s = rec["fused_bytes"] / hbm
        unfused_s = rec["unfused_bytes"] / hbm
        out[name] = {
            "fused_bytes": rec["fused_bytes"],
            "unfused_bytes": rec["unfused_bytes"],
            "fused_s": fused_s,
            "unfused_s": unfused_s,
            "speedup": unfused_s / fused_s if fused_s else float("inf"),
            "bound": "memory",
        }
    return out


def estimate_from_compiled(compiled, *, hw: Optional[Dict] = None,
                           with_phases: bool = True,
                           measured_step_s: Optional[float] = None
                           ) -> Dict[str, Any]:
    """Full hardware-free report for a compiled step: cost_analysis FLOPs +
    (optionally) the per-phase HLO attribution.  with_phases=False skips
    the HLO text parse (large programs) and uses the single-bucket
    roofline over cost_analysis' byte estimate when present."""
    flops = flops_of_compiled(compiled)
    phases = None
    total_bytes = None
    if with_phases:
        try:
            from hetu_tpu.utils.profiling import phase_breakdown
            phases = phase_breakdown(compiled)
        except Exception:
            phases = None
    if phases is None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            total_bytes = float(ca.get("bytes accessed", 0.0) or 0.0) or None
        except Exception:
            total_bytes = None
    return estimate_mfu(flops, hw=hw, phases=phases,
                        total_bytes=total_bytes,
                        measured_step_s=measured_step_s)


def analytic_transformer_estimate(cfg, batch: int, seq: int, *,
                                  hw: Optional[Dict] = None,
                                  param_bytes: int = 2) -> Dict[str, Any]:
    """Jax-free estimate from a model config exposing flops_per_token(seq)
    and num_params() (LlamaConfig/GPT config): analytic train FLOPs plus a
    coarse HBM traffic model — params read fwd + bwd + optimizer update
    (3 passes over the weights) and one activation write/read per layer
    boundary.  This is the bench fallback when the backend is unreachable
    and nothing can even compile."""
    flops = float(batch) * seq * float(cfg.flops_per_token(seq))
    n_params = float(cfg.num_params())
    weight_traffic = 3.0 * n_params * param_bytes
    layers = float(getattr(cfg, "num_hidden_layers", 0) or 0)
    hidden = float(getattr(cfg, "hidden_size", 0) or 0)
    act_traffic = 2.0 * batch * seq * hidden * layers * param_bytes
    rep = estimate_mfu(flops, hw=hw,
                       total_bytes=weight_traffic + act_traffic)
    rep["analytic"] = True
    rep["batch"], rep["seq"] = batch, seq
    return rep
