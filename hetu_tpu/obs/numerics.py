"""The numerics observatory: in-graph tensor statistics, quantization
SNR accounting, and the host-side recording pipeline.

Hetu's scale story runs on aggressive precision reduction — bf16
compute, int8/int4 collectives, quantized ZeRO refresh, int8 KV pages —
but until this module nothing watched the numbers themselves: the health
monitor saw only scalar loss/grad-norm, so underflow creep, SNR
collapse on a compressed path, EF-residual blowup or a collapsing MoE
router were invisible until the loss diverged.

Design
------
Stats are computed *inside* the jitted step (tiny reductions traced at
the tap site) and returned as an auxiliary pytree of scalars — no host
round-trip per tensor, donation-safe, and host-fetched only when
``HETU_TPU_NUMERICS`` is on.  The mechanics:

* ``collecting()`` installs a thread-local :class:`Collector` for the
  duration of one traced step (the trainer/serving engine wraps its
  step function).  Unset flag = the wrapper never runs = the traced
  program is byte-identical to the seed (registered identity contract,
  enforced by the flag-identity sweep on all canonical programs).
* ``tap_tree`` / ``tap_stats`` / ``tap_quant_error`` record values into
  the collector's top *frame*.  Each frame remembers the JAX trace it
  was opened under; a tap arriving from a *different* trace (inside a
  ``lax.scan`` body, a ``vmap``, a ``custom_vjp`` — anywhere its value
  could not legally escape to the frame's return) is silently skipped
  and counted, never leaked.  Sites under such transforms instead
  return their stats explicitly, through one of the bridges below.
* ``frame()`` opens a nested frame whose stats are handed back to the
  enclosing code as a pytree — the bridge out of ``value_and_grad``
  (the trainer's micro-batch loss), out of ``shard_map`` bodies (the
  quantized grad sync, the ZeRO refresh), and out of anything else
  that must thread values through a transform boundary.
  ``reduce_stacked`` folds a scan-stacked stats tree, ``reduce_axis``
  folds a mesh axis inside a ``shard_map`` body, and ``merge`` folds a
  returned stats tree back into the ambient collector — each stat
  carries its own reduction rule (max for absmax, sum for counts and
  signal/error powers, mean otherwise).
* ``Collector.finalize()`` resolves accumulated signal/error powers
  into per-scope ``snr_db`` and returns the ``{scope: {stat: value}}``
  pytree the step emits.

Host side, ``record()`` is the one sink: a schema-versioned
``numerics`` RunLog record, labeled gauges/histograms in the metrics
registry (``numerics.*`` per scope, ``moe.expert_load`` /
``moe.capacity_dropped`` / ``moe.router_entropy`` — the live
expert-load gauges ROADMAP item 1 names; gauges ride the existing
cluster telemetry push), and ``summarize_numerics`` is THE reader both
``tools_numerics.py`` and ``tools_obs_report.py`` render from.

Stats per tensor scope: ``absmax``, ``rms``, ``l2``, ``nonfinite``
(count), ``underflow_frac`` / ``overflow_frac`` (fraction of nonzero
values whose magnitude falls below the smallest normal / above the max
of the tensor's 16-bit reference dtype — bf16 unless the tensor is
already f16/bf16).  Quantized paths add ``snr_db`` (exact: measured
from the same comm/compress primitives the wire uses); the MoE scope
adds ``load`` (per-expert routing fraction), ``load_max``, ``entropy``
(router entropy, nats), ``dropped`` and ``drop_frac`` (capacity
drops).  See docs/observability.md for the full table and the detector
thresholds that consume these (obs.health.NumericsHealthMonitor).
"""
from __future__ import annotations

import contextlib
import math
import threading
from typing import Any, Dict, Iterable, List, Optional

#: schema version stamped on every ``numerics`` RunLog record
NUMERICS_SCHEMA = 1

# ---------------------------------------------------------------------------
# reduction rules: how one stat combines across repeated taps, scan
# stacking, and mesh axes.  Unknown names default to mean.
# ---------------------------------------------------------------------------
_SUM_STATS = frozenset({"nonfinite", "dropped", "sig_pow", "err_pow",
                        "count", "tokens"})
_MAX_STATS = frozenset({"absmax", "load_max"})


def rule_for(name: str) -> str:
    if name in _SUM_STATS:
        return "sum"
    if name in _MAX_STATS:
        return "max"
    return "mean"


# ---------------------------------------------------------------------------
# flag gates
# ---------------------------------------------------------------------------

def numerics_enabled() -> bool:
    """The HETU_TPU_NUMERICS gate (read at build time by the trainer and
    the serving engine — the registered identity contract is that unset
    leaves every canonical program traced-HLO byte-identical)."""
    from hetu_tpu.utils import flags
    return flags.bool_flag("HETU_TPU_NUMERICS")


def record_every() -> int:
    """HETU_TPU_NUMERICS_EVERY: host-fetch/record sampling interval in
    steps (the in-graph stats are computed every step either way — the
    traced program cannot depend on a host-side sampling phase)."""
    from hetu_tpu.utils import flags
    return max(1, flags.int_flag("HETU_TPU_NUMERICS_EVERY"))


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------

def _cur_trace():
    from jax.core import trace_ctx
    return trace_ctx.trace


class _Frame:
    __slots__ = ("trace", "acc")

    def __init__(self):
        self.trace = _cur_trace()
        # scope -> stat -> [rule, value, count]
        self.acc: Dict[str, Dict[str, list]] = {}

    def add(self, scope: str, name: str, value):
        sc = self.acc.setdefault(scope, {})
        rule = rule_for(name)
        slot = sc.get(name)
        if slot is None:
            sc[name] = [rule, value, 1]
            return
        if rule == "sum":
            slot[1] = slot[1] + value
        elif rule == "max":
            import jax.numpy as jnp
            slot[1] = jnp.maximum(slot[1], value)
        else:
            slot[1] = slot[1] + value
            slot[2] += 1

    def resolve(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for scope, stats in self.acc.items():
            dst = out.setdefault(scope, {})
            for name, (rule, value, count) in stats.items():
                dst[name] = value / count if (rule == "mean"
                                              and count > 1) else value
        return out


class Collector:
    """Per-step tap accumulator (install via :func:`collecting`)."""

    def __init__(self):
        self.frames: List[_Frame] = [_Frame()]
        self.skipped = 0      # taps rejected by the trace guard

    def push_frame(self):
        self.frames.append(_Frame())

    def pop_frame(self) -> Dict[str, Dict[str, Any]]:
        return self.frames.pop().resolve()

    def finalize(self) -> Dict[str, Dict[str, Any]]:
        """Resolve the root frame into the step's stats pytree,
        converting accumulated signal/error powers into ``snr_db``."""
        assert len(self.frames) == 1, "unbalanced numerics frames"
        return _with_snr(self.frames[0].resolve())


def _with_snr(scopes: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    import jax.numpy as jnp
    for stats in scopes.values():
        if "sig_pow" in stats and "err_pow" in stats:
            sig, err = stats["sig_pow"], stats["err_pow"]
            stats["snr_db"] = 10.0 * jnp.log10(
                (sig + 1e-30) / (err + 1e-30))
    return scopes


_tls = threading.local()


def _current() -> Optional[Collector]:
    return getattr(_tls, "collector", None)


def active() -> bool:
    """A collector is installed on this thread (static during one trace
    — gate any stats-only computation on this so the unset-flag program
    stays byte-identical)."""
    return _current() is not None


@contextlib.contextmanager
def collecting():
    """Install a Collector for the duration of one (traced) step body."""
    prev = _current()
    col = Collector()
    _tls.collector = col
    try:
        yield col
    finally:
        _tls.collector = prev


class _FrameHandle:
    __slots__ = ("stats",)

    def __init__(self):
        self.stats: Dict[str, Dict[str, Any]] = {}


@contextlib.contextmanager
def frame():
    """Open a nested frame; on exit its resolved stats land on the
    handle (``{}`` when no collector is installed).  THE bridge for taps
    under a transform: push inside the transformed function, return the
    handle's stats through the function's own outputs."""
    h = _FrameHandle()
    col = _current()
    if col is None:
        yield h
        return
    col.push_frame()
    try:
        yield h
    finally:
        h.stats = col.pop_frame()


# ---------------------------------------------------------------------------
# taps
# ---------------------------------------------------------------------------

def _tap(scope: str, name: str, value):
    col = _current()
    if col is None:
        return
    fr = col.frames[-1]
    if fr.trace is not _cur_trace():
        # inside a scan/vmap/custom_vjp body relative to the open frame:
        # the value could not legally escape — skip, never leak
        col.skipped += 1
        return
    fr.add(scope, name, value)


def tap_stats(scope: str, **stats):
    """Record raw stat scalars (or small vectors) under ``scope``."""
    for name, value in stats.items():
        _tap(scope, name, value)


def tree_stats(tree) -> Dict[str, Any]:
    """Pure in-graph tensor statistics over a pytree of float arrays:
    absmax / rms / l2 / nonfinite count / bf16(f16) underflow+overflow
    fractions.  Usable anywhere (no collector needed)."""
    import jax
    import jax.numpy as jnp
    leaves = [x for x in jax.tree.leaves(tree)
              if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
    if not leaves:
        return {}
    n = 0
    sum_sq = jnp.zeros((), jnp.float32)
    absmax = jnp.zeros((), jnp.float32)
    nonfinite = jnp.zeros((), jnp.int32)
    under = jnp.zeros((), jnp.int32)
    over = jnp.zeros((), jnp.int32)
    n_finite = jnp.zeros((), jnp.int32)
    n_nonzero = jnp.zeros((), jnp.int32)   # finite AND nonzero
    for x in leaves:
        # reference dtype for the range fractions: the tensor's own
        # 16-bit dtype when it has one, else bf16 (the compute dtype the
        # precision-reduction story cares about)
        ref = x.dtype if x.dtype in (jnp.bfloat16, jnp.float16) \
            else jnp.bfloat16
        fi = jnp.finfo(ref)
        # the underflow zone sits a margin ABOVE the smallest normal:
        # XLA/TPU flush subnormals to zero (FTZ), so counting exact
        # subnormals would read 0.0 at precisely the moment everything
        # dies — instead we count the band where a few more halvings
        # flush.  2^8 of headroom for the 8-bit-exponent dtypes
        # (bf16/f32 — the band 2^-126..2^-118 is never visited by a
        # healthy run), 2^2 for f16's narrow 5-bit exponent (its tiny
        # is 6.1e-5; a wide band would flag healthy activations).
        margin = 4.0 if ref == jnp.float16 else 256.0
        tiny, fmax = float(fi.tiny) * margin, float(fi.max)
        a = jnp.abs(x.astype(jnp.float32))
        finite = jnp.isfinite(a)
        af = jnp.where(finite, a, 0.0)
        n += int(x.size)
        sum_sq = sum_sq + jnp.sum(af * af)
        absmax = jnp.maximum(absmax, jnp.max(af))
        nonfinite = nonfinite + jnp.sum(
            (~finite).astype(jnp.int32))
        under = under + jnp.sum(((a > 0) & (a < tiny)).astype(jnp.int32))
        over = over + jnp.sum((finite & (a > fmax)).astype(jnp.int32))
        n_finite = n_finite + jnp.sum(finite.astype(jnp.int32))
        n_nonzero = n_nonzero + jnp.sum(
            (finite & (a > 0)).astype(jnp.int32))
    # range fractions denominate over the values that CAN be in range:
    # underflow over finite NONZERO values (a mostly-zero tensor whose
    # every live value is dying must read ~1.0, not ~0.1), overflow
    # over finite values — matching the documented definitions
    return {
        "absmax": absmax,
        "rms": jnp.sqrt(sum_sq / max(n, 1)),
        "l2": jnp.sqrt(sum_sq),
        "nonfinite": nonfinite,
        "underflow_frac": (under.astype(jnp.float32)
                           / jnp.maximum(n_nonzero, 1)),
        "overflow_frac": (over.astype(jnp.float32)
                          / jnp.maximum(n_finite, 1)),
    }


def tap_tree(scope: str, tree):
    """Tap the full tensor-stat set of a pytree under ``scope`` (no-op
    when no collector is installed — and the stats are only COMPUTED
    when one is, so the unset-flag trace is untouched)."""
    if not active():
        return
    for name, value in tree_stats(tree).items():
        _tap(scope, name, value)


def tap_quant_error(scope: str, signal, error):
    """Accumulate one quantize site's exact signal/error powers under
    ``scope`` (``finalize`` turns them into ``snr_db``).  ``error`` is
    the site's own residual (x - dequantize(quantize(x))) so the
    measurement reuses the wire's arithmetic, not a model of it."""
    if not active():
        return
    import jax.numpy as jnp
    s = signal.astype(jnp.float32)
    e = error.astype(jnp.float32)
    _tap(scope, "sig_pow", jnp.sum(s * s))
    _tap(scope, "err_pow", jnp.sum(e * e))


def tap_quant_roundtrip(scope: str, x, mode: str,
                        block_size: Optional[int] = None):
    """SNR probe for call sites that cannot expose their internal
    (q, scales) pair (e.g. the custom_vjp-wrapped SP collectives, whose
    bodies trace under their own trace): re-run the exact
    quantize->dequantize roundtrip on ``x`` with the same comm/compress
    primitives and accumulate the powers.  Costs one extra quantize —
    only ever traced when the collector is active."""
    if not active():
        return
    import jax.numpy as jnp
    from hetu_tpu.comm.compress import (dequantize_blockwise,
                                        quantize_blockwise)
    from hetu_tpu.comm.wire import DEFAULT_BLOCK, mode_bits
    bs = block_size or DEFAULT_BLOCK
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % bs
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, s = quantize_blockwise(flat, bs, bits=mode_bits(mode))
    tap_quant_error(scope, flat, flat - dequantize_blockwise(q, s))


# ---------------------------------------------------------------------------
# cross-transform reductions
# ---------------------------------------------------------------------------

def _reduce(name: str, v, fold_max, fold_sum, fold_mean):
    r = rule_for(name)
    if r == "max":
        return fold_max(v)
    if r == "sum":
        return fold_sum(v)
    return fold_mean(v)


def reduce_stacked(scopes: Dict[str, Dict[str, Any]]
                   ) -> Dict[str, Dict[str, Any]]:
    """Fold a stats tree whose values are stacked along a leading axis
    (a ``lax.scan`` ys output, a vmapped per-group stats dict) down to
    per-stat scalars/vectors with each stat's own rule."""
    import jax.numpy as jnp
    return {scope: {name: _reduce(name, v,
                                  lambda x: jnp.max(x, axis=0),
                                  lambda x: jnp.sum(x, axis=0),
                                  lambda x: jnp.mean(x, axis=0))
                    for name, v in stats.items()}
            for scope, stats in scopes.items()}


def reduce_axis(scopes: Dict[str, Dict[str, Any]], axis_name: str
                ) -> Dict[str, Dict[str, Any]]:
    """Fold a stats tree across a mesh axis INSIDE a shard_map body
    (pmax/psum/pmean per rule) so the body can return replicated stats
    (out_spec ``P()``)."""
    from jax import lax
    return {scope: {name: _reduce(name, v,
                                  lambda x: lax.pmax(x, axis_name),
                                  lambda x: lax.psum(x, axis_name),
                                  lambda x: lax.pmean(x, axis_name))
                    for name, v in stats.items()}
            for scope, stats in scopes.items()}


def merge(scopes: Dict[str, Dict[str, Any]]):
    """Fold a returned stats tree back into the ambient collector's top
    frame (no-op when none is installed or the tree is empty)."""
    if not scopes or not active():
        return
    for scope, stats in scopes.items():
        for name, v in stats.items():
            _tap(scope, name, v)


# ---------------------------------------------------------------------------
# host side: the one sink and the one reader
# ---------------------------------------------------------------------------

def _jsonable_scopes(scopes) -> Dict[str, Dict[str, Any]]:
    import numpy as np
    out: Dict[str, Dict[str, Any]] = {}
    for scope, stats in scopes.items():
        dst = out.setdefault(str(scope), {})
        for name, v in stats.items():
            a = np.asarray(v)
            dst[str(name)] = (a.tolist() if a.ndim else float(a))
    return out


def record(scopes, *, step: int, registry=None,
           runlog=None) -> Optional[Dict[str, Any]]:
    """THE host-side sink for one step's (already device_get) stats:
    schema-versioned ``numerics`` RunLog record + labeled registry
    gauges/histograms.  Cluster visibility rides the gauges through the
    existing telemetry push — deliberately NOT the event push
    (``numerics`` is excluded from aggregate.EVENT_KINDS: per-step
    records verbatim would multiply wire cost for data the coordinator
    already has as series).  Returns the written record (or None when
    there was nothing)."""
    if not scopes:
        return None
    if registry is None:
        from hetu_tpu.obs.metrics import get_registry
        registry = get_registry()
    scopes = _jsonable_scopes(scopes)
    for scope, stats in scopes.items():
        for name, v in stats.items():
            if isinstance(v, list):
                for i, vi in enumerate(v):
                    registry.set_gauge(f"numerics.{name}", vi,
                                       scope=scope, index=str(i))
                continue
            registry.set_gauge(f"numerics.{name}", v, scope=scope)
            if name == "snr_db":
                registry.observe("numerics.snr_db_hist", v, scope=scope)
    moe = scopes.get("moe")
    if moe:
        # the live expert-load surface ROADMAP item 1 names.  NB: with
        # HETU_TPU_NUMERICS_EVERY > 1 this counter accumulates only the
        # SAMPLED steps' drops (the unsampled stats are never fetched);
        # at the default interval of 1 it is exact
        if moe.get("dropped"):
            registry.inc("moe.capacity_dropped", float(moe["dropped"]))
        for i, vi in enumerate(moe.get("load") or []):
            registry.set_gauge("moe.expert_load", vi, expert=str(i))
        if moe.get("entropy") is not None:
            registry.set_gauge("moe.router_entropy", moe["entropy"])
    registry.inc("numerics.records")
    rec = {"kind": "numerics", "numerics_schema": NUMERICS_SCHEMA,
           "step": step, "scopes": scopes}
    if runlog is not None:
        written = runlog.log("numerics", numerics_schema=NUMERICS_SCHEMA,
                             step=step, scopes=scopes)
        rec = written or rec
    return rec


def summarize_numerics(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """THE reader over ``numerics`` RunLog records — shared by
    tools_numerics.py and tools_obs_report.py (no second parser).

    Returns ``{"records", "steps": [first, last], "scopes": {scope:
    {"last": {...}, "min_snr_db", "max_underflow_frac", "nonfinite",
    "taps"}}, "worst": [scope, ...]}`` with ``worst`` ranked most
    alarming first (lowest SNR, then highest underflow fraction)."""
    recs = [r for r in records if r.get("kind") == "numerics"]
    scopes: Dict[str, Dict[str, Any]] = {}
    steps: List[int] = []
    for r in recs:
        if r.get("step") is not None:
            steps.append(int(r["step"]))
        for scope, stats in (r.get("scopes") or {}).items():
            agg = scopes.setdefault(scope, {
                "last": {}, "min_snr_db": None,
                "max_underflow_frac": None, "nonfinite": 0, "taps": 0})
            agg["last"] = stats
            agg["taps"] += 1
            snr = stats.get("snr_db")
            if snr is not None and (agg["min_snr_db"] is None
                                    or snr < agg["min_snr_db"]):
                agg["min_snr_db"] = snr
            uf = stats.get("underflow_frac")
            if uf is not None and (agg["max_underflow_frac"] is None
                                   or uf > agg["max_underflow_frac"]):
                agg["max_underflow_frac"] = uf
            nf = stats.get("nonfinite")
            if nf:
                agg["nonfinite"] += int(nf)

    def badness(item):
        name, agg = item
        snr = agg["min_snr_db"]
        uf = agg["max_underflow_frac"] or 0.0
        return (-agg["nonfinite"],
                snr if snr is not None else math.inf,
                -uf, name)

    worst = [name for name, _ in sorted(scopes.items(), key=badness)]
    return {"records": len(recs),
            "steps": [min(steps), max(steps)] if steps else None,
            "scopes": scopes, "worst": worst}
