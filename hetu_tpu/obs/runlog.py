"""Structured run-event log.

Every training run leaves a machine-readable trace: one JSONL record per
step / compile / switch / elastic epoch, written next to the checkpoints
(reference: the profiler cost records persisted per run — hetu/impl/
profiler/; here the schema is stable and versioned so BENCH tooling and
tools_obs_report.py can read logs across repo revisions).

Record shape (all kinds):

    {"schema": 1, "kind": "step", "t": <unix wall time>, ...kind fields}

Kind fields:
    step          step, step_time_s, loss, tokens_per_s, device_mem_bytes,
                  plan (fingerprint of the dispatched plan)
    compile       name, plan, compile_s, flops, estimated_mfu
    switch        from_id, to_id, wall_s, moved_bytes, total_bytes
    elastic_epoch epoch, alive, strategy
    fault         fault (ckpt_corrupt | step_exception |
                  restore_unrecoverable), generation, detail/error —
                  observed-fault accounting (docs/fault_tolerance.md)
    anomaly       anomaly (obs.health.HealthMonitor.KINDS), step, value,
                  baseline — online health-detector firings
    straggler     stragglers (flagged ranks), workers (per-rank
                  ratio/z) — the cluster straggler report transitions
    serve         event (admit | done | preempt | reshard | report |
                  failover | retry | evict | expired | shed | ship |
                  degraded | replica | hedge | hedge_win | hedge_dupe |
                  dispatch) + the serving SLO fields (hetu_tpu/serving,
                  docs/serving.md); every event also stamps `now`
                  (driver-clock seconds — the engine's virtual clock,
                  matching span t0/t1) and `clock` (the timestamp
                  basis, driver | wall — `FleetTrace.stitch` refuses
                  to mix bases); per-request events (admit/done/
                  preempt/retry/evict/expired/shed) carry `tenant` and,
                  on a sampled RunLog
                  (HETU_TPU_RUNLOG_SERVE_SAMPLE > 1), `sample_weight`
                  (how many requests the sampled record stands for —
                  slo_report re-weights by it):
                  admit: req, slot, prompt_len, chunks, ttft_s,
                  queue_wait_s, slo_class, tenant, shared_tokens (prompt
                  tokens resident via the radix prefix cache — 0 on a
                  miss), queue_depth, page_util;
                  done: req, reason, tokens, ttft_s, e2e_s, tokens_per_s,
                  slo_class, tenant, slo_ttft_s, slo_token_gap_s,
                  spec_proposed/spec_accepted (speculative-decoding
                  draft counts), shared_prefix_tokens, prompt_len,
                  preemptions, queue_depth, slot_occupancy, page_util,
                  + the cost-ledger fields when the run priced requests
                  (serving/costs.py COST_FIELDS: cost_prefill_flops,
                  cost_decode_flops, cost_page_s, cost_kv_byte_s,
                  cost_wire_bytes);
                  preempt: req, slot, by (the preemptor rid), by_class,
                  slo_class (the victim's), tenant, tokens_discarded,
                  queue_depth — one per HETU_TPU_SERVE_PREEMPT
                  evict-and-requeue;
                  reshard: tier, strategy, pause_s (+ kv_repage=true
                  when HETU_TPU_SERVE_KV_REPAGE migrated the pool);
                  report: requests, tokens, elapsed_s, tokens_per_s;
                  failover: requeued, exhausted, queue_depth — one per
                  engine fail_over (chaos engine_kill);
                  retry: req, slot, attempt, tokens_discarded — a
                  request requeued under HETU_TPU_SERVE_RETRY
                  (stall reason replica_lost); disaggregated
                  re-prefills stamp ship=true (the shipment was lost/
                  timed out, stall reason shipment_wait);
                  evict/expired/shed: req, reason (retry_exhausted |
                  deadline_exceeded | brownout_shed), tokens, e2e_s,
                  retries, preemptions, queue_depth (+ the cost fields
                  for live casualties) — fault terminations
                  (HETU_TPU_SERVE_RETRY / _DEADLINE / _BROWNOUT);
                  ship: req, seq, attempt, resend, quant — one per KV
                  shipment sent on the prefill->decode wire
                  (HETU_TPU_SERVE_DISAGG, serving/disagg.py);
                  degraded: state (enter | exit), queue_depth on enter,
                  degraded_s on exit — the colocated-fallback window
                  while the prefill tier is down;
                  replica: replica, state (drain | rejoin | down) —
                  frontend replica health transitions
                  (serving/frontend.py);
                  hedge: req, primary, hedge, waited_steps — a hedged
                  re-dispatch fired (HETU_TPU_SERVE_HEDGE);
                  hedge_win: req, primary, hedge, tokens — the hedge
                  copy finished first (the primary's duplicate stream
                  is withdrawn and its tokens discarded);
                  hedge_dupe: req, replica, tokens — a hedge LOSER ran
                  to completion before withdrawal (the stitcher
                  discounts its duplicate terminal);
                  dispatch: req, tier (prefill | decode), replica,
                  attempt, fallback/rerouted_from when applicable — a
                  frontend/coordinator routing decision, the stitched
                  DAG's dispatch edge (obs/spans.py FleetTrace)
    span          the serving flight recorder (HETU_TPU_SERVE_TRACE,
                  hetu_tpu/serving/tracing.py, schema owned by
                  obs/spans.py): span_schema (version), span (queued |
                  prefill | decode | reshard_pause | done | evicted |
                  deadline_exceeded | hedge_withdrawn), trace (trace
                  id), req, slot, slo_class, t0, t1, clock (timestamp
                  basis: driver | wall — every span record stamps it;
                  stitch refuses mixed bases), tier (prefill | decode,
                  only when stamped) and replica (engine index, only
                  when stamped) — the hop identity fleet stitching
                  keys on
                  (driver-clock seconds; spans of one request tile
                  [arrival, done] — durations sum to its e2e_s;
                  requeued attempts stamp attempt >= 2), plus
                  per-kind attrs: queued carries reason
                  (none|no_slot|no_pages|preempted|quota_exceeded|
                  replica_lost|brownout_shed|prefill_tier_down|
                  shipment_wait — the scheduler's
                  reserve-on-admit stall attribution,
                  obs/spans.py STALL_REASONS), prefill carries
                  chunk (+ last on the TTFT chunk), decode carries
                  tokens/segment/end, reshard_pause carries tier, the
                  zero-duration terminals carry reason/tokens/e2e_s
    profile       name, plan, profile_schema, top (top-k layers/op-groups
                  by predicted roofline time), estimated_step_s,
                  total_flops, total_wire_bytes, peak_hbm_bytes,
                  peak_hbm_vs_xla, hbm_headroom_frac — the per-compile
                  analytic step profile (obs.hlo_profile,
                  HETU_TPU_PROFILE=1)
    budget        name, ok, breaches, budget — declared-perf-budget
                  check per fresh compile (obs.budget,
                  HETU_TPU_BUDGETS)
    lint          name, plan, findings, errors, warnings, lints (per-lint
                  counts), messages (first error/warning lines) — the
                  per-compile graph-contract lint record
                  (hetu_tpu/analysis, HETU_TPU_LINT=1,
                  docs/static_analysis.md)
    numerics      numerics_schema (version), step, scopes — the numerics
                  observatory's per-step stats pytree (obs/numerics.py,
                  HETU_TPU_NUMERICS): {scope: {stat: value}} with
                  absmax/rms/l2/nonfinite/underflow_frac/overflow_frac
                  per tensor scope (params, grads, update, adam_m,
                  embed, hidden, logits, ef), snr_db (+ sig_pow/err_pow)
                  per compressed path (grad_sync/a2a, grad_sync/ag,
                  grad_sync/two_level, zero_refresh, sp/<op>, kv_pages)
                  and the moe scope's load/load_max/entropy/dropped/
                  drop_frac; one record per HETU_TPU_NUMERICS_EVERY
                  steps
    scaler        event (growth | backoff), scale, prev, step — one
                  record per dynamic-loss-scale transition (AMP runs;
                  optim/grad_scaler.classify_transition); the per-step
                  value lives in the scaler.loss_scale gauge
    rotated       segment, records — the size-cap rotation marker (the
                  last record of a rotated segment)
    summary       metrics (a MetricsRegistry snapshot), profiler summary

The writer is append-only and flushes per record by default: a preempted
TPU worker's log is valid up to its last completed step.

Long runs can size-cap the log: with ``HETU_TPU_RUNLOG_MAX_MB`` set (or
``max_bytes`` passed), a segment that overflows the cap is closed with a
``rotated`` marker record and renamed to ``<path>.<n>`` (n increasing —
``<path>.1`` is the OLDEST segment), and a fresh segment opens at
``path``.  ``iter_records``/``read`` follow the whole chain in
chronological order, so downstream tooling (tools_obs_report,
trace_from_runlog) never notices the rotation.

An optional in-memory tail buffer (``tail_records``) keeps the last N
records for the cluster telemetry push (obs.aggregate drains it with
``drain_tail()``); it works even after a disk-write failure disabled the
file writer — telemetry keeps flowing when the disk does not.
"""
from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1

#: field names every record carries — the stability contract tested by
#: tests/test_obs.py (extend with new OPTIONAL fields; never rename these)
REQUIRED_FIELDS = ("schema", "kind", "t")


class RunLog:
    """Append-only JSONL run-event writer."""

    def __init__(self, path: str, flush_every: int = 1,
                 max_bytes: Optional[int] = None, tail_records: int = 0):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self._flush_every = max(1, flush_every)
        self._since_flush = 0
        self.records_written = 0
        if max_bytes is None:
            from hetu_tpu.utils import flags
            mb = flags.int_flag("HETU_TPU_RUNLOG_MAX_MB")
            max_bytes = mb * (1 << 20) if mb > 0 else None
        self._max_bytes = max_bytes
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self.rotations = 0
        self._tail = (collections.deque(maxlen=tail_records)
                      if tail_records > 0 else None)

    # ------------------------------------------------------------------
    def log(self, kind: str, **fields) -> Dict[str, Any]:
        rec = {"schema": SCHEMA_VERSION, "kind": kind, "t": time.time()}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            if self._tail is not None:
                # the telemetry tail rides even when the file writer is
                # disabled/closed — cluster visibility outlives the disk
                self._tail.append(json.loads(line))
            if self._f.closed:
                return rec   # post-close stragglers (daemon threads) drop
            try:
                self._f.write(line + "\n")
                self._bytes += len(line) + 1
                self._since_flush += 1
                self.records_written += 1
                if self._since_flush >= self._flush_every:
                    self._f.flush()
                    self._since_flush = 0
                if self._max_bytes and self._bytes >= self._max_bytes:
                    self._rotate_locked()
            except OSError as e:
                # telemetry must not kill a step: a full disk / dead mount
                # under the runlog disables the writer (warn once) while
                # the training loop — and its checkpoints, possibly on a
                # different path — carry on
                try:
                    self._f.close()
                except OSError:
                    pass
                from hetu_tpu.utils.logging import get_logger
                get_logger("obs.runlog").warning(
                    f"run log write to {self.path} failed ({e!r}); "
                    "disabling run-event logging for this run")
        return rec

    def step(self, step: int, step_time_s: float, *,
             loss: Optional[float] = None,
             tokens_per_s: Optional[float] = None,
             device_mem_bytes: Optional[int] = None,
             plan: Optional[str] = None, **extra) -> Dict[str, Any]:
        return self.log("step", step=step, step_time_s=step_time_s,
                        loss=loss, tokens_per_s=tokens_per_s,
                        device_mem_bytes=device_mem_bytes, plan=plan,
                        **extra)

    def _rotate_locked(self):
        """Close the overflowing segment (ending it with a `rotated`
        marker so readers can SEE the cut), rename it to the next
        `<path>.<n>`, and start a fresh segment at `path`.  A rename
        failure (exotic filesystems) disables rotation rather than the
        log."""
        idx = _max_segment_index(self.path) + 1
        marker = {"schema": SCHEMA_VERSION, "kind": "rotated",
                  "t": time.time(), "segment": idx,
                  "records": self.records_written}
        try:
            self._f.write(json.dumps(marker) + "\n")
            self._f.flush()
            self._f.close()
            os.replace(self.path, f"{self.path}.{idx}")
            self._f = open(self.path, "a")
            self._bytes = 0
            self._since_flush = 0
            self.rotations += 1
        except OSError as e:
            from hetu_tpu.utils.logging import get_logger
            get_logger("obs.runlog").warning(
                f"run log rotation of {self.path} failed ({e!r}); "
                "disabling rotation for this run")
            self._max_bytes = None
            if self._f.closed:
                # reopen append on whichever file survived the failure
                self._f = open(self.path, "a")

    def drain_tail(self) -> List[Dict[str, Any]]:
        """Return-and-clear the in-memory tail (the telemetry push feed);
        [] when the tail buffer is disabled."""
        with self._lock:
            if not self._tail:
                return []
            out = list(self._tail)
            self._tail.clear()
            return out

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------
    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        return list(RunLog.iter_records(path))

    @staticmethod
    def segments(path: str) -> List[str]:
        """All on-disk segments of a (possibly rotated) run log, oldest
        first: `<path>.1`, `<path>.2`, ..., then `path` itself."""
        out = [f"{path}.{n}" for n in _segment_indices(path)]
        if os.path.exists(path) or not out:
            out.append(path)
        return out

    @staticmethod
    def iter_records(path: str) -> Iterator[Dict[str, Any]]:
        """Yields records across ALL rotated segments in chronological
        order, skipping torn trailing lines (a preempted writer's final
        partial write must not poison the whole log)."""
        for seg in RunLog.segments(path):
            with open(seg) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("kind"):
                        yield rec


def _segment_indices(path: str) -> List[int]:
    """Sorted rotation indices n for which `<path>.<n>` exists."""
    d, base = os.path.split(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    out = []
    try:
        for name in os.listdir(d or "."):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
    except OSError:
        pass
    return sorted(out)


def _max_segment_index(path: str) -> int:
    idx = _segment_indices(path)
    return idx[-1] if idx else 0


def _jsonable(obj):
    """Fallback encoder: numpy / jax scalars -> python numbers."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                pass
    return str(obj)


def default_runlog_path(ckpt_dir: Optional[str]) -> Optional[str]:
    """Resolve where a trainer's run log goes: the HETU_TPU_RUNLOG flag
    wins; else next to the checkpoints; else no log."""
    from hetu_tpu.utils import flags
    explicit = flags.str_flag("HETU_TPU_RUNLOG")
    if explicit:
        return explicit
    if ckpt_dir:
        # keep local-path semantics only — remote URIs (gs://) are the
        # checkpointer's business, not a line-buffered JSONL writer's
        if "://" not in ckpt_dir:
            return os.path.join(ckpt_dir, "runlog.jsonl")
    return None
