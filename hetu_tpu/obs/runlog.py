"""Structured run-event log.

Every training run leaves a machine-readable trace: one JSONL record per
step / compile / switch / elastic epoch, written next to the checkpoints
(reference: the profiler cost records persisted per run — hetu/impl/
profiler/; here the schema is stable and versioned so BENCH tooling and
tools_obs_report.py can read logs across repo revisions).

Record shape (all kinds):

    {"schema": 1, "kind": "step", "t": <unix wall time>, ...kind fields}

Kind fields:
    step          step, step_time_s, loss, tokens_per_s, device_mem_bytes,
                  plan (fingerprint of the dispatched plan)
    compile       name, plan, compile_s, flops, estimated_mfu
    switch        from_id, to_id, wall_s, moved_bytes, total_bytes
    elastic_epoch epoch, alive, strategy
    fault         fault (ckpt_corrupt | step_exception |
                  restore_unrecoverable), generation, detail/error —
                  observed-fault accounting (docs/fault_tolerance.md)
    summary       metrics (a MetricsRegistry snapshot), profiler summary

The writer is append-only and flushes per record by default: a preempted
TPU worker's log is valid up to its last completed step.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1

#: field names every record carries — the stability contract tested by
#: tests/test_obs.py (extend with new OPTIONAL fields; never rename these)
REQUIRED_FIELDS = ("schema", "kind", "t")


class RunLog:
    """Append-only JSONL run-event writer."""

    def __init__(self, path: str, flush_every: int = 1):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self._flush_every = max(1, flush_every)
        self._since_flush = 0
        self.records_written = 0

    # ------------------------------------------------------------------
    def log(self, kind: str, **fields) -> Dict[str, Any]:
        rec = {"schema": SCHEMA_VERSION, "kind": kind, "t": time.time()}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            if self._f.closed:
                return rec   # post-close stragglers (daemon threads) drop
            try:
                self._f.write(line + "\n")
                self._since_flush += 1
                self.records_written += 1
                if self._since_flush >= self._flush_every:
                    self._f.flush()
                    self._since_flush = 0
            except OSError as e:
                # telemetry must not kill a step: a full disk / dead mount
                # under the runlog disables the writer (warn once) while
                # the training loop — and its checkpoints, possibly on a
                # different path — carry on
                try:
                    self._f.close()
                except OSError:
                    pass
                from hetu_tpu.utils.logging import get_logger
                get_logger("obs.runlog").warning(
                    f"run log write to {self.path} failed ({e!r}); "
                    "disabling run-event logging for this run")
        return rec

    def step(self, step: int, step_time_s: float, *,
             loss: Optional[float] = None,
             tokens_per_s: Optional[float] = None,
             device_mem_bytes: Optional[int] = None,
             plan: Optional[str] = None, **extra) -> Dict[str, Any]:
        return self.log("step", step=step, step_time_s=step_time_s,
                        loss=loss, tokens_per_s=tokens_per_s,
                        device_mem_bytes=device_mem_bytes, plan=plan,
                        **extra)

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------
    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        return list(RunLog.iter_records(path))

    @staticmethod
    def iter_records(path: str) -> Iterator[Dict[str, Any]]:
        """Yields records, skipping torn trailing lines (a preempted
        writer's final partial write must not poison the whole log)."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind"):
                    yield rec


def _jsonable(obj):
    """Fallback encoder: numpy / jax scalars -> python numbers."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                pass
    return str(obj)


def default_runlog_path(ckpt_dir: Optional[str]) -> Optional[str]:
    """Resolve where a trainer's run log goes: the HETU_TPU_RUNLOG flag
    wins; else next to the checkpoints; else no log."""
    from hetu_tpu.utils import flags
    explicit = flags.str_flag("HETU_TPU_RUNLOG")
    if explicit:
        return explicit
    if ckpt_dir:
        # keep local-path semantics only — remote URIs (gs://) are the
        # checkpointer's business, not a line-buffered JSONL writer's
        if "://" not in ckpt_dir:
            return os.path.join(ckpt_dir, "runlog.jsonl")
    return None
