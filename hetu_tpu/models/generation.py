"""Autoregressive generation with a KV cache.

Rebuild of the reference's model-generation surface (reference:
python/hetu/models/utils/model_utils.py PreTrainedModel generate path; the
reference is training-first and so are we — this is the functional decode
loop for eval/demo, TPU-shaped: static max length, lax.scan decode, cache as
a pytree carried through the scan).

Works with both model families' stacked-scan parameter layouts: the
per-layer KV caches are stacked [L, b, max_len, n_kv, hd] and the decode
step scans layers with the cache rows as per-layer xs/ys.  prefill and
decode_step dispatch on the family (LLaMA: RMSNorm/rotary/fused-GQA QKV;
GPT: LayerNorm/wpe/biased fused QKV).

Serving-facing surface (hetu_tpu/serving, docs/serving.md): the decode
step also comes in a slot-masked form — `decode_step_slots` takes a
PER-SLOT position vector (each batch row is an independent sequence at
its own depth) and returns this step's per-layer K/V so a paged cache
can scatter them into its pool — and `extend_cache` is the multi-token
(chunked-prefill) sibling that advances a cache by a whole token block.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from hetu_tpu import ops


def _attend_cached(q, ck, cv, pos, scale):
    """q: [b, 1, nq, hd]; ck/cv: [b, M, n_kv, hd]; attend over
    cache[:pos+1] (pos scalar, or [b] for per-slot depths).

    GQA attends in the GROUPED layout — q reshaped [b, C, n_kv, g, hd]
    and contracted against the cache's n_kv heads directly — instead of
    materializing a group-repeated copy of the whole cache every step
    (the old jnp.repeat path copied M*n_kv*hd*(g-1) elements per layer
    per token).  Head ordering matches the fused-QKV layout (q head
    j = kv head j // g): the q·k scores are bit-identical to the repeat
    path and the p·v output matches to float32-ulp (the weighted sum
    over the cache axis reassociates without the materialized copy) —
    regression-tested in tests/test_generation.py.

    This is exactly the single-query case of `_attend_cached_chunk`
    (one query at offset 0 from `pos`) — ONE implementation of the
    grouped contraction + causal mask, so decode and chunked prefill
    can never drift numerically."""
    return _attend_cached_chunk(q, ck, cv, pos, scale)


def _attend_cached_chunk(q, ck, cv, start, scale):
    """Multi-query cached attention for chunked prefill.  q: [b, C, nq,
    hd] sits at absolute positions start..start+C-1 (start scalar or
    [b]); key position k is visible to query i iff k <= start + i
    (causal within the chunk, full visibility of the already-cached
    prefix).  Same grouped-GQA contraction as `_attend_cached`."""
    b, M, n_kv, hd = ck.shape
    C, nq = q.shape[1], q.shape[2]
    group = nq // n_kv
    qg = q.reshape(b, C, n_kv, group, hd)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale
    start = jnp.asarray(start)
    if start.ndim == 0:
        start = start[None]
    qpos = start[:, None] + jnp.arange(C)[None, :]            # [b, C]
    mask = jnp.arange(M)[None, None, :] <= qpos[..., None]    # [b, C, M]
    s = jnp.where(mask[:, None, None, :, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv.astype(jnp.float32))
    return out.reshape(b, C, nq, hd).astype(q.dtype)


def _is_gpt(model) -> bool:
    return hasattr(model.model, "wte")


def lm_head_weight(model, params):
    """The lm_head slice as a [hidden, vocab] matrix — the weight
    operand of the fused sampling epilogue (serving/sampling.
    sample_hidden).  Matches `model.logits`: tied embeddings transpose
    the token-embedding table, untied models carry an explicit head."""
    if model.config.tie_word_embeddings:
        key = "wte" if _is_gpt(model) else "embed"
        return params["model"][key]["weight"].T
    return params["lm_head"]


def _check_context_length(config, max_len: int):
    """Past the trained context, GPT's jnp.take on wpe (and LLaMA's RoPE
    table lookup) would silently clamp to the last position — fail loudly
    instead.  One guard shared by every cache-building entry point."""
    if max_len > config.max_position_embeddings:
        raise ValueError(
            f"cache length {max_len} exceeds max_position_embeddings "
            f"{config.max_position_embeddings}")


def init_cache(model, batch: int, max_len: int):
    """Empty KV cache [L, b, max_len, n_kv, hd] (n_kv = heads for GPT)."""
    c = model.config
    _check_context_length(c, max_len)
    n_kv = getattr(c, "num_key_value_heads", c.num_attention_heads)
    shape = (c.num_hidden_layers, batch, max_len, n_kv, c.head_dim)
    return (jnp.zeros(shape, c.compute_dtype), jnp.zeros(shape, c.compute_dtype))


def _gpt_embed(model, mp, ids, pos_ids):
    x = model.model.wte(mp["wte"], ids) \
        + jnp.take(mp["wpe"], pos_ids, axis=0)
    return x.astype(model.config.compute_dtype)


def _prefill_gpt(model, params, input_ids, max_len: int):
    mp = params["model"]
    pos = jnp.arange(input_ids.shape[1], dtype=jnp.int32)
    x = _gpt_embed(model, mp, input_ids, pos)
    block = model.model.block

    def body(h, lp):
        out = block(lp, h)
        hn = block.ln1(lp["ln1"], h)
        # contract only the K/V planes for the cache (the block forward
        # above already computed full QKV for its own attention)
        kv = jnp.einsum("bsh,hngd->bsngd", hn,
                        lp["attn"]["wqkv"][:, :, 1:3, :].astype(h.dtype)) \
            + lp["attn"]["bqkv"][:, 1:3, :].astype(h.dtype)
        return out, (kv[..., 0, :], kv[..., 1, :])

    x, (ks, vs) = lax.scan(body, x, mp["blocks"])
    hidden = model.model.final_ln(mp["final_ln"], x)
    logits = model.logits(params, hidden)[:, -1, :]
    pad = max_len - input_ids.shape[1]
    cache_k = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    cache_v = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    return logits, (cache_k, cache_v)


def _cache_write_token(ck, k, positions, uniform: bool):
    """Write one token's K (or V) [b, 1, n_kv, hd] into a cache row
    [b, M, n_kv, hd] at `positions`.  Uniform (scalar) positions keep
    the old contiguous dynamic_update_slice lowering — the generate()
    hot loop must not pay batched-scatter cost for a broadcast index —
    per-slot vectors scatter per row (the serving form)."""
    if uniform:
        return lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                        (0, positions, 0, 0))
    b = ck.shape[0]
    return ck.at[jnp.arange(b), positions].set(k[:, 0].astype(ck.dtype))


def _decode_step_slots_gpt(model, params, tokens, cache, positions):
    c = model.config
    mp = params["model"]
    b = tokens.shape[0]
    uniform = jnp.ndim(positions) == 0
    pos_ids = (jnp.broadcast_to(positions, (1,)) if uniform
               else positions[:, None])
    x = _gpt_embed(model, mp, tokens[:, None], pos_ids)
    block = model.model.block
    att = block.attn
    nh, hd = c.num_attention_heads, c.head_dim
    scale = hd ** -0.5
    cache_k, cache_v = cache

    def body(h, xs):
        lp, ck, cv = xs
        hn = block.ln1(lp["ln1"], h)
        qkv = jnp.einsum("bsh,hngd->bsngd", hn,
                         lp["attn"]["wqkv"].astype(h.dtype)) \
            + lp["attn"]["bqkv"].astype(h.dtype)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        kt, vt = k[:, 0], v[:, 0]                       # [b, n_kv, hd]
        ck = _cache_write_token(ck, k, positions, uniform)
        cv = _cache_write_token(cv, v, positions, uniform)
        attn = _attend_cached(q, ck, cv, positions, scale)
        h = h + att.o_proj(lp["attn"]["o_proj"],
                           attn.reshape(b, 1, nh * hd))
        h = h + block.mlp(lp["mlp"], block.ln2(lp["ln2"], h))
        return h, (ck, cv, kt, vt)

    x, (new_k, new_v, k_toks, v_toks) = lax.scan(
        body, x, (mp["blocks"], cache_k, cache_v))
    hidden = model.model.final_ln(mp["final_ln"], x)
    logits = model.logits(params, hidden)[:, 0, :]
    return logits, (new_k, new_v), (k_toks, v_toks)


def prefill(model, params, input_ids, max_len: int):
    """Run the full forward over the prompt, returning (last_logits, cache).
    Uses the model's training forward (flash path) plus a kv-extraction pass.
    """
    c = model.config
    if not c.use_scan:
        raise ValueError("generation requires use_scan=True (stacked layer "
                         "params); rebuild the model with use_scan=True")
    _check_context_length(c, max_len)
    if _is_gpt(model):
        return _prefill_gpt(model, params, input_ids, max_len)
    b, plen = input_ids.shape
    # extract per-layer k/v by re-running the projections layer by layer —
    # one pass via the scan collecting (k, v) as ys
    mp = params["model"]
    x = model.model.embed(mp["embed"], input_ids).astype(c.compute_dtype)
    cos, sin = ops.build_rope_cache(c.max_position_embeddings, c.head_dim,
                                    c.rope_theta)
    block = model.model.layers.block

    att = block.attn

    def body(carry, layer_params):
        h = carry
        out, _aux = block(layer_params, h, cos=cos, sin=sin)
        # recompute only the K/V planes of the fused projection for the cache
        # (the q-head planes are sliced out of the weight before the einsum)
        w_kv = layer_params["attn"]["wqkv"][:, :, att.group: att.group + 2, :]
        kv = jnp.einsum("bsh,hkgd->bskgd",
                        block.input_norm(layer_params["input_norm"], h),
                        w_kv.astype(h.dtype))
        k = ops.apply_rotary(kv[..., 0, :], cos, sin, None)
        v = kv[..., 1, :]
        return out, (k, v)

    x, (ks, vs) = lax.scan(body, x, mp["layers"]["layers"])
    hidden = model.model.final_norm(mp["final_norm"], x)
    logits = model.logits(params, hidden)[:, -1, :]
    pad = max_len - plen
    cache_k = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    cache_v = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    return logits, (cache_k, cache_v)


def decode_step_slots(model, params, tokens, cache, positions):
    """One token step with PER-SLOT positions (the serving engine's form:
    each batch row is an independent sequence at its own depth).

    tokens: [b] int32; positions: [b] int32 (this token's absolute
    position per slot) — or a scalar, which keeps the old contiguous
    dynamic_update_slice cache lowering for the uniform-position
    generate() hot loop.  Returns (logits [b, vocab], new_cache,
    (k_toks, v_toks)) where k_toks/v_toks are THIS step's per-layer K/V
    [L, b, n_kv, hd] — a paged cache scatters them into its pool instead
    of carrying the dense cache."""
    c = model.config
    if not c.use_scan:
        raise ValueError("generation requires use_scan=True (stacked layer "
                         "params)")
    if _is_gpt(model):
        return _decode_step_slots_gpt(model, params, tokens, cache, positions)
    mp = params["model"]
    b = tokens.shape[0]
    uniform = jnp.ndim(positions) == 0
    x = model.model.embed(mp["embed"], tokens[:, None]).astype(c.compute_dtype)
    cos, sin = ops.build_rope_cache(c.max_position_embeddings, c.head_dim,
                                    c.rope_theta)
    block = model.model.layers.block
    att = block.attn
    scale = c.head_dim ** -0.5
    pos_ids = (jnp.broadcast_to(positions, (b, 1)) if uniform
               else positions[:, None])
    cache_k, cache_v = cache

    def body(carry, xs):
        h = carry
        layer_params, ck, cv = xs
        hn = block.input_norm(layer_params["input_norm"], h)
        qkv = jnp.einsum("bsh,hkgd->bskgd", hn,
                         layer_params["attn"]["wqkv"].astype(h.dtype))
        q = qkv[..., : att.group, :].reshape(b, 1, att.n_q, c.head_dim)
        k = qkv[..., att.group, :]
        v = qkv[..., att.group + 1, :]
        q, k = ops.apply_rotary_qk(q, k, cos, sin, pos_ids)
        kt, vt = k[:, 0], v[:, 0]                       # [b, n_kv, hd]
        ck = _cache_write_token(ck, k, positions, uniform)
        cv = _cache_write_token(cv, v, positions, uniform)
        attn = _attend_cached(q, ck, cv, positions, scale)
        h = h + att.o_proj(layer_params["attn"]["o_proj"],
                           attn.reshape(b, 1, att.n_q * c.head_dim))
        mlp_out = block.mlp(layer_params["mlp"],
                            block.post_norm(layer_params["post_norm"], h))
        if isinstance(mlp_out, tuple):  # MoE
            mlp_out = mlp_out[0]
        h = h + mlp_out
        return h, (ck, cv, kt, vt)

    x, (new_k, new_v, k_toks, v_toks) = lax.scan(
        body, x, (mp["layers"]["layers"], cache_k, cache_v))
    hidden = model.model.final_norm(mp["final_norm"], x)
    logits = model.logits(params, hidden)[:, 0, :]
    return logits, (new_k, new_v), (k_toks, v_toks)


def decode_step(model, params, token, cache, pos):
    """One token step. token: [b] int32; pos: scalar current position.
    Returns (logits [b, vocab], new_cache).  Delegates to the slot-masked
    form; the scalar position keeps the contiguous cache-update
    lowering."""
    logits, new_cache, _ = decode_step_slots(
        model, params, token, cache, jnp.asarray(pos, jnp.int32))
    return logits, new_cache


def _paged_write(pool, table, positions, t):
    """Scatter one token's K (or V) [S, n_kv, hd] into ONE layer's page
    array [P, ps, n_kv, hd] at each slot's (table[pos // ps], pos % ps).
    Inactive slots' tables point at the null page (id 0) — their write
    lands there harmlessly (serving/kv_pool.py)."""
    ps = pool.shape[1]
    S = positions.shape[0]
    page = table[jnp.arange(S), positions // ps]
    return pool.at[page, positions % ps].set(t.astype(pool.dtype))


def _quantize_head_vectors(t, bits: int):
    """Quantize [..., hd] head-vectors for a paged pool: int8 through
    the SAME blockwise primitives the gather path uses (comm/compress ->
    the fused Pallas quant kernel when routed), int4 through the shared
    `ops/quantization` nibble packer — so pool contents are
    bit-identical across the decode programs.  Returns (payload
    [..., hd or hd//2], scales [...])."""
    hd = t.shape[-1]
    x32 = t.astype(jnp.float32)
    if bits == 4:
        from hetu_tpu.ops.quantization import quantize_int4
        q, s = quantize_int4(x32, block_size=hd)
        q = q.reshape(t.shape[:-1] + (hd // 2,))
    else:
        from hetu_tpu.comm.compress import quantize_blockwise
        q, s = quantize_blockwise(x32, block_size=hd)
        q = q.reshape(t.shape)
    return q, s.reshape(t.shape[:-1])


def _paged_write_q(pool, scale, table, positions, t, *, bits: int = 8):
    """The quantized-page form of `_paged_write` (int8, or int4 nibble
    payloads with ``bits=4``): write payload + per-head-vector f32
    scale."""
    ps = pool.shape[1]
    S = positions.shape[0]
    q, s = _quantize_head_vectors(t, bits)
    page = table[jnp.arange(S), positions // ps]
    off = positions % ps
    return pool.at[page, off].set(q.astype(pool.dtype)), \
        scale.at[page, off].set(s)


def _token_block_pages(table, positions, C, ps):
    """Page ids + offsets for a C-token block at positions[s] + i.
    Block positions past the table's reach land in the null page (id 0)
    — the same redirect `serving/kv_pool.write_tokens` applies — and
    inactive slots' zeroed table rows point there already."""
    S = positions.shape[0]
    mp = table.shape[1]
    pos = positions[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    pidx = pos // ps
    safe = pidx < mp
    page = jnp.where(
        safe, table[jnp.arange(S)[:, None], jnp.clip(pidx, 0, mp - 1)], 0)
    return page, pos % ps


def _paged_write_tokens(pool, table, positions, t):
    """Scatter a C-token block's K (or V) [S, C, n_kv, hd] into ONE
    layer's page array — the verify-step sibling of `_paged_write`."""
    ps = pool.shape[1]
    page, off = _token_block_pages(table, positions, t.shape[1], ps)
    return pool.at[page, off].set(t.astype(pool.dtype))


def _paged_write_tokens_q(pool, scale, table, positions, t, *,
                          bits: int = 8):
    """Quantized-page form of `_paged_write_tokens`."""
    ps = pool.shape[1]
    q, s = _quantize_head_vectors(t, bits)
    page, off = _token_block_pages(table, positions, t.shape[1], ps)
    return pool.at[page, off].set(q.astype(pool.dtype)), \
        scale.at[page, off].set(s)


def _decode_step_paged_gpt(model, params, tokens, k_pool, v_pool, table,
                           positions, k_scale, v_scale, kv_quant):
    from hetu_tpu.ops.pallas.paged_attention import paged_attention
    c = model.config
    mp_ = params["model"]
    b = tokens.shape[0]
    quant = k_scale is not None
    bits = 4 if kv_quant == "int4" else 8
    x = _gpt_embed(model, mp_, tokens[:, None], positions[:, None])
    block = model.model.block
    att = block.attn
    nh, hd = c.num_attention_heads, c.head_dim
    scale = hd ** -0.5

    def body(h, xs):
        if quant:
            lp, kp, vp, ksc, vsc = xs
        else:
            lp, kp, vp = xs
            ksc = vsc = None
        hn = block.ln1(lp["ln1"], h)
        qkv = jnp.einsum("bsh,hngd->bsngd", hn,
                         lp["attn"]["wqkv"].astype(h.dtype)) \
            + lp["attn"]["bqkv"].astype(h.dtype)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        if quant:
            kp, ksc = _paged_write_q(kp, ksc, table, positions, k[:, 0],
                                     bits=bits)
            vp, vsc = _paged_write_q(vp, vsc, table, positions, v[:, 0],
                                     bits=bits)
        else:
            kp = _paged_write(kp, table, positions, k[:, 0])
            vp = _paged_write(vp, table, positions, v[:, 0])
        with jax.named_scope("pallas_paged_attention"):
            attn = paged_attention(q[:, 0], kp, vp, table, positions,
                                   softmax_scale=scale,
                                   k_scale=ksc, v_scale=vsc,
                                   quant=kv_quant)
        h = h + att.o_proj(lp["attn"]["o_proj"],
                           attn.reshape(b, 1, nh * hd))
        h = h + block.mlp(lp["mlp"], block.ln2(lp["ln2"], h))
        return h, ((kp, vp, ksc, vsc) if quant else (kp, vp))

    xs = ((mp_["blocks"], k_pool, v_pool, k_scale, v_scale) if quant
          else (mp_["blocks"], k_pool, v_pool))
    x, pools = lax.scan(body, x, xs)
    hidden = model.model.final_ln(mp_["final_ln"], x)
    logits = model.logits(params, hidden)[:, 0, :]
    return (logits,) + tuple(pools)


def decode_step_paged(model, params, tokens, k_pool, v_pool, table,
                      positions, *, k_scale=None, v_scale=None,
                      kv_quant=None):
    """One decode step attending DIRECTLY over a paged KV pool — the
    gather-free form of `decode_step_slots` (ops/pallas/paged_attention;
    serving engine's HETU_TPU_PALLAS decode program).

    k_pool/v_pool: [L, P, page_size, n_kv, hd] (page 0 = the null page);
    table: [S, max_pages] int32; positions: [S] int32 — slot s's current
    token sits at positions[s] and attends over everything at or before
    it.  This step's K/V are scattered into each slot's page BEFORE the
    kernel runs (so the token sees itself, exactly like the dense path's
    write-then-attend), and the updated pools are returned:
    (logits [S, vocab], new_k_pool, new_v_pool).

    int8 pools (``HETU_TPU_KV_QUANT=int8``) pass their per-head-vector
    f32 scales [L, P, page_size, n_kv] as k_scale/v_scale: the token
    write quantizes through the shared blockwise primitives and the
    kernel dequantizes pages in-VMEM; the return gains
    (..., new_k_scale, new_v_scale).  int4 pools
    (``HETU_TPU_KV_QUANT=int4``) additionally pass ``kv_quant="int4"``
    — uint8 nibble payloads of head dim hd//2, the
    `ops/quantization.pack_nibbles` storage layout."""
    c = model.config
    if not c.use_scan:
        raise ValueError("generation requires use_scan=True (stacked layer "
                         "params)")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    quant = k_scale is not None
    if kv_quant is None:
        kv_quant = "int8" if quant else None
    bits = 4 if kv_quant == "int4" else 8
    positions = positions.astype(jnp.int32)
    table = table.astype(jnp.int32)
    if _is_gpt(model):
        return _decode_step_paged_gpt(model, params, tokens, k_pool,
                                      v_pool, table, positions,
                                      k_scale, v_scale, kv_quant)
    from hetu_tpu.ops.pallas.paged_attention import paged_attention
    mp_ = params["model"]
    b = tokens.shape[0]
    x = model.model.embed(mp_["embed"], tokens[:, None]).astype(
        c.compute_dtype)
    cos, sin = ops.build_rope_cache(c.max_position_embeddings, c.head_dim,
                                    c.rope_theta)
    block = model.model.layers.block
    att = block.attn
    scale = c.head_dim ** -0.5

    def body(h, xs):
        if quant:
            layer_params, kp, vp, ksc, vsc = xs
        else:
            layer_params, kp, vp = xs
            ksc = vsc = None
        hn = block.input_norm(layer_params["input_norm"], h)
        qkv = jnp.einsum("bsh,hkgd->bskgd", hn,
                         layer_params["attn"]["wqkv"].astype(h.dtype))
        q = qkv[..., : att.group, :].reshape(b, 1, att.n_q, c.head_dim)
        k = qkv[..., att.group, :]
        v = qkv[..., att.group + 1, :]
        q, k = ops.apply_rotary_qk(q, k, cos, sin, positions[:, None])
        if quant:
            kp, ksc = _paged_write_q(kp, ksc, table, positions, k[:, 0],
                                     bits=bits)
            vp, vsc = _paged_write_q(vp, vsc, table, positions, v[:, 0],
                                     bits=bits)
        else:
            kp = _paged_write(kp, table, positions, k[:, 0])
            vp = _paged_write(vp, table, positions, v[:, 0])
        with jax.named_scope("pallas_paged_attention"):
            attn = paged_attention(q[:, 0], kp, vp, table, positions,
                                   softmax_scale=scale,
                                   k_scale=ksc, v_scale=vsc,
                                   quant=kv_quant)
        h = h + att.o_proj(layer_params["attn"]["o_proj"],
                           attn.reshape(b, 1, att.n_q * c.head_dim))
        mlp_out = block.mlp(layer_params["mlp"],
                            block.post_norm(layer_params["post_norm"], h))
        if isinstance(mlp_out, tuple):  # MoE
            mlp_out = mlp_out[0]
        h = h + mlp_out
        return h, ((kp, vp, ksc, vsc) if quant else (kp, vp))

    xs = ((mp_["layers"]["layers"], k_pool, v_pool, k_scale, v_scale)
          if quant else (mp_["layers"]["layers"], k_pool, v_pool))
    x, pools = lax.scan(body, x, xs)
    hidden = model.model.final_norm(mp_["final_norm"], x)
    logits = model.logits(params, hidden)[:, 0, :]
    return (logits,) + tuple(pools)


def _verify_step_paged_gpt(model, params, tokens, k_pool, v_pool, table,
                           positions, k_scale, v_scale, kv_quant,
                           return_hidden):
    from hetu_tpu.ops.pallas.paged_attention import paged_verify
    c = model.config
    mp_ = params["model"]
    S, C = tokens.shape
    quant = k_scale is not None
    bits = 4 if kv_quant == "int4" else 8
    qpos = positions[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    x = _gpt_embed(model, mp_, tokens, qpos)
    block = model.model.block
    att = block.attn
    nh, hd = c.num_attention_heads, c.head_dim
    scale = hd ** -0.5

    def body(h, xs):
        if quant:
            lp, kp, vp, ksc, vsc = xs
        else:
            lp, kp, vp = xs
            ksc = vsc = None
        hn = block.ln1(lp["ln1"], h)
        qkv = jnp.einsum("bsh,hngd->bsngd", hn,
                         lp["attn"]["wqkv"].astype(h.dtype)) \
            + lp["attn"]["bqkv"].astype(h.dtype)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        if quant:
            kp, ksc = _paged_write_tokens_q(kp, ksc, table, positions, k,
                                            bits=bits)
            vp, vsc = _paged_write_tokens_q(vp, vsc, table, positions, v,
                                            bits=bits)
        else:
            kp = _paged_write_tokens(kp, table, positions, k)
            vp = _paged_write_tokens(vp, table, positions, v)
        with jax.named_scope("pallas_paged_verify"):
            attn = paged_verify(
                q.reshape(S, C, nh, hd), kp, vp, table, positions,
                softmax_scale=scale, k_scale=ksc, v_scale=vsc,
                quant=kv_quant)
        h = h + att.o_proj(lp["attn"]["o_proj"],
                           attn.reshape(S, C, nh * hd))
        h = h + block.mlp(lp["mlp"], block.ln2(lp["ln2"], h))
        return h, ((kp, vp, ksc, vsc) if quant else (kp, vp))

    xs = ((mp_["blocks"], k_pool, v_pool, k_scale, v_scale) if quant
          else (mp_["blocks"], k_pool, v_pool))
    x, pools = lax.scan(body, x, xs)
    hidden = model.model.final_ln(mp_["final_ln"], x)
    if return_hidden:
        return (hidden,) + tuple(pools)
    return (model.logits(params, hidden),) + tuple(pools)


def verify_step_paged(model, params, tokens, k_pool, v_pool, table,
                      positions, *, k_scale=None, v_scale=None,
                      kv_quant=None, return_hidden: bool = False):
    """The speculative VERIFY step attending DIRECTLY over a paged KV
    pool — `verify_step_slots` without the gather (ops/pallas/
    paged_attention.paged_verify: all k+1 query positions walk the
    slot's pages in one launch with per-position causal masks).

    tokens: [S, C] int32 (last emitted token + k drafts per slot);
    positions: [S] int32 — token i of the block sits at positions[s]+i.
    The block's K/V are scattered into each slot's pages BEFORE the
    kernel runs (write-then-attend, exactly like the dense path), and
    the updated pools return: (logits [S, C, vocab], *new_pools).
    Quantized pools pass scales (+ ``kv_quant="int4"`` for nibble
    pages) exactly as `decode_step_paged`.

    ``return_hidden=True`` returns the final-norm HIDDEN states
    [S, C, hidden] instead of logits — the fused sampling epilogue
    (serving/sampling.sample_hidden_grid) consumes them directly so the
    [S, C, vocab] logits plane never materializes in HBM."""
    c = model.config
    if not c.use_scan:
        raise ValueError("generation requires use_scan=True (stacked layer "
                         "params)")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    quant = k_scale is not None
    if kv_quant is None:
        kv_quant = "int8" if quant else None
    bits = 4 if kv_quant == "int4" else 8
    positions = positions.astype(jnp.int32)
    table = table.astype(jnp.int32)
    if _is_gpt(model):
        return _verify_step_paged_gpt(model, params, tokens, k_pool,
                                      v_pool, table, positions, k_scale,
                                      v_scale, kv_quant, return_hidden)
    from hetu_tpu.ops.pallas.paged_attention import paged_verify
    mp_ = params["model"]
    S, C = tokens.shape
    qpos = positions[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    x = model.model.embed(mp_["embed"], tokens).astype(c.compute_dtype)
    cos, sin = ops.build_rope_cache(c.max_position_embeddings, c.head_dim,
                                    c.rope_theta)
    block = model.model.layers.block
    att = block.attn
    scale = c.head_dim ** -0.5

    def body(h, xs):
        if quant:
            layer_params, kp, vp, ksc, vsc = xs
        else:
            layer_params, kp, vp = xs
            ksc = vsc = None
        hn = block.input_norm(layer_params["input_norm"], h)
        qkv = jnp.einsum("bsh,hkgd->bskgd", hn,
                         layer_params["attn"]["wqkv"].astype(h.dtype))
        q = qkv[..., : att.group, :].reshape(S, C, att.n_q, c.head_dim)
        k = qkv[..., att.group, :]
        v = qkv[..., att.group + 1, :]
        q, k = ops.apply_rotary_qk(q, k, cos, sin, qpos)
        if quant:
            kp, ksc = _paged_write_tokens_q(kp, ksc, table, positions, k,
                                            bits=bits)
            vp, vsc = _paged_write_tokens_q(vp, vsc, table, positions, v,
                                            bits=bits)
        else:
            kp = _paged_write_tokens(kp, table, positions, k)
            vp = _paged_write_tokens(vp, table, positions, v)
        with jax.named_scope("pallas_paged_verify"):
            attn = paged_verify(q, kp, vp, table, positions,
                                softmax_scale=scale, k_scale=ksc,
                                v_scale=vsc, quant=kv_quant)
        h = h + att.o_proj(layer_params["attn"]["o_proj"],
                           attn.reshape(S, C, att.n_q * c.head_dim))
        mlp_out = block.mlp(layer_params["mlp"],
                            block.post_norm(layer_params["post_norm"], h))
        if isinstance(mlp_out, tuple):  # MoE
            mlp_out = mlp_out[0]
        h = h + mlp_out
        return h, ((kp, vp, ksc, vsc) if quant else (kp, vp))

    xs = ((mp_["layers"]["layers"], k_pool, v_pool, k_scale, v_scale)
          if quant else (mp_["layers"]["layers"], k_pool, v_pool))
    x, pools = lax.scan(body, x, xs)
    hidden = model.model.final_norm(mp_["final_norm"], x)
    if return_hidden:
        return (hidden,) + tuple(pools)
    return (model.logits(params, hidden),) + tuple(pools)


def _extend_cache_gpt(model, params, tokens, cache, start,
                      collect: bool = False):
    c = model.config
    mp = params["model"]
    b, C = tokens.shape
    rows = jnp.arange(b)
    start = jnp.broadcast_to(jnp.asarray(start, jnp.int32), (b,))
    qpos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]  # [b, C]
    x = _gpt_embed(model, mp, tokens, qpos)
    block = model.model.block
    att = block.attn
    nh, hd = c.num_attention_heads, c.head_dim
    scale = hd ** -0.5
    cache_k, cache_v = cache

    def body(h, xs):
        lp, ck, cv = xs
        hn = block.ln1(lp["ln1"], h)
        qkv = jnp.einsum("bsh,hngd->bsngd", hn,
                         lp["attn"]["wqkv"].astype(h.dtype)) \
            + lp["attn"]["bqkv"].astype(h.dtype)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        ck = ck.at[rows[:, None], qpos].set(k.astype(ck.dtype))
        cv = cv.at[rows[:, None], qpos].set(v.astype(cv.dtype))
        attn = _attend_cached_chunk(q, ck, cv, start, scale)
        h = h + att.o_proj(lp["attn"]["o_proj"],
                           attn.reshape(b, C, nh * hd))
        h = h + block.mlp(lp["mlp"], block.ln2(lp["ln2"], h))
        return h, ((ck, cv, k, v) if collect else (ck, cv))

    x, ys = lax.scan(body, x, (mp["blocks"], cache_k, cache_v))
    hidden = model.model.final_ln(mp["final_ln"], x)
    logits = model.logits(params, hidden)
    if collect:
        new_k, new_v, k_chunk, v_chunk = ys
        return logits, (new_k, new_v), (k_chunk, v_chunk)
    return logits, ys


def extend_cache(model, params, tokens, cache, start, *,
                 collect_token_kv: bool = False):
    """Advance a KV cache by a whole token block (chunked prefill).

    tokens: [b, C] int32 at absolute positions start..start+C-1 (start
    scalar or [b]); the chunk's K/V are written into the cache and each
    query attends causally over cache[:start+i+1].  Returns
    (logits [b, C, vocab], new_cache).  Running consecutive chunks
    through this is numerically the incremental form of `prefill` — the
    serving engine uses it so one long prompt never stalls the decode
    batch (docs/serving.md).

    ``collect_token_kv=True`` (the `verify_step_slots` path) also
    returns the chunk's per-layer K/V [L, b, C, n_kv, hd] so a paged
    cache can scatter them into its pool; the default False traces
    exactly the pre-speculative chunk program."""
    c = model.config
    if not c.use_scan:
        raise ValueError("generation requires use_scan=True (stacked layer "
                         "params)")
    if _is_gpt(model):
        return _extend_cache_gpt(model, params, tokens, cache, start,
                                 collect=collect_token_kv)
    mp = params["model"]
    b, C = tokens.shape
    rows = jnp.arange(b)
    start = jnp.broadcast_to(jnp.asarray(start, jnp.int32), (b,))
    qpos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]  # [b, C]
    x = model.model.embed(mp["embed"], tokens).astype(c.compute_dtype)
    cos, sin = ops.build_rope_cache(c.max_position_embeddings, c.head_dim,
                                    c.rope_theta)
    block = model.model.layers.block
    att = block.attn
    scale = c.head_dim ** -0.5

    cache_k, cache_v = cache

    def body(carry, xs):
        h = carry
        layer_params, ck, cv = xs
        hn = block.input_norm(layer_params["input_norm"], h)
        qkv = jnp.einsum("bsh,hkgd->bskgd", hn,
                         layer_params["attn"]["wqkv"].astype(h.dtype))
        q = qkv[..., : att.group, :].reshape(b, C, att.n_q, c.head_dim)
        k = qkv[..., att.group, :]
        v = qkv[..., att.group + 1, :]
        q, k = ops.apply_rotary_qk(q, k, cos, sin, qpos)
        ck = ck.at[rows[:, None], qpos].set(k.astype(ck.dtype))
        cv = cv.at[rows[:, None], qpos].set(v.astype(cv.dtype))
        attn = _attend_cached_chunk(q, ck, cv, start, scale)
        h = h + att.o_proj(layer_params["attn"]["o_proj"],
                           attn.reshape(b, C, att.n_q * c.head_dim))
        mlp_out = block.mlp(layer_params["mlp"],
                            block.post_norm(layer_params["post_norm"], h))
        if isinstance(mlp_out, tuple):  # MoE
            mlp_out = mlp_out[0]
        h = h + mlp_out
        return h, ((ck, cv, k, v) if collect_token_kv else (ck, cv))

    x, ys = lax.scan(
        body, x, (mp["layers"]["layers"], cache_k, cache_v))
    hidden = model.model.final_norm(mp["final_norm"], x)
    logits = model.logits(params, hidden)
    if collect_token_kv:
        new_k, new_v, k_chunk, v_chunk = ys
        return logits, (new_k, new_v), (k_chunk, v_chunk)
    return logits, ys


def verify_step_slots(model, params, tokens, cache, positions):
    """The speculative-decoding VERIFY step: advance every slot by a
    whole [k+1]-token block in ONE forward (serving/spec_decode.py).

    tokens: [S, k+1] int32 — per slot, the last emitted token followed
    by the k draft tokens; positions: [S] int32 — the slot's current
    write position (token i of the block sits at positions[s] + i).
    This is exactly `extend_cache` with PER-SLOT start positions (each
    batch row an independent sequence at its own depth, the
    `decode_step_slots` convention) plus the block's per-layer K/V
    handed out for the paged-pool scatter.

    Returns (logits [S, k+1, vocab], new_cache, (k_chunk, v_chunk))
    with k_chunk/v_chunk [L, S, k+1, n_kv, hd].  logits[:, i] is the
    next-token distribution AFTER input token i — the verification
    targets: greedy acceptance compares draft i+1 against
    argmax(logits[:, i]), bit-identical to what the sequential
    single-token path would have computed at that depth (same
    chunk-causal grouped-GQA attention as chunked prefill — one
    implementation, so spec-decode and sequential decode cannot drift
    numerically)."""
    return extend_cache(model, params, tokens, cache,
                        positions.astype(jnp.int32),
                        collect_token_kv=True)


def generate(model, params, input_ids, *, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             eos_id: Optional[int] = None,
             eos_token_id: Optional[int] = None,
             pad_token_id: Optional[int] = None):
    """Autoregressive generation (greedy when temperature == 0; top_k
    and/or top_p (nucleus) filtering when sampling).
    input_ids: [b, plen] int32 -> [b, plen + max_new_tokens].

    EOS handling: with eos_token_id (alias: eos_id) set, a sequence that
    emits EOS is done — it keeps emitting `pad_token_id` (default: the
    EOS id itself, the pre-serving behavior) and, once EVERY sequence in
    the batch is done, the remaining scan iterations skip the decode
    computation entirely via lax.cond (the same active-mask early-exit
    the serving scheduler uses per slot)."""
    b, plen = input_ids.shape
    max_len = plen + max_new_tokens
    # context-length validation happens in prefill (_check_context_length)
    logits, cache = prefill(model, params, input_ids, max_len)
    rng = rng if rng is not None else jax.random.key(0)
    eos = eos_token_id if eos_token_id is not None else eos_id
    fill = pad_token_id if pad_token_id is not None else eos

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None or (top_p is not None and top_p > 0.0):
            # ONE descending full-vocab sort serves both filters (the sort
            # is the sampler's dominant cost inside the decode scan)
            desc = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k is not None:
            kth = desc[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -1e30, logits)
        if top_p is not None and top_p > 0.0:
            # nucleus: keep the smallest prefix of the sorted distribution
            # whose mass exceeds top_p; the max-prob token always survives
            # (its preceding mass is 0 < top_p), so small top_p degenerates
            # to greedy.  top_p in (None, 0.0) = filter disabled.  With
            # top_k set, the nucleus is computed over the RENORMALIZED
            # top-k distribution (HF semantics: top_k filters first); the
            # filtered descending view is just the top-k prefix of `desc`,
            # so no second sort is needed.
            desc_f = desc if top_k is None else jnp.where(
                jnp.arange(desc.shape[-1]) < top_k, desc, -1e30)
            probs = jax.nn.softmax(desc_f, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = cum - probs < top_p          # mass BEFORE this token
            cutoff = jnp.min(jnp.where(keep, desc_f, jnp.inf),
                             axis=-1, keepdims=True)
            logits = jnp.where(logits < cutoff, -1e30, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    def step(carry, i):
        logits, cache, key, done = carry
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        if eos is not None:
            tok = jnp.where(done, fill, tok)
            done = done | (tok == eos)
            # all sequences finished -> skip the whole decode computation
            # (a real branch under the scan: only the taken side runs)
            logits, cache = lax.cond(
                jnp.all(done),
                lambda c: c,
                lambda c: decode_step(model, params, tok, c[1], plen + i),
                (logits, cache))
        else:
            logits, cache = decode_step(model, params, tok, cache, plen + i)
        return (logits, cache, key, done), tok

    done0 = jnp.zeros((b,), bool)
    (_, _, _, _), toks = lax.scan(
        step, (logits, cache, rng, done0), jnp.arange(max_new_tokens))
    return jnp.concatenate([input_ids, toks.T], axis=1)
