"""LLaMA model family, TPU-first.

Functional rebuild of the reference LLaMA
(reference: python/hetu/models/llama/llama_model.py:88 LlamaAttention,
:292 LlamaMLP, :342 LlamaBlock, :385 LlamaModel, :446 LlamaLMHeadModel)
with TPU-native choices:

- fused, kv-group-aligned QKV projection (one MXU matmul; the TP split lands
  on kv-head-group boundaries so no resharding is needed after the reshape)
- fused gate+up projection stored [h, 2, I] (TP split on I)
- scan-over-layers (`lax.scan` over stacked per-layer params) — one compiled
  block body instead of L copies; remat (`jax.checkpoint`) per block is the
  reference's recompute pass (recompute/recompute.cc) for free
- layouts come from ParallelStrategy; the same model code runs single-chip,
  TP/SP, DP×TP, and (via the parallel engines) PP and ring-attention CP.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hetu_tpu import ops
from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module, ParamSpec, stack_param_specs
from hetu_tpu.nn.remat import remat_policy as _remat_policy
from hetu_tpu.nn.parallel import (
    ColumnParallelLinear, ParallelRMSNorm, RowParallelLinear,
    VocabParallelEmbedding,
)
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.models.llama.config import LlamaConfig
from hetu_tpu.dstates import DistributedStates as DS


class LlamaAttention(Module):
    """GQA attention with RoPE (reference: llama_model.py:88)."""

    def __init__(self, config: LlamaConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config, self.strategy = config, strategy
        c, hd = config, config.head_dim
        self.n_q, self.n_kv = c.num_attention_heads, c.num_key_value_heads
        self.group = self.n_q // self.n_kv  # q heads per kv head
        if self.n_kv % max(strategy.tp, 1) != 0:
            raise ValueError(
                f"num_key_value_heads={self.n_kv} must divide by tp={strategy.tp}")
        # qkv weight [h, n_kv, group+2, hd]: per kv group [q...q | k | v].
        # TP shards the n_kv dim -> the fused matmul splits cleanly.
        qkv_ds = DS.make(4, {1: "tp"}) if strategy.tp > 1 else None
        qkv_ds = strategy.fsdp(qkv_ds, 4, 0)
        self.param("wqkv", (c.hidden_size, self.n_kv, self.group + 2, hd),
                   init.normal(c.initializer_range), dtype=c.param_dtype,
                   ds=qkv_ds)
        self.o_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, strategy, bias=False,
            param_dtype=c.param_dtype,
            weight_init=init.normal(c.initializer_range))

    def forward(self, params, x, *, cos, sin,
                position_ids: Optional[jnp.ndarray] = None,
                segment_ids: Optional[jnp.ndarray] = None,
                rng: Optional[jnp.ndarray] = None,
                deterministic: bool = True):
        c, st = self.config, self.strategy
        b, s, h = x.shape
        hd = c.head_dim
        qkv = jnp.einsum("bsh,hkgd->bskgd", x, params["wqkv"].astype(x.dtype))
        qkv = st.constrain(qkv, st.act_qkv())
        q = qkv[..., : self.group, :].reshape(b, s, self.n_q, hd)
        k = qkv[..., self.group, :]
        v = qkv[..., self.group + 1, :]

        # one fused Pallas pass over q AND k when routed
        # (HETU_TPU_PALLAS; fallback = the seed's two apply_rotary calls)
        q, k = ops.apply_rotary_qk(q, k, cos, sin, position_ids)

        use_attn_dropout = (c.attention_dropout > 0.0 and not deterministic
                            and rng is not None)
        if st.cp > 1:
            if use_attn_dropout:
                # mirror the pipeline's explicit guard — silently dropping a
                # configured attention_dropout would be a training-semantics
                # surprise
                raise NotImplementedError(
                    "attention_dropout inside ring attention (cp > 1)")
            # the ring composes with the GSPMD pipeline too (a full
            # shard_map nests cleanly inside vmap(spmd_axis_name='pp');
            # only the PARTIAL-manual shard_map mode is partitioner-hostile)
            from hetu_tpu.parallel.ring_attention import ring_attention_gspmd
            attn = ring_attention_gspmd(q, k, v, strategy=st,
                                        segment_ids=segment_ids,
                                        position_ids=position_ids)
        elif use_attn_dropout:
            # dropout on attention probs only exists in the XLA composition
            attn = ops.attention(q, k, v, causal=True, segment_ids=segment_ids,
                                 dropout_rate=c.attention_dropout,
                                 dropout_rng=jax.random.fold_in(rng, 1))
        else:
            # use_pallas=None -> auto (Pallas kernel when built & on TPU)
            attn = ops.flash_attention(
                q, k, v, causal=True, segment_ids=segment_ids,
                use_pallas=None if c.use_flash_attention else False)
        attn = st.constrain(attn, st.act_attn())
        # named so the "dots_attn" remat policy can SAVE the kernel output:
        # recomputing flash attention in the bwd is the single most
        # expensive recompute under the dot-only policies (nn/remat.py)
        from jax.ad_checkpoint import checkpoint_name
        attn = checkpoint_name(attn, "attn_out")
        out = self.o_proj(params["o_proj"], attn.reshape(b, s, self.n_q * hd))
        return out


class LlamaMLP(Module):
    """SwiGLU MLP with fused gate+up (reference: llama_model.py:292)."""

    def __init__(self, config: LlamaConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config, self.strategy = config, strategy
        c = config
        gu_ds = DS.make(3, {2: "tp"}) if strategy.tp > 1 else None
        gu_ds = strategy.fsdp(gu_ds, 3, 0)
        self.param("w_gate_up", (c.hidden_size, 2, c.intermediate_size),
                   init.normal(c.initializer_range), dtype=c.param_dtype,
                   ds=gu_ds)
        self.down_proj = RowParallelLinear(
            c.intermediate_size, c.hidden_size, strategy, bias=False,
            param_dtype=c.param_dtype,
            weight_init=init.normal(c.initializer_range))

    def forward(self, params, x):
        st = self.strategy
        gu = jnp.einsum("bsh,hci->bsci", x, params["w_gate_up"].astype(x.dtype))
        gu = st.constrain(gu, st.act_gate_up())
        hidden = ops.swiglu(gu[:, :, 0, :], gu[:, :, 1, :])
        return self.down_proj(params["down_proj"], hidden)


class LlamaBlock(Module):
    """Pre-norm transformer block (reference: llama_model.py:342)."""

    def __init__(self, config: LlamaConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config = config
        c = config
        self.input_norm = ParallelRMSNorm(c.hidden_size, strategy,
                                          eps=c.rms_norm_eps,
                                          param_dtype=c.param_dtype)
        self.attn = LlamaAttention(c, strategy)
        self.post_norm = ParallelRMSNorm(c.hidden_size, strategy,
                                         eps=c.rms_norm_eps,
                                         param_dtype=c.param_dtype)
        if c.num_experts > 0:
            from hetu_tpu.nn.moe import MoEConfig, MoELayer
            self.mlp = MoELayer(
                c.hidden_size, c.intermediate_size,
                MoEConfig(num_experts=c.num_experts, top_k=c.moe_top_k,
                          capacity_factor=c.moe_capacity_factor,
                          gate=c.moe_gate, dispatch=c.moe_dispatch,
                          sam_group_size=c.moe_sam_group_size),
                strategy, param_dtype=c.param_dtype,
                initializer_range=c.initializer_range)
        else:
            self.mlp = LlamaMLP(c, strategy)

    def forward(self, params, x, *, cos, sin, position_ids=None,
                segment_ids=None, rng=None, deterministic=True,
                token_ids=None):
        c = self.config
        # named phase scopes survive into the optimized HLO metadata and
        # profiler traces (utils/profiling.py phase_breakdown reads them;
        # reference: impl/profiler/profiler.h:25 per-op cost attribution)
        with jax.named_scope("attn"):
            h = self.attn(params["attn"],
                          self.input_norm(params["input_norm"], x),
                          cos=cos, sin=sin, position_ids=position_ids,
                          segment_ids=segment_ids, rng=rng,
                          deterministic=deterministic)
        if not deterministic and rng is not None:
            h = ops.dropout(h, c.hidden_dropout, jax.random.fold_in(rng, 2),
                            deterministic)
        # the residual-add + post-norm pair fuses into ONE Pallas pass
        # when routed (nn/parallel.ParallelRMSNorm.residual); the
        # fallback is exactly the seed composition `x = x + h; norm(x)`
        aux = jnp.zeros((), jnp.float32)
        if c.num_experts > 0:
            with jax.named_scope("moe"):
                normed, x = self.post_norm.residual(params["post_norm"],
                                                    x, h)
                h, aux = self.mlp(params["mlp"], normed,
                                  token_ids=token_ids)
        else:
            with jax.named_scope("mlp"):
                normed, x = self.post_norm.residual(params["post_norm"],
                                                    x, h)
                h = self.mlp(params["mlp"], normed)
        if not deterministic and rng is not None:
            h = ops.dropout(h, c.hidden_dropout, jax.random.fold_in(rng, 3),
                            deterministic)
        return x + h, aux


class LlamaDecoderStack(Module):
    """All decoder layers as ONE scanned block with stacked params
    (use_scan=True) or a python loop of per-layer subtrees (False)."""

    def __init__(self, config: LlamaConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config, self.strategy = config, strategy
        self.block = LlamaBlock(config, strategy)
        self.num_layers = config.num_hidden_layers

    def param_specs(self):
        block_specs = self.block.param_specs()
        if self.config.use_scan:
            # pp shards the layer dim -> each stage holds its layer slice
            lead = "pp" if self.strategy.pp > 1 else None
            return {"layers": stack_param_specs(block_specs, self.num_layers,
                                                lead_axis=lead)}
        import copy
        return {f"layer_{i}": copy.deepcopy(block_specs)
                for i in range(self.num_layers)}

    def forward(self, params, x, *, cos, sin, position_ids=None,
                segment_ids=None, rng=None, deterministic=True,
                n_micro: Optional[int] = None, token_ids=None):
        c = self.config
        st = self.strategy
        use_drop = not deterministic and rng is not None
        if st.pp > 1:
            if not c.use_scan:
                raise ValueError("pipeline parallelism requires use_scan")
            return self._pipeline_forward(params, x, cos=cos, sin=sin,
                                          position_ids=position_ids,
                                          segment_ids=segment_ids,
                                          n_micro=n_micro,
                                          rng=rng if use_drop else None)
        layer_rngs = (jax.random.split(rng, self.num_layers)
                      if use_drop else None)

        def body(carry, xs):
            x_c, aux_c = carry
            layer_params, layer_rng = xs
            # the "layer" scope marks the scanned block body in HLO
            # metadata: per-layer attribution (obs.hlo_profile) groups
            # the whole stack under layer/... with the scan's trip
            # count multiplying through (unrolled stacks get layer_<i>)
            with jax.named_scope("layer"):
                out, aux = self.block(layer_params, x_c, cos=cos, sin=sin,
                                      position_ids=position_ids,
                                      segment_ids=segment_ids,
                                      rng=layer_rng if use_drop else None,
                                      deterministic=deterministic,
                                      token_ids=token_ids)
            return (out, aux_c + aux), None

        if c.use_scan:
            fn = body
            if c.remat:
                fn = jax.checkpoint(body, policy=_remat_policy(c.remat_policy))
            xs = (params["layers"],
                  layer_rngs if use_drop else
                  jnp.zeros((self.num_layers,), jnp.uint32))
            (x, aux), _ = lax.scan(fn, (x, jnp.zeros((), jnp.float32)), xs)
            return x, aux

        aux_total = jnp.zeros((), jnp.float32)
        for i in range(self.num_layers):
            def blk(p, y, i=i):
                # per-layer scope: decoder block i is individually
                # attributable in the optimized HLO (obs.hlo_profile
                # layer_table groups by layer_<i>/<phase>)
                with jax.named_scope(f"layer_{i}"):
                    return self.block(p, y, cos=cos, sin=sin,
                                      position_ids=position_ids,
                                      segment_ids=segment_ids,
                                      rng=layer_rngs[i] if use_drop else None,
                                      deterministic=deterministic,
                                      token_ids=token_ids)
            if c.remat:
                blk = jax.checkpoint(blk, policy=_remat_policy(c.remat_policy))
            x, aux = blk(params[f"layer_{i}"], x)
            aux_total = aux_total + aux
        return x, aux_total

    def _pipeline_forward(self, params, x, *, cos, sin, position_ids,
                          segment_ids, n_micro: Optional[int], rng=None):
        """pp > 1: run the decoder stack through the circular SPMD pipeline
        (hetu_tpu.parallel.pipeline; reference: executable_graph.cc:803/:836
        pipeline schedules).  Uneven stage_layers (the Malleus layout) run as
        padded + masked stage stacks."""
        from hetu_tpu.core.mesh import current_mesh
        from hetu_tpu.parallel.pipeline import staged_stack_forward

        st, c = self.strategy, self.config
        mesh = current_mesh()
        if mesh is None:
            raise ValueError("pipeline needs a mesh (use hetu_tpu.use_mesh)")

        if st.pp_tp_eff is not None:
            # unequal effective TP per stage in ONE program (reference:
            # distributed_states.h:158 unions over unequal stage groups)
            from hetu_tpu.parallel.hetero_pp import (
                llama_block_maker, staged_stack_forward_hetero_tp)
            if c.num_experts > 0 or st.cp > 1:
                raise NotImplementedError(
                    "pp_tp_eff composes with dense blocks, cp=1")
            if rng is not None and c.attention_dropout > 0.0:
                raise NotImplementedError(
                    "attention_dropout inside the hetero-TP pipeline "
                    "(hidden_dropout is supported)")
            return staged_stack_forward_hetero_tp(
                llama_block_maker(c, cos, sin, tp=st.tp,
                                  sequence_parallel=st.sequence_parallel),
                self.block.param_specs(), params["layers"], x,
                num_layers=self.num_layers, pp=st.pp, tp=st.tp,
                tp_eff=st.pp_tp_eff, mesh=mesh, rng=rng,
                sequence_parallel=st.sequence_parallel,
                position_ids=position_ids, segment_ids=segment_ids,
                stage_layers=c.pipeline_stage_layers, n_micro=n_micro,
                remat=c.remat, remat_policy=c.remat_policy,
                state_spec=st.pipeline_state_spec())

        def block_fn(layer_params, x_mb, pos_mb, seg_mb, rng=None):
            with jax.named_scope("layer"):
                return self.block(layer_params, x_mb, cos=cos, sin=sin,
                                  position_ids=pos_mb, segment_ids=seg_mb,
                                  rng=rng, deterministic=rng is None)

        return staged_stack_forward(
            block_fn, params["layers"], x,
            num_layers=self.num_layers, pp=st.pp, mesh=mesh,
            position_ids=position_ids, segment_ids=segment_ids,
            stage_layers=c.pipeline_stage_layers, n_micro=n_micro,
            remat=c.remat, remat_policy=c.remat_policy,
            state_spec=st.pipeline_state_spec(), rng=rng,
            # ragged (hetero-exec) stages skip untaken-branch collectives;
            # the cp ring's explicit ppermute spans all stages in one
            # instruction, and the MoE dispatch's grouped collectives
            # check-fail XLA's partitioner inside a non-uniform cond —
            # both layouts stay padded
            hetero_exec="auto" if (st.cp == 1 and c.num_experts == 0)
            else False)


class LlamaModel(Module):
    """Backbone: embed + decoder stack + final norm
    (reference: llama_model.py:385)."""

    def __init__(self, config: LlamaConfig,
                 strategy: Optional[ParallelStrategy] = None):
        super().__init__()
        strategy = strategy or ParallelStrategy()
        self.config, self.strategy = config, strategy
        c = config
        self.embed = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, strategy, param_dtype=c.param_dtype,
            weight_init=init.normal(c.initializer_range))
        self.layers = LlamaDecoderStack(c, strategy)
        self.final_norm = ParallelRMSNorm(c.hidden_size, strategy,
                                          eps=c.rms_norm_eps,
                                          param_dtype=c.param_dtype)

    def forward(self, params, input_ids, *, position_ids=None,
                segment_ids=None, rng=None, deterministic=True,
                n_micro=None):
        c, st = self.config, self.strategy
        with jax.named_scope("embed"):
            x = self.embed(params["embed"], input_ids).astype(c.compute_dtype)
            x = st.constrain(x, st.act_hidden())
            # numerics tap (obs/numerics.py, HETU_TPU_NUMERICS): no-op —
            # and zero traced ops — unless a collector is active.  Taps
            # sit at model BOUNDARIES (embed/hidden/logits), not inside
            # the scanned layer stack, so their values can always escape
            # to the step's auxiliary stats pytree.
            from hetu_tpu.obs import numerics as _numerics
            _numerics.tap_tree("embed", x)
        cos, sin = ops.build_rope_cache(
            c.max_position_embeddings, c.head_dim, c.rope_theta,
            dtype=jnp.float32)
        x, aux = self.layers(params["layers"], x, cos=cos, sin=sin,
                             position_ids=position_ids,
                             segment_ids=segment_ids,
                             rng=rng, deterministic=deterministic,
                             n_micro=n_micro, token_ids=input_ids)
        hidden = self.final_norm(params["final_norm"], x)
        from hetu_tpu.obs import numerics as _numerics
        _numerics.tap_tree("hidden", hidden)
        return hidden, aux


class LlamaLMHeadModel(Module):
    """LM head + loss (reference: llama_model.py:446 LlamaLMHeadModel with
    VocabParallelCrossEntropy).  In GSPMD mode the CE over the tp-sharded
    vocab dim compiles to the same max/denominator collectives the reference
    implements by hand."""

    def __init__(self, config: LlamaConfig,
                 strategy: Optional[ParallelStrategy] = None):
        super().__init__()
        strategy = strategy or ParallelStrategy()
        self.config, self.strategy = config, strategy
        c = config
        self.model = LlamaModel(c, strategy)
        if not c.tie_word_embeddings:
            if strategy.tp > 1 and c.vocab_size % strategy.tp:
                raise ValueError(
                    f"vocab size {c.vocab_size} must divide by tp="
                    f"{strategy.tp}; pad the vocab (e.g. 50257 -> 50304)")
            lm_ds = strategy.fsdp(
                DS.make(2, {1: "tp"}) if strategy.tp > 1 else None, 2, 0)
            self.param("lm_head", (c.hidden_size, c.vocab_size),
                       init.normal(c.initializer_range), dtype=c.param_dtype,
                       ds=lm_ds)

    def logits(self, params, hidden):
        c = self.config
        with jax.named_scope("lm_head"):
            if c.tie_word_embeddings:
                w = params["model"]["embed"]["weight"].astype(hidden.dtype).T
            else:
                w = params["lm_head"].astype(hidden.dtype)
            logits = hidden @ w
            logits = self.strategy.constrain(logits,
                                             self.strategy.act_logits())
            from hetu_tpu.obs import numerics as _numerics
            _numerics.tap_tree("logits", logits)
            return logits

    def forward(self, params, input_ids, labels=None, *, position_ids=None,
                segment_ids=None, rng=None, deterministic=True,
                loss_reduction: str = "mean", n_micro=None,
                include_aux_loss: bool = True, labels_shifted: bool = False):
        """include_aux_loss: fold MoE router losses into the returned loss
        (disable for evaluation so perplexity stays comparable to dense).

        labels_shifted: labels[t] is ALREADY the next-token target of
        input[t] (host-side pre-shift) — required when the seq axis was
        reordered (CP sym/stripe splits), where array adjacency no longer
        means token adjacency (reference: bucket.py:193
        generate_cp_pack_data pre-shifts before the CP split)."""
        hidden, aux = self.model(params["model"], input_ids,
                                 position_ids=position_ids,
                                 segment_ids=segment_ids,
                                 rng=rng, deterministic=deterministic,
                                 n_micro=n_micro)
        logits = self.logits(params, hidden)
        if labels is None:
            return logits
        # next-token objective: logits[t] predicts labels[t+1] (or labels[t]
        # when pre-shifted)
        if labels_shifted:
            lg, tgt = logits, labels
        else:
            lg, tgt = logits[:, :-1, :], labels[:, 1:]
        if loss_reduction not in ("mean", "sum"):
            raise ValueError(f"loss_reduction must be 'mean' or 'sum', got "
                             f"{loss_reduction!r}")
        if loss_reduction == "sum":
            # (sum, token_count) — lets grad accumulation / DP weight micro
            # batches by their true token counts instead of mean-of-means
            loss = ops.softmax_cross_entropy_sparse(
                lg, tgt, ignore_index=-100, reduction="sum")
            count = jnp.sum((tgt != -100).astype(jnp.float32))
            # aux (MoE router losses) scales with the token count so that
            # sum/count recovers mean-loss + aux
            if include_aux_loss:
                loss = loss + aux * count
            return loss, count
        loss = ops.softmax_cross_entropy_sparse(
            lg, tgt, ignore_index=-100)
        return loss + aux if include_aux_loss else loss

    # ------------------------------------------------------------------
    def pipeline_train_grads(self, params, input_ids, labels, *,
                             position_ids=None, segment_ids=None,
                             n_micro: int, labels_shifted: bool = False,
                             loss_scale=1.0, skip_dead_halves="auto",
                             rng=None):
        """1F1B (PipeDream-flush) training pass: returns
        ((loss_sum, count), grads) with grads matching `params` exactly
        (reference: executable_graph.cc:836 GeneratePipedreamFlushSchedule).

        Bit-parity with the GPipe autodiff path is tested; memory holds
        O(pp) stage inputs instead of O(n_micro) — use for large n_micro.
        Embedding runs inside stage 0 and final_norm + LM head + CE inside
        the last stage (hetu_tpu.parallel.pipeline_1f1b module docs)."""
        from hetu_tpu.core.mesh import current_mesh
        from hetu_tpu.parallel.pipeline import (
            build_stage_stack, unstack_stage_grads)
        from hetu_tpu.parallel.pipeline_1f1b import pipeline_train_1f1b

        c, st = self.config, self.strategy
        if st.pp <= 1:
            raise ValueError("pipeline_train_grads requires pp > 1")
        if st.pp_tp_eff is not None and (
                c.num_experts > 0 or st.cp > 1
                or (rng is not None and c.attention_dropout > 0.0)):
            raise NotImplementedError(
                "pp_tp_eff under 1f1b composes with dense blocks, cp=1, "
                "hidden dropout only (same envelope as the GPipe hetero "
                "path)")
        if not c.use_scan:
            raise ValueError("1f1b requires use_scan")
        mesh = current_mesh()
        if mesh is None:
            raise ValueError("pipeline needs a mesh (use hetu_tpu.use_mesh)")

        stack = params["model"]["layers"]["layers"]
        sp, layer_mask, stage_layers = build_stage_stack(
            stack, c.num_hidden_layers, st.pp, c.pipeline_stage_layers)
        ep = {"embed": params["model"]["embed"],
              "final_norm": params["model"]["final_norm"]}
        if not c.tie_word_embeddings:
            ep["lm_head"] = params["lm_head"]
        count = jnp.sum(((labels if labels_shifted else labels[:, 1:])
                         != -100).astype(jnp.float32))

        cos, sin = ops.build_rope_cache(
            c.max_position_embeddings, c.head_dim, c.rope_theta,
            dtype=jnp.float32)
        block = self.model.layers.block

        use_drop = rng is not None and (c.hidden_dropout > 0.0
                                        or c.attention_dropout > 0.0)

        def stage_scan(sp_slice, x0, pos, seg, mask_row, drop_seed, offset):
            def body(carry, xs):
                lp, mj = xs if mask_row is not None else (xs, None)
                x_c, aux_c, gid = carry
                layer_rng = None
                if use_drop:
                    # (micro bits, global layer id) -> a mask the backward
                    # visit REPRODUCES exactly: the seed rides the saved
                    # token stream, the id comes from the stage offset
                    layer_rng = jax.random.fold_in(
                        jax.random.key(drop_seed), gid)
                with jax.named_scope("layer"):
                    out, aux = block(lp, x_c, cos=cos, sin=sin,
                                     position_ids=pos, segment_ids=seg,
                                     rng=layer_rng,
                                     deterministic=not use_drop)
                if mj is not None:
                    out = jnp.where(mj > 0, out, x_c)
                    aux = aux * mj
                return (out, aux_c + aux, gid + 1), None

            fn = body
            if c.remat:
                fn = jax.checkpoint(body, policy=_remat_policy(c.remat_policy))
            xs = sp_slice if mask_row is None else (sp_slice, mask_row)
            # under the shard_map 1f1b round bodies x0 (and hence any
            # data-derived aux — mask-multiplied OR MoE router losses) is
            # pp-varying, so the scan's aux carry must start varying too
            from hetu_tpu.core.vma import cast_varying, vma_of
            init_aux = cast_varying(jnp.zeros((), jnp.float32),
                                    tuple(vma_of(x0)))
            gid0 = (offset if offset is not None
                    else cast_varying(jnp.zeros((), jnp.uint32),
                                      tuple(vma_of(x0))))
            (y, aux, _), _ = lax.scan(fn, (x0, init_aux, gid0), xs)
            return y, aux

        def head_loss(ep_, y, lab):
            hidden = self.model.final_norm(ep_["final_norm"], y)
            shim = {"model": {"embed": ep_["embed"]}}
            if not c.tie_word_embeddings:
                shim["lm_head"] = ep_["lm_head"]
            logits = self.logits(shim, hidden)
            if labels_shifted:
                lg, tgt = logits, lab
            else:
                lg, tgt = logits[:, :-1, :], lab[:, 1:]
            return ops.softmax_cross_entropy_sparse(
                lg, tgt, ignore_index=-100, reduction="sum")

        def stage_fn(sp_slice, ep_, x_in, feed_b, feed_s, flg):
            emb = self.model.embed(ep_["embed"], feed_b["ids"])
            emb = st.constrain(emb.astype(c.compute_dtype), st.act_hidden())
            x0 = jnp.where(flg["is_first"] > 0, emb, x_in)
            drop = feed_s.get("dropout_rng")
            y, aux = stage_scan(sp_slice, x0,
                                feed_s.get("position_ids"),
                                feed_s.get("segment_ids"),
                                flg.get("layer_mask"),
                                drop[0, 0] if drop is not None else None,
                                flg.get("stage_offset"))
            ce = head_loss(ep_, y, feed_b["labels"]) * flg["is_last"]
            return y, ce, aux

        ride = {}
        if position_ids is not None:
            ride["position_ids"] = position_ids
        if segment_ids is not None:
            ride["segment_ids"] = segment_ids
        flags_extra = {}
        if layer_mask is not None:
            flags_extra["layer_mask"] = layer_mask
        if use_drop:
            from hetu_tpu.parallel.pipeline_1f1b import build_dropout_ride
            ride["dropout_rng"], flags_extra["stage_offset"] = \
                build_dropout_ride(rng, n_micro, input_ids.shape,
                                   stage_layers)
        state_spec = st.pipeline_state_spec()

        custom = None
        if st.pp_tp_eff is not None:
            # per-stage hetero TP: manual-(pp, tp) switch round bodies with
            # the edges (vocab embedding, loss head) composed in auto mode
            # (parallel/hetero_pp.py hetero_tp_1f1b_rounds)
            from hetu_tpu.parallel.hetero_pp import (
                hetero_tp_1f1b_rounds, llama_block_maker)

            def embed_fn(ep_, feed_b, feed_s):
                emb = self.model.embed(ep_["embed"], feed_b["ids"])
                return st.constrain(emb.astype(c.compute_dtype),
                                    st.act_hidden())

            custom = hetero_tp_1f1b_rounds(
                llama_block_maker(c, cos, sin, tp=st.tp,
                                  sequence_parallel=st.sequence_parallel),
                block.param_specs(), embed_fn, head_loss,
                mesh=mesh, pp=st.pp, tp=st.tp, tp_eff=st.pp_tp_eff,
                stage_layers=stage_layers, remat=c.remat,
                remat_policy=c.remat_policy, compute_dtype=c.compute_dtype,
                token_keys=tuple(ride.keys()),
                sequence_parallel=st.sequence_parallel)

        ce_sum, aux_sum, d_stage, d_edge = pipeline_train_1f1b(
            stage_fn, sp, ep, input_ids, labels, ride,
            n_micro=n_micro, mesh=mesh, hidden_size=c.hidden_size,
            compute_dtype=c.compute_dtype, aux_seed=count,
            state_spec=state_spec, loss_scale=loss_scale,
            skip_dead_halves=skip_dead_halves,
            flags_extra=flags_extra or None, custom_rounds=custom)

        d_layers = unstack_stage_grads(
            d_stage, c.num_hidden_layers, st.pp, stage_layers)
        grads = {"model": {"embed": d_edge["embed"],
                           "layers": {"layers": d_layers},
                           "final_norm": d_edge["final_norm"]}}
        if not c.tie_word_embeddings:
            grads["lm_head"] = d_edge["lm_head"]
        return (ce_sum + aux_sum * count, count), grads
