from hetu_tpu.models.llama.config import LlamaConfig
from hetu_tpu.models.llama.model import (
    LlamaAttention, LlamaMLP, LlamaBlock, LlamaModel, LlamaLMHeadModel,
)
