"""HuggingFace <-> hetu_tpu weight conversion for the LLaMA family.

Rebuild of the reference's model hub/converter
(reference: python/hetu/models/utils/model_utils.py + config_utils.py:9 —
HF-compatible PreTrainedModel loading).  Maps an HF `LlamaForCausalLM` state
dict onto our parameter tree, regrouping per-head projections into the fused,
kv-group-aligned QKV layout and the fused gate+up layout (see
models/llama/model.py header for why those layouts exist).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax.numpy as jnp

from hetu_tpu.models.llama.config import LlamaConfig


def _t(x) -> np.ndarray:
    """torch tensor / array -> numpy float32."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, np.float32)


def convert_hf_llama(state_dict: Dict[str, Any], config: LlamaConfig,
                     dtype=None) -> Dict[str, Any]:
    """HF LlamaForCausalLM state dict -> hetu_tpu params pytree
    (use_scan layout: per-layer weights stacked on a leading dim)."""
    c = config
    h, hd = c.hidden_size, c.head_dim
    nq, nkv = c.num_attention_heads, c.num_key_value_heads
    g = nq // nkv
    L = c.num_hidden_layers
    dtype = dtype or c.param_dtype

    def get(name):
        return _t(state_dict[name])

    wqkv, o_proj, gate_up, down, in_norm, post_norm = [], [], [], [], [], []
    for i in range(L):
        pre = f"model.layers.{i}."
        # HF stores [out, in]; ours is [in, out]
        q = get(pre + "self_attn.q_proj.weight").T.reshape(h, nkv, g, hd)
        k = get(pre + "self_attn.k_proj.weight").T.reshape(h, nkv, 1, hd)
        v = get(pre + "self_attn.v_proj.weight").T.reshape(h, nkv, 1, hd)
        wqkv.append(np.concatenate([q, k, v], axis=2))  # [h, nkv, g+2, hd]
        o_proj.append(get(pre + "self_attn.o_proj.weight").T)
        gate = get(pre + "mlp.gate_proj.weight").T      # [h, I]
        up = get(pre + "mlp.up_proj.weight").T
        gate_up.append(np.stack([gate, up], axis=1))    # [h, 2, I]
        down.append(get(pre + "mlp.down_proj.weight").T)
        in_norm.append(get(pre + "input_layernorm.weight"))
        post_norm.append(get(pre + "post_attention_layernorm.weight"))

    def stack(xs):
        return jnp.asarray(np.stack(xs), dtype)

    layers = {
        "attn": {"wqkv": stack(wqkv), "o_proj": {"weight": stack(o_proj)}},
        "mlp": {"w_gate_up": stack(gate_up),
                "down_proj": {"weight": stack(down)}},
        "input_norm": {"weight": stack(in_norm)},
        "post_norm": {"weight": stack(post_norm)},
    }
    params: Dict[str, Any] = {
        "model": {
            "embed": {"weight": jnp.asarray(
                get("model.embed_tokens.weight"), dtype)},
            "layers": {"layers": layers},
            "final_norm": {"weight": jnp.asarray(
                get("model.norm.weight"), dtype)},
        }
    }
    if not c.tie_word_embeddings:
        lm = state_dict.get("lm_head.weight",
                            state_dict["model.embed_tokens.weight"])
        params["lm_head"] = jnp.asarray(_t(lm).T, dtype)
    return params


def export_hf_llama(params: Dict[str, Any], config: LlamaConfig) -> Dict[str, np.ndarray]:
    """Inverse mapping: hetu_tpu params -> HF state dict (numpy)."""
    c = config
    h, hd = c.hidden_size, c.head_dim
    nq, nkv = c.num_attention_heads, c.num_key_value_heads
    g = nq // nkv
    layers = params["model"]["layers"]["layers"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["model"]["embed"]["weight"], np.float32),
        "model.norm.weight": np.asarray(
            params["model"]["final_norm"]["weight"], np.float32),
    }
    wqkv = np.asarray(layers["attn"]["wqkv"], np.float32)
    o = np.asarray(layers["attn"]["o_proj"]["weight"], np.float32)
    gu = np.asarray(layers["mlp"]["w_gate_up"], np.float32)
    dn = np.asarray(layers["mlp"]["down_proj"]["weight"], np.float32)
    inn = np.asarray(layers["input_norm"]["weight"], np.float32)
    pon = np.asarray(layers["post_norm"]["weight"], np.float32)
    for i in range(c.num_hidden_layers):
        pre = f"model.layers.{i}."
        out[pre + "self_attn.q_proj.weight"] = \
            wqkv[i][:, :, :g, :].reshape(h, nq * hd).T
        out[pre + "self_attn.k_proj.weight"] = \
            wqkv[i][:, :, g, :].reshape(h, nkv * hd).T
        out[pre + "self_attn.v_proj.weight"] = \
            wqkv[i][:, :, g + 1, :].reshape(h, nkv * hd).T
        out[pre + "self_attn.o_proj.weight"] = o[i].T
        out[pre + "mlp.gate_proj.weight"] = gu[i][:, 0, :].T
        out[pre + "mlp.up_proj.weight"] = gu[i][:, 1, :].T
        out[pre + "mlp.down_proj.weight"] = dn[i].T
        out[pre + "input_layernorm.weight"] = inn[i]
        out[pre + "post_attention_layernorm.weight"] = pon[i]
    if "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T
    return out
