"""LLaMA configuration (reference: python/hetu/models/llama/llama_config.py +
HF-compatible PreTrainedConfig, models/utils/config_utils.py:9)."""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None  # None -> MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # the LLaMA family has the hetero-TP pipeline block maker
    # (parallel/hetero_pp.py llama_block_maker); ParallelStrategy.validate
    # rejects pp_tp_eff for families without one
    supports_hetero_tp: bool = True
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0

    # MoE (0 experts = dense; reference: v1 HetuMoE semantics, SURVEY §2.4)
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_gate: str = "topk"       # topk|top1|ktop1|balance|hash|sam
    moe_dispatch: str = "sort"   # sort (O(T·k) indices) | dense ([T,E,C])
    moe_sam_group_size: int = 0  # sam gate: experts per locality group
                                 # (0 = auto; see nn/moe.py MoEConfig)

    # heterogeneous pipeline: per-stage layer counts (sum = num_hidden_layers,
    # len = pp). None = equal split. The Malleus planner emits this
    # (reference: hetero pipelines with per-stage layer counts,
    # generate_llama_hetero_4d_config.py; engine/strategy.py planner)
    pipeline_stage_layers: object = None

    # TPU-build knobs
    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.bfloat16
    use_scan: bool = True          # lax.scan over layers (compile-time + pipeline friendly)
    remat: bool = True             # gradient checkpointing per block
                                   # (reference: recompute/recompute.cc pass)
    remat_policy: str = "nothing"  # nothing|dots|dots_attn|offload — what each
                                   # block saves (jax.checkpoint_policies;
                                   # 'offload' stages dot outputs to host,
                                   # the reference's activation_cpu_offload)
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        from hetu_tpu.nn.remat import validate_remat_policy
        validate_remat_policy(self.remat_policy)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # -- canonical sizes ----------------------------------------------------
    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Test-scale config."""
        d = dict(vocab_size=256, hidden_size=64, intermediate_size=176,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=256)
        d.update(kw)
        return LlamaConfig(**d)

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        d = dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                 num_hidden_layers=32, num_attention_heads=32,
                 num_key_value_heads=32, max_position_embeddings=4096)
        d.update(kw)
        return LlamaConfig(**d)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        d = dict(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                 num_hidden_layers=40, num_attention_heads=40,
                 num_key_value_heads=40, max_position_embeddings=4096)
        d.update(kw)
        return LlamaConfig(**d)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        d = dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                 num_hidden_layers=32, num_attention_heads=32,
                 num_key_value_heads=8, max_position_embeddings=8192,
                 rope_theta=500000.0)
        d.update(kw)
        return LlamaConfig(**d)

    def num_params(self) -> int:
        h, i, v, L = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_hidden_layers
        kvh = self.num_key_value_heads * self.head_dim
        ffn = 3 * h * i * max(self.num_experts, 1)
        per_layer = h * (h + 2 * kvh + h) + ffn + 2 * h  # attn + ffn + norms
        emb = v * h * (1 if self.tie_word_embeddings else 2)
        return L * per_layer + emb + h

    def flops_per_token(self, seq_len: int) -> float:
        """Approximate training FLOPs/token (fwd+bwd = 6*N + attention term)."""
        n = self.num_params()
        attn = 12 * self.num_hidden_layers * self.hidden_size * seq_len
        return 6.0 * n + attn
