from hetu_tpu.models.llama import LlamaConfig, LlamaModel, LlamaLMHeadModel
from hetu_tpu.models.gpt import GPTConfig, GPTModel, GPTLMHeadModel
