from hetu_tpu.models.llama import LlamaConfig, LlamaModel, LlamaLMHeadModel
