"""HuggingFace <-> hetu_tpu weight conversion for the GPT family.

Counterpart of models/llama/convert.py (reference: python/hetu/models/utils/
model_utils.py HF interop).  HF GPT-2 uses Conv1D modules whose weights are
stored [in, out] — already our orientation — so the mapping is mostly
regrouping: c_attn's packed [h, 3h] splits into our per-head
[h, heads, 3, hd] fused QKV, and per-layer tensors stack onto the leading
scan dim.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from hetu_tpu.models.gpt.model import GPTConfig


def _t(x) -> np.ndarray:
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, np.float32)


def convert_hf_gpt2(state_dict: Dict[str, Any], config: GPTConfig,
                    dtype=None) -> Dict[str, Any]:
    """HF GPT2LMHeadModel state dict -> hetu_tpu params pytree
    (use_scan layout: per-layer weights stacked on a leading dim)."""
    c = config
    h, hd, nh = c.hidden_size, c.head_dim, c.num_attention_heads
    L = c.num_hidden_layers
    dtype = dtype or c.param_dtype

    def get(name):
        return _t(state_dict[name])

    cols = {k: [] for k in ("wqkv", "bqkv", "ow", "ob", "ln1w", "ln1b",
                            "ln2w", "ln2b", "uw", "ub", "dw", "db")}
    for i in range(L):
        pre = f"transformer.h.{i}."
        w = get(pre + "attn.c_attn.weight")          # [h, 3h], [q|k|v]
        b = get(pre + "attn.c_attn.bias")            # [3h]
        qkv_w = np.stack([w[:, j * h:(j + 1) * h].reshape(h, nh, hd)
                          for j in range(3)], axis=2)   # [h, nh, 3, hd]
        qkv_b = np.stack([b[j * h:(j + 1) * h].reshape(nh, hd)
                          for j in range(3)], axis=1)   # [nh, 3, hd]
        cols["wqkv"].append(qkv_w)
        cols["bqkv"].append(qkv_b)
        cols["ow"].append(get(pre + "attn.c_proj.weight"))   # [h, h] in,out
        cols["ob"].append(get(pre + "attn.c_proj.bias"))
        cols["ln1w"].append(get(pre + "ln_1.weight"))
        cols["ln1b"].append(get(pre + "ln_1.bias"))
        cols["ln2w"].append(get(pre + "ln_2.weight"))
        cols["ln2b"].append(get(pre + "ln_2.bias"))
        cols["uw"].append(get(pre + "mlp.c_fc.weight"))      # [h, 4h]
        cols["ub"].append(get(pre + "mlp.c_fc.bias"))
        cols["dw"].append(get(pre + "mlp.c_proj.weight"))    # [4h, h]
        cols["db"].append(get(pre + "mlp.c_proj.bias"))

    def stack(key):
        return jnp.asarray(np.stack(cols[key]), dtype)

    blocks = {
        "ln1": {"weight": stack("ln1w"), "bias": stack("ln1b")},
        "attn": {"wqkv": stack("wqkv"), "bqkv": stack("bqkv"),
                 "o_proj": {"weight": stack("ow"), "bias": stack("ob")}},
        "ln2": {"weight": stack("ln2w"), "bias": stack("ln2b")},
        "mlp": {"w_up": stack("uw"), "b_up": stack("ub"),
                "down": {"weight": stack("dw"), "bias": stack("db")}},
    }
    params: Dict[str, Any] = {
        "model": {
            "wte": {"weight": jnp.asarray(
                get("transformer.wte.weight"), dtype)},
            "wpe": jnp.asarray(get("transformer.wpe.weight"), dtype),
            "blocks": blocks,
            "final_ln": {"weight": jnp.asarray(
                get("transformer.ln_f.weight"), dtype),
                "bias": jnp.asarray(get("transformer.ln_f.bias"), dtype)},
        }
    }
    if not c.tie_word_embeddings:
        lm = state_dict.get("lm_head.weight",
                            state_dict["transformer.wte.weight"])
        params["lm_head"] = jnp.asarray(_t(lm).T, dtype)
    return params


def export_hf_gpt2(params: Dict[str, Any],
                   config: GPTConfig) -> Dict[str, np.ndarray]:
    """Inverse mapping: hetu_tpu params -> HF state dict (numpy)."""
    c = config
    h, hd, nh = c.hidden_size, c.head_dim, c.num_attention_heads
    blocks = params["model"]["blocks"]
    out: Dict[str, np.ndarray] = {
        "transformer.wte.weight": np.asarray(
            params["model"]["wte"]["weight"], np.float32),
        "transformer.wpe.weight": np.asarray(
            params["model"]["wpe"], np.float32),
        "transformer.ln_f.weight": np.asarray(
            params["model"]["final_ln"]["weight"], np.float32),
        "transformer.ln_f.bias": np.asarray(
            params["model"]["final_ln"]["bias"], np.float32),
    }
    # materialize each stacked tensor ONCE (one device-to-host transfer
    # per tensor, not per layer — mirrors export_hf_llama)
    wqkv = np.asarray(blocks["attn"]["wqkv"], np.float32)
    bqkv = np.asarray(blocks["attn"]["bqkv"], np.float32)
    ow = np.asarray(blocks["attn"]["o_proj"]["weight"], np.float32)
    ob = np.asarray(blocks["attn"]["o_proj"]["bias"], np.float32)
    ln1w = np.asarray(blocks["ln1"]["weight"], np.float32)
    ln1b = np.asarray(blocks["ln1"]["bias"], np.float32)
    ln2w = np.asarray(blocks["ln2"]["weight"], np.float32)
    ln2b = np.asarray(blocks["ln2"]["bias"], np.float32)
    uw = np.asarray(blocks["mlp"]["w_up"], np.float32)
    ub = np.asarray(blocks["mlp"]["b_up"], np.float32)
    dw = np.asarray(blocks["mlp"]["down"]["weight"], np.float32)
    db = np.asarray(blocks["mlp"]["down"]["bias"], np.float32)
    for i in range(c.num_hidden_layers):
        pre = f"transformer.h.{i}."
        out[pre + "attn.c_attn.weight"] = np.concatenate(
            [wqkv[i][:, :, j, :].reshape(h, nh * hd) for j in range(3)],
            axis=1)
        out[pre + "attn.c_attn.bias"] = np.concatenate(
            [bqkv[i][:, j, :].reshape(nh * hd) for j in range(3)])
        out[pre + "attn.c_proj.weight"] = ow[i]
        out[pre + "attn.c_proj.bias"] = ob[i]
        out[pre + "ln_1.weight"] = ln1w[i]
        out[pre + "ln_1.bias"] = ln1b[i]
        out[pre + "ln_2.weight"] = ln2w[i]
        out[pre + "ln_2.bias"] = ln2b[i]
        out[pre + "mlp.c_fc.weight"] = uw[i]
        out[pre + "mlp.c_fc.bias"] = ub[i]
        out[pre + "mlp.c_proj.weight"] = dw[i]
        out[pre + "mlp.c_proj.bias"] = db[i]
    if "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"],
                                           np.float32).T
    return out
