"""GPT-2/3 model family.

Rebuild of the reference GPT (reference: python/hetu/models/gpt/gpt_model.py +
tests/ci_test/hetu_gpt_ds_parallel.py — the CI workload model): learned
position embeddings, pre-LN blocks, GELU MLP, MHA with biases, tied LM head
by default.  Shares the TPU-first machinery of the LLaMA family (strategy-
driven layouts, scan-over-layers + remat, flash attention, pipeline, CP).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from hetu_tpu import ops
from hetu_tpu.dstates import DistributedStates as DS
from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module, stack_param_specs
from hetu_tpu.nn.parallel import (ParallelLayerNorm, RowParallelLinear,
                                  VocabParallelEmbedding)
from hetu_tpu.parallel.strategy import ParallelStrategy


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    # the GPT family has a hetero-TP pipeline block maker too
    # (parallel/hetero_pp.py gpt_block_maker)
    supports_hetero_tp: bool = True
    tie_word_embeddings: bool = True
    initializer_range: float = 0.02
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0

    # heterogeneous pipeline stage layer counts (see LlamaConfig)
    pipeline_stage_layers: object = None

    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.bfloat16
    use_scan: bool = True
    remat: bool = True
    remat_policy: str = "nothing"
    use_flash_attention: bool = True

    def __post_init__(self):
        from hetu_tpu.nn.remat import validate_remat_policy
        validate_remat_policy(self.remat_policy)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        d = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=256)
        d.update(kw)
        return GPTConfig(**d)

    @staticmethod
    def gpt2_large(**kw) -> "GPTConfig":
        d = dict(hidden_size=1280, num_hidden_layers=36,
                 num_attention_heads=20)
        d.update(kw)
        return GPTConfig(**d)

    def num_params(self) -> int:
        h, L, v = self.hidden_size, self.num_hidden_layers, self.vocab_size
        per_layer = 4 * h * h + 2 * 4 * h * h + 9 * h + 4 * h  # qkv/o + mlp + biases/norms
        emb = v * h + self.max_position_embeddings * h
        return L * per_layer + emb + 2 * h

    def flops_per_token(self, seq_len: int) -> float:
        n = self.num_params()
        return 6.0 * n + 12 * self.num_hidden_layers * self.hidden_size * seq_len


class GPTAttention(Module):
    """MHA with biases (reference: gpt_model.py GPTAttention)."""

    def __init__(self, config: GPTConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config, self.strategy = config, strategy
        c, hd = config, config.head_dim
        self.n_heads = c.num_attention_heads
        if self.n_heads % max(strategy.tp, 1):
            raise ValueError(f"heads={self.n_heads} vs tp={strategy.tp}")
        # [h, heads, 3, hd]: per head [q|k|v] — TP splits the heads dim
        qkv_ds = strategy.fsdp(
            DS.make(4, {1: "tp"}) if strategy.tp > 1 else None, 4, 0)
        self.param("wqkv", (c.hidden_size, self.n_heads, 3, hd),
                   init.normal(c.initializer_range), dtype=c.param_dtype,
                   ds=qkv_ds)
        self.param("bqkv", (self.n_heads, 3, hd), init.zeros,
                   dtype=c.param_dtype,
                   ds=DS.make(3, {0: "tp"}) if strategy.tp > 1 else None)
        self.o_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, strategy, bias=True,
            param_dtype=c.param_dtype,
            weight_init=init.normal(c.initializer_range))

    def forward(self, params, x, *, position_ids=None, segment_ids=None,
                rng=None, deterministic=True):
        c, st = self.config, self.strategy
        b, s, h = x.shape
        hd = c.head_dim
        qkv = jnp.einsum("bsh,hngd->bsngd", x, params["wqkv"].astype(x.dtype))
        qkv = qkv + params["bqkv"].astype(x.dtype)
        qkv = st.constrain(qkv, st.act_qkv())
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        use_attn_dropout = (c.attention_dropout > 0.0 and not deterministic
                            and rng is not None)
        if st.cp > 1:
            from hetu_tpu.parallel.ring_attention import ring_attention_gspmd
            attn = ring_attention_gspmd(q, k, v, strategy=st,
                                        segment_ids=segment_ids,
                                        position_ids=position_ids)
        elif use_attn_dropout:
            attn = ops.attention(q, k, v, causal=True, segment_ids=segment_ids,
                                 dropout_rate=c.attention_dropout,
                                 dropout_rng=jax.random.fold_in(rng, 1))
        else:
            attn = ops.flash_attention(
                q, k, v, causal=True, segment_ids=segment_ids,
                use_pallas=None if c.use_flash_attention else False)
        attn = st.constrain(attn, st.act_attn())
        # named so the "dots_attn" remat policy can save the kernel output
        # (mirrors models/llama/model.py)
        from jax.ad_checkpoint import checkpoint_name
        attn = checkpoint_name(attn, "attn_out")
        return self.o_proj(params["o_proj"], attn.reshape(b, s, h))


class GPTMLP(Module):
    def __init__(self, config: GPTConfig, strategy: ParallelStrategy):
        super().__init__()
        self.strategy = strategy
        c = config
        i = c.intermediate_size
        self.param("w_up", (c.hidden_size, i),
                   init.normal(c.initializer_range), dtype=c.param_dtype,
                   ds=strategy.col_weight())
        self.param("b_up", (i,), init.zeros, dtype=c.param_dtype,
                   ds=strategy.col_bias())
        self.down = RowParallelLinear(i, c.hidden_size, strategy, bias=True,
                                      param_dtype=c.param_dtype,
                                      weight_init=init.normal(c.initializer_range))

    def forward(self, params, x):
        st = self.strategy
        y = x @ params["w_up"].astype(x.dtype) + params["b_up"].astype(x.dtype)
        y = st.constrain(y, st.act_inner())
        return self.down(params["down"], ops.gelu(y))


class GPTBlock(Module):
    def __init__(self, config: GPTConfig, strategy: ParallelStrategy):
        super().__init__()
        self.config = config
        c = config
        self.ln1 = ParallelLayerNorm(c.hidden_size, strategy,
                                     eps=c.layer_norm_eps,
                                     param_dtype=c.param_dtype)
        self.attn = GPTAttention(c, strategy)
        self.ln2 = ParallelLayerNorm(c.hidden_size, strategy,
                                     eps=c.layer_norm_eps,
                                     param_dtype=c.param_dtype)
        self.mlp = GPTMLP(c, strategy)

    def forward(self, params, x, *, position_ids=None, segment_ids=None,
                rng=None, deterministic=True):
        c = self.config
        # phase scopes for HLO/trace attribution (see LlamaBlock.forward)
        with jax.named_scope("attn"):
            h = self.attn(params["attn"], self.ln1(params["ln1"], x),
                          position_ids=position_ids, segment_ids=segment_ids,
                          rng=rng, deterministic=deterministic)
        if not deterministic and rng is not None:
            h = ops.dropout(h, c.hidden_dropout, jax.random.fold_in(rng, 2),
                            deterministic)
        with jax.named_scope("mlp"):
            # residual-add + ln2 as ONE fused Pallas pass when routed
            # (nn/parallel.ParallelLayerNorm.residual; fallback = the
            # seed composition `x = x + h; ln2(x)`)
            normed, x = self.ln2.residual(params["ln2"], x, h)
            h = self.mlp(params["mlp"], normed)
        if not deterministic and rng is not None:
            h = ops.dropout(h, c.hidden_dropout, jax.random.fold_in(rng, 3),
                            deterministic)
        return x + h


class GPTModel(Module):
    """Backbone (reference: gpt_model.py GPTModel)."""

    def __init__(self, config: GPTConfig,
                 strategy: Optional[ParallelStrategy] = None):
        super().__init__()
        strategy = strategy or ParallelStrategy()
        self.config, self.strategy = config, strategy
        c = config
        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, strategy, param_dtype=c.param_dtype,
            weight_init=init.normal(c.initializer_range))
        self.param("wpe", (c.max_position_embeddings, c.hidden_size),
                   init.normal(c.initializer_range), dtype=c.param_dtype)
        self.block = GPTBlock(c, strategy)
        self.final_ln = ParallelLayerNorm(c.hidden_size, strategy,
                                          eps=c.layer_norm_eps,
                                          param_dtype=c.param_dtype)

    def param_specs(self):
        out = dict(self._params)
        out["wte"] = self.wte.param_specs()
        out["final_ln"] = self.final_ln.param_specs()
        block_specs = self.block.param_specs()
        if self.config.use_scan:
            lead = "pp" if self.strategy.pp > 1 else None
            out["blocks"] = stack_param_specs(
                block_specs, self.config.num_hidden_layers, lead_axis=lead)
        else:
            import copy
            for i in range(self.config.num_hidden_layers):
                out[f"block_{i}"] = copy.deepcopy(block_specs)
        return out

    def forward(self, params, input_ids, *, position_ids=None,
                segment_ids=None, rng=None, deterministic=True,
                n_micro=None):
        c, st = self.config, self.strategy
        b, s = input_ids.shape
        pos = position_ids if position_ids is not None else \
            jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        with jax.named_scope("embed"):
            x = self.wte(params["wte"], input_ids)
            x = x + jnp.take(params["wpe"], pos, axis=0)
            x = x.astype(c.compute_dtype)
            x = st.constrain(x, st.act_hidden())

        use_drop = not deterministic and rng is not None
        if st.pp > 1:
            if not c.use_scan:
                raise ValueError("pipeline parallelism requires use_scan")
            from hetu_tpu.core.mesh import current_mesh
            from hetu_tpu.parallel.pipeline import staged_stack_forward
            mesh = current_mesh()
            if mesh is None:
                raise ValueError("pipeline needs a mesh (use hetu_tpu.use_mesh)")

            if st.pp_tp_eff is not None:
                # per-stage hetero TP (see LlamaModel counterpart)
                from hetu_tpu.parallel.hetero_pp import (
                    gpt_block_maker, staged_stack_forward_hetero_tp)
                if st.cp > 1 or (use_drop and c.attention_dropout > 0.0):
                    raise NotImplementedError(
                        "pp_tp_eff composes with cp=1, hidden dropout only")
                x, _aux = staged_stack_forward_hetero_tp(
                    gpt_block_maker(c, tp=st.tp,
                                    sequence_parallel=st.sequence_parallel),
                    self.block.param_specs(), params["blocks"], x,
                    num_layers=c.num_hidden_layers, pp=st.pp, tp=st.tp,
                    tp_eff=st.pp_tp_eff, mesh=mesh,
                    rng=rng if use_drop else None,
                    sequence_parallel=st.sequence_parallel,
                    position_ids=position_ids, segment_ids=segment_ids,
                    stage_layers=c.pipeline_stage_layers, n_micro=n_micro,
                    remat=c.remat, remat_policy=c.remat_policy,
                    state_spec=st.pipeline_state_spec())
                return self.final_ln(params["final_ln"], x)

            def block_fn(layer_params, x_mb, pos_mb, seg_mb, rng=None):
                with jax.named_scope("layer"):
                    out = self.block(layer_params, x_mb,
                                     position_ids=pos_mb,
                                     segment_ids=seg_mb, rng=rng,
                                     deterministic=rng is None)
                return out, jnp.zeros((), jnp.float32)

            x, _aux = staged_stack_forward(
                block_fn, params["blocks"], x,
                num_layers=c.num_hidden_layers, pp=st.pp, mesh=mesh,
                position_ids=position_ids, segment_ids=segment_ids,
                stage_layers=c.pipeline_stage_layers,
                n_micro=n_micro, remat=c.remat, remat_policy=c.remat_policy,
                state_spec=st.pipeline_state_spec(),
                rng=rng if use_drop else None,
                # see llama._pipeline_forward: cp ring ppermute is not
                # branch-safe, so hetero-exec stays off under cp>1
                hetero_exec="auto" if st.cp == 1 else False)
            return self.final_ln(params["final_ln"], x)
        layer_rngs = (jax.random.split(rng, c.num_hidden_layers)
                      if use_drop else None)
        if c.use_scan:
            def body(carry, xs):
                layer_params, layer_rng = xs
                # "layer" scope: per-layer HLO attribution of the
                # scanned stack (obs.hlo_profile; see llama counterpart)
                with jax.named_scope("layer"):
                    return self.block(layer_params, carry,
                                      position_ids=position_ids,
                                      segment_ids=segment_ids,
                                      rng=layer_rng if use_drop else None,
                                      deterministic=deterministic), None
            fn = body
            if c.remat:
                from hetu_tpu.nn.remat import remat_policy
                fn = jax.checkpoint(body, policy=remat_policy(c.remat_policy))
            xs = (params["blocks"],
                  layer_rngs if use_drop else
                  jnp.zeros((c.num_hidden_layers,), jnp.uint32))
            x, _ = lax.scan(fn, x, xs)
        else:
            from hetu_tpu.nn.remat import remat_policy
            for i in range(c.num_hidden_layers):
                def blk(p, y, i=i):
                    with jax.named_scope(f"layer_{i}"):
                        return self.block(
                            p, y, position_ids=position_ids,
                            segment_ids=segment_ids,
                            rng=layer_rngs[i] if use_drop else None,
                            deterministic=deterministic)
                if c.remat:
                    blk = jax.checkpoint(blk,
                                         policy=remat_policy(c.remat_policy))
                x = blk(params[f"block_{i}"], x)
        return self.final_ln(params["final_ln"], x)


class GPTLMHeadModel(Module):
    """LM head (tied by default — reference GPTLMHeadModel)."""

    def __init__(self, config: GPTConfig,
                 strategy: Optional[ParallelStrategy] = None):
        super().__init__()
        strategy = strategy or ParallelStrategy()
        self.config, self.strategy = config, strategy
        self.model = GPTModel(config, strategy)
        if not config.tie_word_embeddings:
            if strategy.tp > 1 and config.vocab_size % strategy.tp:
                raise ValueError(
                    f"vocab size {config.vocab_size} must divide by tp="
                    f"{strategy.tp}; pad the vocab (e.g. 50257 -> 50304)")
            lm_ds = strategy.fsdp(
                DS.make(2, {1: "tp"}) if strategy.tp > 1 else None, 2, 0)
            self.param("lm_head", (config.hidden_size, config.vocab_size),
                       init.normal(config.initializer_range),
                       dtype=config.param_dtype, ds=lm_ds)

    def logits(self, params, hidden):
        """hidden -> logits via the tied/untied head (one implementation
        for the training forward AND the generation decode paths)."""
        with jax.named_scope("lm_head"):
            if self.config.tie_word_embeddings:
                w = params["model"]["wte"]["weight"].astype(hidden.dtype).T
            else:
                w = params["lm_head"].astype(hidden.dtype)
            return self.strategy.constrain(hidden @ w,
                                           self.strategy.act_logits())

    def forward(self, params, input_ids, labels=None, *, position_ids=None,
                segment_ids=None, loss_reduction: str = "mean", rng=None,
                deterministic=True, n_micro=None,
                include_aux_loss: bool = True, labels_shifted: bool = False):
        # include_aux_loss: accepted for API uniformity with the MoE-capable
        # LLaMA family; GPT has no router losses so it is a no-op
        hidden = self.model(params["model"], input_ids,
                            position_ids=position_ids,
                            segment_ids=segment_ids, rng=rng,
                            deterministic=deterministic, n_micro=n_micro)
        logits = self.logits(params, hidden)
        if labels is None:
            return logits
        # labels_shifted: host pre-shifted targets (CP seq reorder) — see
        # LlamaLMHeadModel.forward
        if labels_shifted:
            lg, tgt = logits, labels
        else:
            lg, tgt = logits[:, :-1, :], labels[:, 1:]
        if loss_reduction not in ("mean", "sum"):
            raise ValueError(f"loss_reduction must be 'mean' or 'sum', got "
                             f"{loss_reduction!r}")
        if loss_reduction == "sum":
            loss = ops.softmax_cross_entropy_sparse(
                lg, tgt, ignore_index=-100, reduction="sum")
            count = jnp.sum((tgt != -100).astype(jnp.float32))
            return loss, count
        return ops.softmax_cross_entropy_sparse(
            lg, tgt, ignore_index=-100)

    # ------------------------------------------------------------------
    def pipeline_train_grads(self, params, input_ids, labels, *,
                             position_ids=None, segment_ids=None,
                             n_micro: int, labels_shifted: bool = False,
                             loss_scale=1.0, skip_dead_halves="auto",
                             rng=None):
        """1F1B (PipeDream-flush) training pass for the GPT family —
        ((loss_sum, count), grads); mirrors LlamaLMHeadModel
        .pipeline_train_grads (reference: executable_graph.cc:836).
        wte+wpe run inside stage 0, final_ln + (tied) head + CE inside the
        last stage; O(pp) activation ring buffer."""
        from hetu_tpu.core.mesh import current_mesh
        from hetu_tpu.nn.remat import remat_policy
        from hetu_tpu.parallel.pipeline import (
            build_stage_stack, unstack_stage_grads)
        from hetu_tpu.parallel.pipeline_1f1b import pipeline_train_1f1b

        c, st = self.config, self.strategy
        if st.pp <= 1:
            raise ValueError("pipeline_train_grads requires pp > 1")
        if st.pp_tp_eff is not None and (
                st.cp > 1 or (rng is not None and c.attention_dropout > 0.0)):
            raise NotImplementedError(
                "pp_tp_eff under 1f1b composes with cp=1, hidden dropout "
                "only (same envelope as the GPipe hetero path)")
        if not c.use_scan:
            raise ValueError("1f1b requires use_scan")
        mesh = current_mesh()
        if mesh is None:
            raise ValueError("pipeline needs a mesh (use hetu_tpu.use_mesh)")

        stack = params["model"]["blocks"]
        sp, layer_mask, stage_layers = build_stage_stack(
            stack, c.num_hidden_layers, st.pp, c.pipeline_stage_layers)
        ep = {"wte": params["model"]["wte"],
              "wpe": params["model"]["wpe"],
              "final_ln": params["model"]["final_ln"]}
        if not c.tie_word_embeddings:
            ep["lm_head"] = params["lm_head"]
        count = jnp.sum(((labels if labels_shifted else labels[:, 1:])
                         != -100).astype(jnp.float32))

        use_drop = rng is not None and (c.hidden_dropout > 0.0
                                        or c.attention_dropout > 0.0)

        def stage_scan(sp_slice, x0, pos, seg, mask_row, drop_seed, offset):
            def body(carry, xs):
                lp, mj = xs if mask_row is not None else (xs, None)
                x_c, gid = carry
                layer_rng = None
                if use_drop:
                    # masks replay exactly in the backward visit: the seed
                    # rides the saved token stream, the id is the stage
                    # offset + local layer index (see llama counterpart)
                    layer_rng = jax.random.fold_in(
                        jax.random.key(drop_seed), gid)
                out = self.model.block(lp, x_c, position_ids=pos,
                                       segment_ids=seg, rng=layer_rng,
                                       deterministic=not use_drop)
                if mj is not None:
                    out = jnp.where(mj > 0, out, x_c)
                return (out, gid + 1), None

            fn = body
            if c.remat:
                fn = jax.checkpoint(body, policy=remat_policy(c.remat_policy))
            xs = sp_slice if mask_row is None else (sp_slice, mask_row)
            from hetu_tpu.core.vma import cast_varying, vma_of
            gid0 = (offset if offset is not None
                    else cast_varying(jnp.zeros((), jnp.uint32),
                                      tuple(vma_of(x0))))
            (y, _), _ = lax.scan(fn, (x0, gid0), xs)
            return y

        def head_loss(ep_, y, lab):
            hidden = self.model.final_ln(ep_["final_ln"], y)
            if c.tie_word_embeddings:
                w = ep_["wte"]["weight"].astype(hidden.dtype).T
            else:
                w = ep_["lm_head"].astype(hidden.dtype)
            logits = hidden @ w
            if labels_shifted:
                lg, tgt = logits, lab
            else:
                lg, tgt = logits[:, :-1, :], lab[:, 1:]
            return ops.softmax_cross_entropy_sparse(
                lg, tgt, ignore_index=-100, reduction="sum")

        def embed_micro(ep_, ids, pos_row):
            """wte + wpe + cast + constrain for one [mb, s] micro — ONE
            implementation for the homogeneous stage_fn AND the hetero-TP
            round bodies (which differ only in position-row indexing)."""
            pos_eff = pos_row if pos_row is not None else jnp.broadcast_to(
                jnp.arange(ids.shape[1], dtype=jnp.int32), ids.shape)
            emb = self.model.wte(ep_["wte"], ids) \
                + jnp.take(ep_["wpe"], pos_eff, axis=0)
            return st.constrain(emb.astype(c.compute_dtype),
                                st.act_hidden())

        def stage_fn(sp_slice, ep_, x_in, feed_b, feed_s, flg):
            ids = feed_b["ids"]
            pos = feed_s.get("position_ids")
            emb = embed_micro(ep_, ids, pos)
            x0 = jnp.where(flg["is_first"] > 0, emb, x_in)
            drop = feed_s.get("dropout_rng")
            y = stage_scan(sp_slice, x0, pos, feed_s.get("segment_ids"),
                           flg.get("layer_mask"),
                           drop[0, 0] if drop is not None else None,
                           flg.get("stage_offset"))
            ce = head_loss(ep_, y, feed_b["labels"]) * flg["is_last"]
            return y, ce, jnp.zeros((), jnp.float32)

        ride = {}
        if position_ids is not None:
            ride["position_ids"] = position_ids
        if segment_ids is not None:
            ride["segment_ids"] = segment_ids
        flags_extra = {}
        if layer_mask is not None:
            flags_extra["layer_mask"] = layer_mask
        if use_drop:
            from hetu_tpu.parallel.pipeline_1f1b import build_dropout_ride
            ride["dropout_rng"], flags_extra["stage_offset"] = \
                build_dropout_ride(rng, n_micro, input_ids.shape,
                                   stage_layers)

        custom = None
        if st.pp_tp_eff is not None:
            # per-stage hetero TP round bodies (see llama counterpart)
            from hetu_tpu.parallel.hetero_pp import (
                gpt_block_maker, hetero_tp_1f1b_rounds)

            def embed_fn(ep_, feed_b, feed_s):
                pos = feed_s.get("position_ids")
                # riders carry a leading pp dim here: stage 0's row
                return embed_micro(ep_, feed_b["ids"],
                                   pos[0] if pos is not None else None)

            custom = hetero_tp_1f1b_rounds(
                gpt_block_maker(c, tp=st.tp,
                                sequence_parallel=st.sequence_parallel),
                self.model.block.param_specs(), embed_fn, head_loss,
                mesh=mesh, pp=st.pp, tp=st.tp, tp_eff=st.pp_tp_eff,
                stage_layers=stage_layers, remat=c.remat,
                remat_policy=c.remat_policy, compute_dtype=c.compute_dtype,
                token_keys=tuple(ride.keys()),
                sequence_parallel=st.sequence_parallel)

        ce_sum, _aux, d_stage, d_edge = pipeline_train_1f1b(
            stage_fn, sp, ep, input_ids, labels, ride,
            n_micro=n_micro, mesh=mesh, hidden_size=c.hidden_size,
            compute_dtype=c.compute_dtype, aux_seed=0.0,
            state_spec=st.pipeline_state_spec(), loss_scale=loss_scale,
            skip_dead_halves=skip_dead_halves,
            flags_extra=flags_extra or None, custom_rounds=custom)

        d_blocks = unstack_stage_grads(
            d_stage, c.num_hidden_layers, st.pp, stage_layers)
        grads = {"model": {"wte": d_edge["wte"], "wpe": d_edge["wpe"],
                           "blocks": d_blocks,
                           "final_ln": d_edge["final_ln"]}}
        if not c.tie_word_embeddings:
            grads["lm_head"] = d_edge["lm_head"]
        return (ce_sum, count), grads
