from hetu_tpu.models.gpt.model import GPTConfig, GPTModel, GPTLMHeadModel
from hetu_tpu.models.gpt import convert  # noqa: F401  (HF interop)
