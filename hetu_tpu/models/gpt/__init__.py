from hetu_tpu.models.gpt.model import GPTConfig, GPTModel, GPTLMHeadModel
