from hetu_tpu.optim.optimizer import Optimizer, AdamW, Adam, SGD, clip_by_global_norm
from hetu_tpu.optim.grad_scaler import GradScaler
