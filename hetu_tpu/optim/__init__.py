from hetu_tpu.optim.optimizer import (
    Optimizer, AdamW, Adam, SGD, clip_by_global_norm, zero_shardings,
    cosine_schedule, constant_schedule,
)
from hetu_tpu.optim.grad_scaler import GradScaler
from hetu_tpu.optim.zero_refresh import (
    quantized_zero_update, refresh_dims, refresh_specs,
)
