"""Optimizers (reference: hetu/graph/optim/optimizer.h:13-159 SGD/Adam +
ops/optimizer_update.h fused update ops).

Functional: `opt.init(params)` -> state pytree, `opt.update(grads, state,
params)` -> (new_params, new_state).  The update math runs in float32 on the
float32 master params regardless of compute dtype (AMP), matching the
reference's fused Adam (hetu/impl/kernel/Optimizers.cu).

ZeRO-1 (optimizer-state sharding over dp, reference: distributed_states.h:15
`zero` + the OPTIMIZE_COMPUTE_BRIDGE subgraphs) is expressed through shardings:
`zero_shardings()` returns NamedShardings that additionally shard every state
leaf (and master param copy) over the dp axis; GSPMD then turns the grad
all-reduce into reduce-scatter + the param refresh into all-gather — the same
comm pattern the reference builds explicitly with Split* collectives.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def clip_by_global_norm(grads, max_norm: float):
    """Global-norm clip (used by the trainer; reference clips via
    GradScaler/CheckFinite pipeline).

    The per-leaf squared sums are stacked and reduced with ONE jnp.sum —
    a python `sum(...)` over the leaf scalars lowers to a serial chain of
    O(n_leaves) scalar adds in HLO (each dependent on the last), which on
    a scan-free 100+-leaf model is a visible critical path; the stacked
    reduction is a single tree-reduce."""
    leaves = jax.tree.leaves(grads)
    sq = jnp.stack([jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in leaves])
    gnorm = jnp.sqrt(jnp.sum(sq))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gnorm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads), gnorm


class Optimizer:
    def init(self, params):
        raise NotImplementedError

    def update(self, grads, state, params):
        raise NotImplementedError


@dataclasses.dataclass
class SGD(Optimizer):
    lr: float = 0.01
    momentum: float = 0.0
    weight_decay: float = 0.0

    def init(self, params):
        if self.momentum == 0.0:
            return {"step": jnp.zeros((), jnp.int32)}
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def update(self, grads, state, params):
        step = state["step"] + 1

        def upd(p, g, v=None):
            g = g.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p.astype(jnp.float32)
            if v is not None:
                v = self.momentum * v + g
                g = v
            newp = p.astype(jnp.float32) - self.lr * g
            return newp.astype(p.dtype), v

        if self.momentum == 0.0:
            new_params = jax.tree.map(lambda p, g: upd(p, g)[0], params, grads)
            return new_params, {"step": step}
        out = jax.tree.map(lambda p, g, v: upd(p, g, v), params, grads, state["velocity"])
        new_params = jax.tree.map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_vel = jax.tree.map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": step, "velocity": new_vel}


@dataclasses.dataclass
class AdamW(Optimizer):
    """AdamW with bias correction (reference MakeAdamOp semantics,
    ops/optimizer_update.h:207 + Optimizers.cu fused kernel)."""

    lr: float | Callable[[jnp.ndarray], jnp.ndarray] = 1e-3
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.0

    def init(self, params):
        zeros_like = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(zeros_like, params),
            "v": jax.tree.map(zeros_like, params),
        }

    def _lr(self, step):
        return self.lr(step) if callable(self.lr) else self.lr

    def update(self, grads, state, params):
        step = state["step"] + 1
        lr = self._lr(step)
        c1 = 1.0 - self.b1 ** step.astype(jnp.float32)
        c2 = 1.0 - self.b2 ** step.astype(jnp.float32)
        from hetu_tpu.ops import pallas as _pl
        from hetu_tpu.ops.pallas import adam as _padam

        def upd(p, g, m, v):
            # fused Adam kernel (ops/pallas/adam.py): one read of
            # p/g/m/v, one write of p'/m'/v' per lane-aligned leaf;
            # ragged leaves (biases, gains) keep the XLA chain below
            if _pl.resolve_route("adam", _padam.compatible(p.shape)):
                with jax.named_scope("pallas_adam"):
                    return _padam.adam_update(
                        p, g, m, v, lr, c1, c2, b1=self.b1, b2=self.b2,
                        eps=self.eps, weight_decay=self.weight_decay)
            g = g.astype(jnp.float32)
            m = self.b1 * m + (1.0 - self.b1) * g
            v = self.b2 * v + (1.0 - self.b2) * jnp.square(g)
            mhat = m / c1
            vhat = v / c2
            pf = p.astype(jnp.float32)
            newp = pf - lr * (mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * pf)
            return newp.astype(p.dtype), m, v

        triples = jax.tree.map(upd, params, grads, state["m"], state["v"])
        is_t = lambda t: isinstance(t, tuple)
        new_params = jax.tree.map(lambda t: t[0], triples, is_leaf=is_t)
        new_m = jax.tree.map(lambda t: t[1], triples, is_leaf=is_t)
        new_v = jax.tree.map(lambda t: t[2], triples, is_leaf=is_t)
        from hetu_tpu.obs import numerics as _numerics
        if _numerics.active():
            # numerics observatory (HETU_TPU_NUMERICS): watch the update
            # magnitude (lr-scale — where int8 delta-gather error lives)
            # and the first moment.  Only traced when a collector is on.
            deltas = jax.tree.map(
                lambda n, p: n.astype(jnp.float32) - p.astype(jnp.float32),
                new_params, params)
            _numerics.tap_tree("update", deltas)
            _numerics.tap_tree("adam_m", new_m)
        return new_params, {"step": step, "m": new_m, "v": new_v}


def Adam(lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    return AdamW(lr=lr, b1=b1, b2=b2, eps=eps, weight_decay=0.0)


# ---------------------------------------------------------------------------
# ZeRO-1 sharding helpers
# ---------------------------------------------------------------------------

def zero_shardings(param_shardings, abstract_params, mesh, axis: str = "dp"):
    """Derive optimizer-state shardings: each state leaf inherits its param's
    sharding plus an extra split of the first free, divisible dim over `axis`
    (ZeRO-1; the comm consequences — reduce-scatter of grads, all-gather of
    fresh params — are inserted by GSPMD).  Scalars and indivisible params
    stay replicated.

    `abstract_params` supplies shapes (params or ShapeDtypeStructs) since a
    NamedSharding's spec alone does not know the tensor rank.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    size = mesh.shape.get(axis, 1)
    if size <= 1:
        return param_shardings

    def shard_one(ns, ref):
        shape = ref.shape
        spec = list(ns.spec) + [None] * (len(shape) - len(ns.spec))
        flat = [a for s in spec if s is not None
                for a in ((s,) if isinstance(s, str) else s)]
        if axis in flat:
            return ns  # already sharded over this axis (e.g. FSDP weights)
        for i in range(len(shape)):
            if spec[i] is None and shape[i] % size == 0 and shape[i] >= size:
                spec[i] = axis
                return NamedSharding(mesh, P(*spec))
        return ns

    return jax.tree.map(shard_one, param_shardings, abstract_params)


# ---------------------------------------------------------------------------
# compressed-grad-sync error-feedback state (HETU_TPU_GRAD_COMPRESS=int8-ef)
# ---------------------------------------------------------------------------

def ef_state_entry(bucket_plan, mesh, dp: int, axis: str = "dp",
                   topology=None):
    """(initial EF residuals, their shardings) for the optimizer-state
    pytree's "ef" entry — the quantized DP sync's error-feedback memory
    (comm/grad_sync.py) rides in the SAME state dict as Adam's moments so
    it checkpoints, donates and reshards with them.  Residual layout:
    per-replica [dp, L] (split over dp) + per-shard [L] (split over dp)
    per bucket; a routing two-level `topology` adds the hierarchical
    schedule's two chunk-sized per-replica residuals."""
    from hetu_tpu.comm.grad_sync import ef_init, ef_shardings
    shardings = ef_shardings(bucket_plan, mesh, axis, topology)
    state = jax.jit(lambda: ef_init(bucket_plan, dp, topology),
                    out_shardings=shardings)()
    return state, shardings


# ---------------------------------------------------------------------------
# LR schedules (reference trainer passes scalar lr; schedules are the TPU-side
# convenience so the jitted update closes over a step->lr function)
# ---------------------------------------------------------------------------

def cosine_schedule(peak_lr: float, warmup_steps: int, total_steps: int,
                    min_ratio: float = 0.1):
    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak_lr * step / max(warmup_steps, 1)
        prog = jnp.clip((step - warmup_steps) /
                        max(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = peak_lr * (min_ratio + (1 - min_ratio) * 0.5 *
                         (1.0 + jnp.cos(jnp.pi * prog)))
        return jnp.where(step < warmup_steps, warm, cos)

    return lr


def constant_schedule(lr_value: float):
    def lr(step):
        return jnp.full((), lr_value, jnp.float32)
    return lr
