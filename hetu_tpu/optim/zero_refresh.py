"""Quantized ZeRO-1/2 parameter refresh (HETU_TPU_ZERO_COMPRESS).

Under ZeRO the optimizer state is dp-sharded (`optim.zero_shardings`)
and GSPMD's lowering of the update ends in an **f32 all-gather of the
fresh parameters** — the param-refresh bytes ROADMAP item 3 names as
still-uncompressed.  This module replaces that implicit gather with an
explicit one that ships the parameter **delta** quantized:

    shard_map over dp:
      slice params + grads to my opt-state shard      (local, no comm)
      run the optimizer update on the shard           (exact, f32)
      delta = new_shard - old_shard                   (lr-magnitude values)
      all-gather delta as blockwise int8/int4 + f32 scales
      params += dequantized delta                     (replicated again)

Gathering the DELTA instead of the parameters is the load-bearing
choice: updates are lr-scale, so the absmax/qmax quantization error is
relative to the *step*, not the weight — a naive quantized-params gather
would freeze weights whose per-step movement is smaller than their int8
grid step (absmax/127 of the weight).  Every rank applies the SAME
dequantized delta (its own shard included), so replicas stay bitwise
identical and no master-state divergence can accumulate across ranks.

Envelope: the same homogeneous DP one as the compressed grad sync
(dp > 1, tp = cp = pp = ep = 1, zero_stage 1-2) — `Trainer` enforces it
loudly.  Refresh bytes drop 4/(1+4/B) ~ 3.94x (int8) or ~7.76x (int4)
vs the f32 param all-gather (comm/wire.py), verified from lowered HLO by
the obs.comm analyzer.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from hetu_tpu.comm.collectives import all_gather_q
from hetu_tpu.comm.wire import DEFAULT_BLOCK

#: leaf marker for "this leaf's opt state is not dp-sharded"
UNSHARDED = -1


def refresh_dims(opt_shardings, axis: str = "dp"):
    """Per-leaf index of the dim `zero_shardings` split over `axis`
    (UNSHARDED when the leaf stayed replicated) — the static slicing
    plan of the quantized refresh."""
    def one(ns):
        for d, entry in enumerate(ns.spec):
            axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
            if axis in axes:
                return d
        return UNSHARDED
    return jax.tree.map(one, opt_shardings)


def refresh_specs(opt_shardings):
    """Per-leaf PartitionSpecs of the dp-sharded opt state (shard_map
    in/out specs for the m/v trees)."""
    return jax.tree.map(lambda ns: ns.spec, opt_shardings)


def quantized_zero_update(optimizer, grads, opt_state, params, *, mesh,
                          dims, specs, mode: str,
                          block_size: int = DEFAULT_BLOCK,
                          axis: str = "dp", grads_sharded: bool = False):
    """Drop-in for `optimizer.update(grads, opt_state, params)` under the
    quantized ZeRO refresh: returns (new_params replicated, new opt state
    still dp-sharded).  `dims`/`specs` from `refresh_dims`/`refresh_specs`
    of the m-tree shardings; `grads_sharded=True` when the caller already
    constrained grads to the opt-state sharding (ZeRO-2)."""
    from jax.experimental.shard_map import shard_map

    if not {"step", "m", "v"} <= set(opt_state):
        # the body threads the AdamW slot layout explicitly; a different
        # optimizer's slots would be silently dropped — refuse instead
        raise ValueError(
            "quantized_zero_update supports the AdamW optimizer-state "
            "layout {step, m, v}; got "
            f"{sorted(opt_state)} — extend the body's slot threading "
            "before enabling HETU_TPU_ZERO_COMPRESS with this optimizer")
    dp = int(mesh.shape[axis])
    from hetu_tpu.obs import numerics as _numerics

    def body(params, grads, m, v, step):
        i = lax.axis_index(axis)

        def shard(x, d):
            if d == UNSHARDED:
                return x
            size = x.shape[d] // dp
            return lax.dynamic_slice_in_dim(x, i * size, size, axis=d)

        with _numerics.frame() as nf:
            p_sh = jax.tree.map(shard, params, dims)
            g_sh = (grads if grads_sharded
                    else jax.tree.map(shard, grads, dims))
            new_p_sh, new_state = optimizer.update(
                g_sh, {"step": step, "m": m, "v": v}, p_sh)

            def refresh(p_full, p_s, np_s, d):
                if d == UNSHARDED:
                    return np_s  # updated exactly, replicated
                delta = (np_s.astype(jnp.float32)
                         - p_s.astype(jnp.float32))
                dfull = all_gather_q(delta, axis, axis=d, tiled=True,
                                     mode=mode, block_size=block_size)
                if _numerics.active():
                    # exact delta-gather quantization error: my shard's
                    # reconstruction is my slice of the gathered full
                    size = delta.shape[d]
                    mine = lax.dynamic_slice_in_dim(
                        dfull, i * size, size, axis=d)
                    _numerics.tap_quant_error("zero_refresh", delta,
                                              delta - mine)
                return (p_full.astype(jnp.float32)
                        + dfull).astype(p_full.dtype)

            new_params = jax.tree.map(refresh, params, p_sh, new_p_sh,
                                      dims)
        nstats = _numerics.reduce_axis(nf.stats, axis)
        return (new_params, new_state["m"], new_state["v"],
                new_state["step"], nstats)

    gspec: Any = specs if grads_sharded else P()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), gspec, specs, specs, P()),
        out_specs=(P(), specs, specs, P(), P()),
        # the gathered params ARE replicated over dp but the checker
        # cannot infer that through the quantized gather
        check_rep=False)
    new_params, new_m, new_v, new_step, nstats = fn(
        params, grads, opt_state["m"], opt_state["v"], opt_state["step"])
    # stats folded across dp inside the body are step-level values here:
    # hand them back to the ambient collector (no-op when inactive)
    _numerics.merge(nstats)
    return new_params, {"step": new_step, "m": new_m, "v": new_v}
