"""Dynamic loss scaling (reference: hetu/graph/autocast/gradscaler.h:33 +
ops/CheckFinite.cc, ops/update_scale.cc).

Only needed for float16 compute; bfloat16 (the TPU default) has fp32's range
so the trainer leaves this off unless compute_dtype == float16 — kept for
parity with the reference's AMP surface.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class GradScaler:
    init_scale: float = 2.0 ** 16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000

    def init(self):
        return {
            "scale": jnp.asarray(self.init_scale, jnp.float32),
            "growth_tracker": jnp.zeros((), jnp.int32),
        }

    def scale_loss(self, loss, state):
        # promote to fp32 before scaling: 2**16 overflows float16's max
        return loss.astype(jnp.float32) * state["scale"]

    @staticmethod
    def all_finite(grads) -> jnp.ndarray:
        """CheckFinite analog — ONE definition of grad finiteness (the
        trainer's skip-update predicate uses this too)."""
        return jnp.all(jnp.stack([
            jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)
        ]))

    def unscale_and_check(self, grads, state) -> Tuple[Any, jnp.ndarray]:
        """Unscale grads; return (grads, all_finite)."""
        inv = 1.0 / state["scale"]
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
        return grads, self.all_finite(grads)

    def update(self, state, all_finite):
        """update_scale op: grow on streaks of finite steps, back off on inf."""
        tracker = jnp.where(all_finite, state["growth_tracker"] + 1, 0)
        grow = tracker >= self.growth_interval
        scale = jnp.where(
            all_finite,
            jnp.where(grow, state["scale"] * self.growth_factor, state["scale"]),
            state["scale"] * self.backoff_factor,
        )
        tracker = jnp.where(grow, 0, tracker)
        return {"scale": scale, "growth_tracker": tracker}


def classify_transition(prev: float | None, new: float) -> str | None:
    """Host-side loss-scale transition classifier: the trainer compares
    each step's fetched scale against the previous one and emits ONE
    ``scaler`` RunLog event per transition (docs/observability.md) —
    'growth' (a finished growth-interval streak), 'backoff' (a
    non-finite step halved the scale), or None (unchanged / first
    observation).  One definition so the trainer and its regression
    test cannot disagree on what counts as a transition."""
    if prev is None or new == prev:
        return None
    return "growth" if new > prev else "backoff"
