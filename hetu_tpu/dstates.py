"""DistributedStates: the distributed-layout algebra.

This is the TPU-native rebuild of the reference's central abstraction
(reference: hetu/graph/distributed_states.h:13-138): a tensor layout described
as a map {tensor dim -> shard factor} with dim -1 = replicate and dim -2 =
partial(pending-reduce), plus an `order` vector tying state dims to device-group
positions.

On TPU the device-group + order pair is subsumed by a named `jax.sharding.Mesh`:
a layout here is *per-tensor-dim tuples of mesh axis names* (exactly the
information in a `PartitionSpec`) **plus** an explicit set of mesh axes over
which the value is a partial sum.  GSPMD has no user-visible "partial", so we
keep partial in our layer (as the reference keeps dim -2) and emit the correct
collective — psum vs psum_scatter vs all_gather — at conversion points, the
way the reference lowers CommOp via get_comm_type
(reference: hetu/graph/ops/Communication.cc get_comm_type +
hetu/graph/executable_graph.cc:366 SubstituteCommOp).

Two execution contexts consume this algebra:
  * GSPMD context (inside jit):   `named_sharding()` / `constrain()` — XLA
    inserts the collectives.
  * Explicit context (inside shard_map): `convert()` — we emit
    psum / all_gather / psum_scatter / all_to_all / slice ourselves; used by
    ring attention, pipeline, MoE dispatch, and the hot-switch engine.
"""
from __future__ import annotations

import contextlib
import dataclasses
import enum
import threading
from typing import Dict, FrozenSet, Optional, Sequence, Tuple, Union

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_constrain_local = threading.local()


@contextlib.contextmanager
def suppress_constraints():
    """Trace-scope context: `DS.constrain` becomes the identity.

    GSPMD sharding constraints are illegal inside a fully-manual shard_map
    region (every mesh axis is already manual), and semantically vacuous
    there — per-device values are local by construction.  The compressed
    grad-sync path (engine/trainer.py _compressed_grads) traces the model
    inside such a region and wraps the trace in this context."""
    prev = getattr(_constrain_local, "off", False)
    _constrain_local.off = True
    try:
        yield
    finally:
        _constrain_local.off = prev

AxisName = str
DimSpec = Tuple[AxisName, ...]  # mesh axes sharding one tensor dim (outer→inner)


def _norm_dimspec(s) -> DimSpec:
    if s is None:
        return ()
    if isinstance(s, str):
        return (s,)
    return tuple(s)


@dataclasses.dataclass(frozen=True)
class DistributedStates:
    """A distributed tensor layout over a named mesh.

    spec[d]  = mesh axes sharding tensor dim d (empty tuple = not sharded).
    partial  = mesh axes over which the value is an unreduced partial sum
               (the reference's dim -2 state, distributed_states.h:133).
    Axes appearing in neither are replicated (the reference's dim -1 "dup").
    """

    spec: Tuple[DimSpec, ...]
    partial: FrozenSet[AxisName] = frozenset()

    # -- constructors -------------------------------------------------------
    @staticmethod
    def make(ndim: int, splits: Optional[Dict[int, Union[str, Sequence[str]]]] = None,
             partial: Sequence[str] = ()) -> "DistributedStates":
        spec = [()] * ndim
        for d, axes in (splits or {}).items():
            if d < 0:
                d += ndim
            spec[d] = _norm_dimspec(axes)
        return DistributedStates(tuple(spec), frozenset(partial))

    @staticmethod
    def dup(ndim: int) -> "DistributedStates":
        return DistributedStates(tuple(() for _ in range(ndim)))

    @staticmethod
    def from_pspec(pspec: P, ndim: Optional[int] = None) -> "DistributedStates":
        dims = [_norm_dimspec(s) for s in tuple(pspec)]
        if ndim is not None:
            dims += [()] * (ndim - len(dims))
        return DistributedStates(tuple(dims))

    # -- basic properties ---------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.spec)

    def sharded_axes(self) -> FrozenSet[AxisName]:
        return frozenset(a for dim in self.spec for a in dim)

    def used_axes(self) -> FrozenSet[AxisName]:
        return self.sharded_axes() | self.partial

    def dim_of(self, axis: AxisName) -> Optional[int]:
        """Tensor dim sharded by `axis`, or None (replicated/partial)."""
        for d, axes in enumerate(self.spec):
            if axis in axes:
                return d
        return None

    def num_shards(self, dim: int, mesh: Mesh) -> int:
        return int(
            _prod(int(mesh.shape.get(a, 1)) for a in self.spec[dim])
        )

    def is_resolved(self) -> bool:
        return not self.partial

    def validate(self):
        seen = set()
        for axes in self.spec:
            for a in axes:
                if a in seen:
                    raise ValueError(f"mesh axis {a!r} shards two tensor dims: {self}")
                seen.add(a)
        if seen & self.partial:
            raise ValueError(f"axes {seen & self.partial} both shard and partial: {self}")
        return self

    # -- derivations (the reference's combine/reduce state transitions) -----
    def with_split(self, dim: int, axis: Union[str, Sequence[str]]) -> "DistributedStates":
        if dim < 0:
            dim += self.ndim
        spec = list(self.spec)
        spec[dim] = spec[dim] + _norm_dimspec(axis)
        return dataclasses.replace(self, spec=tuple(spec)).validate()

    def without_split(self, dim: int) -> "DistributedStates":
        if dim < 0:
            dim += self.ndim
        spec = list(self.spec)
        spec[dim] = ()
        return dataclasses.replace(self, spec=tuple(spec))

    def without_axis(self, axis: AxisName) -> "DistributedStates":
        spec = tuple(tuple(a for a in axes if a != axis) for axes in self.spec)
        return dataclasses.replace(self, spec=spec)

    def with_partial(self, axes: Union[str, Sequence[str]]) -> "DistributedStates":
        return dataclasses.replace(
            self, partial=self.partial | set(_norm_dimspec(axes))
        ).validate()

    def reduced(self) -> "DistributedStates":
        """Layout after the pending partial sum is reduced (psum)."""
        return dataclasses.replace(self, partial=frozenset())

    def shifted(self, n: int = 1, lead: Tuple[DimSpec, ...] = ((),)) -> "DistributedStates":
        """Layout with `n` new leading dims prepended (for stacked/scanned
        params: per-layer weights gain a leading layer dim)."""
        assert len(lead) == n
        return dataclasses.replace(self, spec=tuple(lead) + self.spec)

    # -- emission to JAX ----------------------------------------------------
    def partition_spec(self) -> P:
        # single-axis dims emit the bare name: older jax compares
        # P(("dp",)) != P("dp") (newer releases normalize the 1-tuple)
        return P(*[(axes[0] if len(axes) == 1 else axes) if axes else None
                   for axes in self.spec])

    def named_sharding(self, mesh: Mesh) -> NamedSharding:
        if self.partial:
            raise ValueError(
                f"cannot emit NamedSharding for partial layout {self}; "
                "reduce first (insert a comm op)"
            )
        return NamedSharding(mesh, self.partition_spec())

    def constrain(self, x, mesh: Optional[Mesh] = None):
        """GSPMD context: annotate `x` with this layout (partial must be resolved).
        A fully-unsharded layout is a no-op so single-device code never needs a
        mesh in context."""
        if self.partial:
            raise ValueError(f"cannot constrain to partial layout {self}")
        if not self.sharded_axes():
            return x
        if getattr(_constrain_local, "off", False):
            return x  # inside a fully-manual region (suppress_constraints)
        if mesh is not None:
            return lax.with_sharding_constraint(x, self.named_sharding(mesh))
        return lax.with_sharding_constraint(x, self.partition_spec())

    # -- hetu ds-parallel JSON interop --------------------------------------
    @staticmethod
    def from_hetu(states: Dict[int, int], ndim: int,
                  dim_to_axis: Dict[int, Union[str, Sequence[str]]]) -> "DistributedStates":
        """Translate a reference-style states map {dim: split_num, -1: dup, -2:
        partial} (reference: engine/parallel_config.py:206 config2ds) given the
        mapping from tensor dims to mesh axes used by the current strategy."""
        splits = {}
        partial: Tuple[str, ...] = ()
        for d, n in states.items():
            if int(n) <= 1:
                continue
            d = int(d)
            if d == -2:
                partial = _norm_dimspec(dim_to_axis.get(-2, "tp"))
            elif d >= 0:
                splits[d] = dim_to_axis[d]
        return DistributedStates.make(ndim, splits, partial)

    def __str__(self):
        dims = ",".join("+".join(a) if a else "·" for a in self.spec)
        p = f"|partial({','.join(sorted(self.partial))})" if self.partial else ""
        return f"DS[{dims}{p}]"


def _prod(it):
    r = 1
    for v in it:
        r *= v
    return r


# ---------------------------------------------------------------------------
# Comm deduction — the analog of get_comm_type
# (reference: hetu/graph/ops/Communication.cc; lowering table at
#  executable_graph.cc:366-760 SubstituteCommOp).
# ---------------------------------------------------------------------------

class CommType(enum.Enum):
    NONE = "none"                    # layouts equal
    ALL_REDUCE = "all_reduce"        # partial -> replicated        (psum)
    REDUCE_SCATTER = "reduce_scatter"  # partial -> split           (psum_scatter)
    ALL_GATHER = "all_gather"        # split -> replicated          (all_gather)
    SPLIT = "split"                  # replicated -> split          (local slice)
    ALL_TO_ALL = "all_to_all"        # split(d1) -> split(d2)       (all_to_all)
    GENERIC = "generic"              # multi-step resharding


@dataclasses.dataclass(frozen=True)
class CommPlan:
    kind: CommType
    axis: Optional[AxisName] = None   # mesh axis the collective runs over
    src_dim: Optional[int] = None     # tensor dim (gather/scatter/a2a source)
    dst_dim: Optional[int] = None


def deduce_comm(src: DistributedStates, dst: DistributedStates) -> Tuple[CommPlan, ...]:
    """Plan the collectives converting layout `src` into `dst`.

    Returns a sequence of single-axis CommPlans (executed in order inside a
    shard_map region, or used as documentation of what GSPMD will insert).
    Mirrors the decision table of the reference's get_comm_type: partial is
    resolved first (all-reduce or fused reduce-scatter), then per-axis
    gather/slice/all-to-all moves.
    """
    if src.ndim != dst.ndim:
        raise ValueError(f"rank mismatch: {src} vs {dst}")
    if src == dst:
        return (CommPlan(CommType.NONE),)

    if dst.partial - src.partial:
        raise ValueError(f"cannot introduce partial: {src} -> {dst}")

    plans = []
    cur = src

    # 1. Resolve partial sums. Fuse into reduce-scatter when the destination
    #    appends exactly this axis (innermost) to an otherwise-unchanged dim
    #    (the TP/SP and ZeRO-bridge pattern, reference:
    #    ops/Communication.h:786 SplitReduceScatter); else plain all-reduce.
    for axis in sorted(cur.partial):
        if axis in dst.partial:
            continue  # stays partial
        ddim = dst.dim_of(axis)
        fuse = (
            ddim is not None
            and dst.spec[ddim] == cur.spec[ddim] + (axis,)
        )
        if fuse:
            plans.append(CommPlan(CommType.REDUCE_SCATTER, axis=axis, dst_dim=ddim))
            cur = dataclasses.replace(cur, partial=cur.partial - {axis}).with_split(ddim, axis)
        else:
            plans.append(CommPlan(CommType.ALL_REDUCE, axis=axis))
            cur = dataclasses.replace(cur, partial=cur.partial - {axis})

    # 2a. Pure single-axis dim transposes lower to one all-to-all
    #     (the CP token<->head move); anything fancier uses gather+split.
    for axis in sorted(cur.sharded_axes()):
        sdim, ddim = cur.dim_of(axis), dst.dim_of(axis)
        if (sdim is not None and ddim is not None and sdim != ddim
                and cur.spec[sdim] == (axis,) and dst.spec[sdim] == ()
                and cur.spec[ddim] == () and dst.spec[ddim] == (axis,)):
            plans.append(CommPlan(CommType.ALL_TO_ALL, axis=axis, src_dim=sdim, dst_dim=ddim))
            cur = cur.without_axis(axis).with_split(ddim, axis)

    # 2b. Per dim: gather (innermost first) until the current axes are a
    #     prefix of the destination's — gathering an outer axis while an
    #     inner one is still sharded would interleave blocks.
    for d in range(cur.ndim):
        while cur.spec[d] and not _is_prefix(cur.spec[d], dst.spec[d]):
            axis = cur.spec[d][-1]
            plans.append(CommPlan(CommType.ALL_GATHER, axis=axis, src_dim=d))
            cur = cur.without_axis(axis)

    # 2c. Per dim: split the missing destination axes outer-to-inner, so the
    #     final per-dim axis order matches dst exactly.
    for d in range(cur.ndim):
        for axis in dst.spec[d][len(cur.spec[d]):]:
            if cur.dim_of(axis) is not None:
                raise NotImplementedError(
                    f"generic reshard not planned: {src} -> {dst} (axis {axis})")
            plans.append(CommPlan(CommType.SPLIT, axis=axis, dst_dim=d))
            cur = cur.with_split(d, axis)

    if cur.spec != dst.spec:
        raise NotImplementedError(f"reshard plan failed: {src} -> {dst} (got {cur})")

    return tuple(plans) if plans else (CommPlan(CommType.NONE),)


def _is_prefix(a: Tuple, b: Tuple) -> bool:
    return len(a) <= len(b) and b[: len(a)] == a


# ---------------------------------------------------------------------------
# Explicit conversion inside shard_map (the CommOp lowering itself).
# ---------------------------------------------------------------------------

def convert(x, src: DistributedStates, dst: DistributedStates):
    """Apply the collectives converting `x` from layout src to dst.

    Must be called inside a shard_map region whose mesh binds every axis named
    by the layouts.  This is the executable form of SubstituteCommOp
    (reference: executable_graph.cc:366): each CommPlan lowers to one XLA
    collective on the bound axis.

    HETU_TPU_SP_COMPRESS (int8 | int4) routes the gather / scatter /
    all-to-all / all-reduce emissions through the quantized collectives
    in comm/collectives.py (blockwise int + f32 scales on the wire,
    quantized transpose in the backward); "none" — the default — emits
    exactly the plain lax calls below, HLO-byte-identical to before the
    flag existed.  Non-float payloads and sub-block buffers always take
    the exact path (collectives.eligible).
    """
    from hetu_tpu.comm import collectives as qc
    mode = qc.sp_mode()

    def _probe(op: str, payload):
        # numerics SNR probe (obs/numerics.py): the quantized collectives
        # are custom_vjp-wrapped, so their internal (q, scales) pair
        # cannot escape their own trace — measure the identical
        # quantize->dequantize roundtrip at THIS call site instead
        # (same primitives, deterministic, only traced when a numerics
        # collector is active and a frame is open in this trace).
        from hetu_tpu.obs import numerics as _numerics
        if _numerics.active() and qc.eligible(payload, mode):
            _numerics.tap_quant_roundtrip(f"sp/{op}", payload, mode)

    for plan in deduce_comm(src, dst):
        if plan.kind is CommType.NONE:
            continue
        elif plan.kind is CommType.ALL_REDUCE:
            if mode != "none":
                _probe("all_reduce", x)
                x = qc.all_reduce_q(x, plan.axis, mode=mode)
            else:
                x = lax.psum(x, plan.axis)
        elif plan.kind is CommType.REDUCE_SCATTER:
            if mode != "none":
                _probe("reduce_scatter", x)
                x = qc.reduce_scatter_q(x, plan.axis,
                                        scatter_dimension=plan.dst_dim,
                                        tiled=True, mode=mode)
            else:
                x = lax.psum_scatter(x, plan.axis, scatter_dimension=plan.dst_dim, tiled=True)
        elif plan.kind is CommType.ALL_GATHER:
            if mode != "none":
                _probe("all_gather", x)
                x = qc.all_gather_q(x, plan.axis, axis=plan.src_dim,
                                    tiled=True, mode=mode)
            else:
                x = lax.all_gather(x, plan.axis, axis=plan.src_dim, tiled=True)
        elif plan.kind is CommType.ALL_TO_ALL:
            if mode != "none":
                _probe("all_to_all", x)
                x = qc.all_to_all_q(x, plan.axis, split_axis=plan.dst_dim,
                                    concat_axis=plan.src_dim, mode=mode)
            else:
                x = lax.all_to_all(x, plan.axis, split_axis=plan.dst_dim,
                                   concat_axis=plan.src_dim, tiled=True)
        elif plan.kind is CommType.SPLIT:
            idx = lax.axis_index(plan.axis)
            size = lax.axis_size(plan.axis)
            dim = plan.dst_dim
            if x.shape[dim] % size != 0:
                raise ValueError(
                    f"cannot split dim {dim} of size {x.shape[dim]} over "
                    f"axis {plan.axis!r} of size {size} (not divisible)")
            chunk = x.shape[dim] // size
            x = lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)
        else:
            raise NotImplementedError(plan)
    return x


# Convenience preset layouts mirroring the reference's ds_union_map presets
# ('dup', 'split0', 'split0_dup', 'dup_split0' —
#  reference: python/hetu/nn/modules/parallel_multi_ds.py).
def dup(ndim: int) -> DistributedStates:
    return DistributedStates.dup(ndim)


def split0(ndim: int, axis: Union[str, Sequence[str]] = "tp") -> DistributedStates:
    return DistributedStates.make(ndim, {0: axis})


# ---------------------------------------------------------------------------
# Hetero layout unions — the analog of DistributedStatesUnion
# (reference: hetu/graph/distributed_states.h:158-321: a list of per-group
# DistributedStates plus `hetero_dim`, the tensor dim partitioned across
# groups, with possibly UNEVEN extents).
#
# TPU-native reading: a union describes one logical tensor executed by
# SEVERAL compiled programs over disjoint sub-meshes (hetero dp groups with
# different tp degrees, hetero pipeline stage groups with different layer
# counts).  Inside one group everything is an ordinary DistributedStates /
# GSPMD layout; the union layer owns only the cross-group partition: which
# slice of `hetero_dim` each group holds and how big it is.  Uneven extents
# execute as equal physical shards + valid-length metadata where a single
# program needs them (the hetero-CP design, data/bucket.py cp_split_uneven),
# or as genuinely different per-group shapes when the groups are separate
# programs (parallel/hetero_dp.py).
# ---------------------------------------------------------------------------

HETERO_REPLICATED = -1   # groups replicate the tensor (hetero over params)


def partition_extents(shares: Sequence[int], total: int) -> Tuple[int, ...]:
    """Partition `total` units into len(shares) positive integer extents
    proportional to shares (largest-remainder rounding).  The cross-group
    partition primitive shared by DistributedStatesUnion.extents and the
    Malleus hetero-dp row planner."""
    n = len(shares)
    if total < n:
        raise ValueError(
            f"cannot give each of {n} groups a nonzero extent of {total}")
    s = sum(shares)
    raw = [total * sh / s for sh in shares]
    out = [max(1, int(r)) for r in raw]
    rema = sorted(range(n), key=lambda i: raw[i] - int(raw[i]),
                  reverse=True)
    i = 0
    while sum(out) < total:
        out[rema[i % n]] += 1
        i += 1
    i = 0
    while sum(out) > total:
        j = rema[-1 - (i % n)]
        if out[j] > 1:
            out[j] -= 1
        i += 1
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class DistributedStatesUnion:
    """Union of per-group layouts partitioned (unevenly) across groups.

    groups:     inner layout per hetero group (all the same rank).
    hetero_dim: tensor dim split ACROSS groups, or HETERO_REPLICATED (-1)
                when every group holds the full tensor (params under hetero
                dp; reference hetero_dim -1 "dup" unions).
    shares:     relative extent of each group along hetero_dim (layer counts
                per stage group, batch rows per dp group...).  None = even.
    """

    groups: Tuple[DistributedStates, ...]
    hetero_dim: int = HETERO_REPLICATED
    shares: Optional[Tuple[int, ...]] = None

    # -- constructors -------------------------------------------------------
    @staticmethod
    def even(ds: DistributedStates, n_groups: int,
             hetero_dim: int = HETERO_REPLICATED) -> "DistributedStatesUnion":
        """The homogeneous union (reference: all-same ds_union_map entries)."""
        return DistributedStatesUnion((ds,) * n_groups, hetero_dim).validate()

    def validate(self) -> "DistributedStatesUnion":
        if not self.groups:
            raise ValueError("union needs at least one group")
        ndim = self.groups[0].ndim
        for g in self.groups:
            if g.ndim != ndim:
                raise ValueError(f"rank mismatch across union groups: {self}")
        if self.hetero_dim != HETERO_REPLICATED and not (
                0 <= self.hetero_dim < ndim):
            raise ValueError(f"hetero_dim {self.hetero_dim} out of range "
                             f"for rank {ndim}")
        if self.shares is not None:
            if len(self.shares) != len(self.groups):
                raise ValueError(
                    f"{len(self.shares)} shares for {len(self.groups)} groups")
            if self.hetero_dim == HETERO_REPLICATED:
                raise ValueError("shares require a real hetero_dim")
            if any(s <= 0 for s in self.shares):
                raise ValueError(f"shares must be positive: {self.shares}")
            # canonicalize: gcd-reduce, and drop all-equal shares entirely so
            # semantically identical unions compare equal ((2,2) == (1,1)
            # == None for every total)
            import math
            g = math.gcd(*self.shares) if len(self.shares) > 1 \
                else self.shares[0]
            norm = tuple(s // g for s in self.shares)
            if len(set(norm)) == 1:
                norm = None
            if norm != self.shares:
                return dataclasses.replace(self, shares=norm)
        return self

    # -- basic properties ---------------------------------------------------
    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def ndim(self) -> int:
        return self.groups[0].ndim

    def get(self, i: int) -> DistributedStates:
        return self.groups[i]

    def is_hetero(self) -> bool:
        """True when the union is not expressible as one homogeneous layout:
        groups differ, or extents are uneven (reference: is_hetero over
        DistributedStatesUnion)."""
        if any(g != self.groups[0] for g in self.groups[1:]):
            return True
        return self.shares is not None and len(set(self.shares)) > 1

    # -- the cross-group partition ------------------------------------------
    def extents(self, total: int) -> Tuple[int, ...]:
        """Per-group extent along hetero_dim summing exactly to `total`,
        proportional to shares (largest-remainder rounding, every group
        nonzero)."""
        if self.hetero_dim == HETERO_REPLICATED:
            return (total,) * self.num_groups
        return partition_extents(self.shares or (1,) * self.num_groups,
                                 total)

    def offsets(self, total: int) -> Tuple[Tuple[int, int], ...]:
        """Per-group [start, stop) along hetero_dim."""
        ext = self.extents(total)
        bounds, acc = [], 0
        for e in ext:
            bounds.append((acc, acc + e))
            acc += e
        return tuple(bounds)

    def padded_extent(self, total: int) -> int:
        """The equal physical shard size for single-program execution of an
        uneven union (pad-to-max + valid-len metadata — the hetero-CP
        execution form, data/bucket.py cp_split_uneven)."""
        return max(self.extents(total))

    def split_host(self, arr, axis: Optional[int] = None):
        """Split a host array into per-group pieces along hetero_dim (the
        data-dispatch step feeding per-group programs)."""
        axis = self.hetero_dim if axis is None else axis
        if axis == HETERO_REPLICATED:
            return [arr] * self.num_groups
        bounds = self.offsets(arr.shape[axis])
        sl = [slice(None)] * arr.ndim
        out = []
        for (a, b) in bounds:
            sl[axis] = slice(a, b)
            out.append(arr[tuple(sl)])
        return out

    def __str__(self):
        gs = "; ".join(str(g) for g in self.groups)
        hd = ("dup" if self.hetero_dim == HETERO_REPLICATED
              else f"dim{self.hetero_dim}")
        sh = f" shares={list(self.shares)}" if self.shares else ""
        return f"DSUnion[{gs} | hetero={hd}{sh}]"


def union_deduce_comm(src: DistributedStatesUnion,
                      dst: DistributedStatesUnion
                      ) -> Tuple[Tuple[CommPlan, ...], ...]:
    """Comm plans converting one union into another.  Uniform return shape:
    a tuple of CommPlan-sequences (iterate `for seq in plans: for p in seq`).

    Homogeneous-to-homogeneous with matching group structure lowers to the
    ordinary per-group deduce_comm, one sequence per group (each group
    converts inside its own mesh).  Anything that changes the cross-group
    partition (group count or uneven extents) is a single GENERIC sequence —
    executed by the switch engine's device_put program, not by single-mesh
    collectives (reference: the union branches of SubstituteCommOp / hetero
    switch planning)."""
    src = src.validate()
    dst = dst.validate()
    if (src.num_groups == dst.num_groups
            and src.hetero_dim == dst.hetero_dim
            and src.shares == dst.shares):
        return tuple(deduce_comm(s, d)
                     for s, d in zip(src.groups, dst.groups))
    return ((CommPlan(CommType.GENERIC),),)
