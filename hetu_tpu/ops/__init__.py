"""Functional ops.

The reference implements ~171 CUDA/CPU kernel files dispatched through a
per-op OpInterface (SURVEY.md §2.3).  On TPU ~90% of those lower to plain
jax.numpy/lax, which XLA fuses onto the MXU/VPU; this package holds the
functional forms plus the hand-written Pallas kernels for the hot ops
(flash attention, fused norms, rotary) and the collective-based ops
(ring attention, vocab-parallel CE).
"""
from hetu_tpu.ops.activations import gelu, silu, swiglu, relu, leaky_relu, mish, softplus, hardswish, sigmoid, dropout
from hetu_tpu.ops.norms import rms_norm, layer_norm, residual_rms_norm, residual_layer_norm
from hetu_tpu.ops.rotary import build_rope_cache, apply_rotary, apply_rotary_qk
from hetu_tpu.ops.losses import (
    softmax_cross_entropy,
    softmax_cross_entropy_sparse,
    vocab_parallel_cross_entropy,
    mse_loss,
    nll_loss,
    kl_div_loss,
    binary_cross_entropy,
)
from hetu_tpu.ops.attention import attention, flash_attention
from hetu_tpu.ops import tensor
from hetu_tpu.ops.quantization import (
    quantize_int8, dequantize_int8, quantize_int4, dequantize_int4,
    quantized_matmul_int8, pack_nibbles, unpack_nibbles,
)
