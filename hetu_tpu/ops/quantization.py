"""Quantize / dequantize ops.

Rebuild of the reference quantization ops (reference: hetu/graph/ops/
Quantization.h:15 Quantization/DeQuantization backed by bitsandbytes kernels
in third_party/bitsandbytes — int8 absmax and 4-bit block quantization).

TPU version: block-wise absmax int8 and packed int4, written in jnp (XLA
vectorizes these well on the VPU; a Pallas variant is only worth it fused
into a matmul, which is the weight-only-quantized matmul below).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def quantize_int8(x, block_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-wise absmax int8: returns (q [.../bs, bs] int8-valued, scales)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block_size == 0, (n, block_size)
    blocks = flat.reshape(-1, block_size).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return q, scale[:, 0]


def dequantize_int8(q, scale, shape) -> jnp.ndarray:
    blocks = q.astype(jnp.float32) * scale[:, None]
    return blocks.reshape(shape)


def quantize_int4(x, block_size: int = 64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-wise absmax int4, two nibbles packed per int8."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block_size == 0 and block_size % 2 == 0
    blocks = flat.reshape(-1, block_size).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 7.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -7, 7).astype(jnp.int8) + 8
    lo = q[:, 0::2]
    hi = q[:, 1::2]
    packed = (lo | (hi << 4)).astype(jnp.uint8)
    return packed, scale[:, 0]


def dequantize_int4(packed, scale, shape) -> jnp.ndarray:
    lo = (packed & 0xF).astype(jnp.int32) - 8
    hi = ((packed >> 4) & 0xF).astype(jnp.int32) - 8
    blocks = jnp.stack([lo, hi], axis=2).reshape(packed.shape[0], -1)
    return (blocks.astype(jnp.float32) * scale[:, None]).reshape(shape)


def quantized_matmul_int8(x, wq, wscale, w_shape) -> jnp.ndarray:
    """Weight-only int8 matmul: dequantize-on-the-fly (XLA fuses the
    dequant into the matmul epilogue's operand feed)."""
    w = dequantize_int8(wq, wscale, w_shape).astype(x.dtype)
    return x @ w
