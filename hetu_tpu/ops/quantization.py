"""Quantize / dequantize ops.

Rebuild of the reference quantization ops (reference: hetu/graph/ops/
Quantization.h:15 Quantization/DeQuantization backed by bitsandbytes kernels
in third_party/bitsandbytes — int8 absmax and 4-bit block quantization).

TPU version: block-wise absmax int8 and packed int4, written in jnp (XLA
vectorizes these well on the VPU; a Pallas variant is only worth it fused
into a matmul, which is the weight-only-quantized matmul below).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def quantize_int8(x, block_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-wise absmax int8: returns (q [.../bs, bs] int8-valued, scales)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block_size == 0, (n, block_size)
    blocks = flat.reshape(-1, block_size).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return q, scale[:, 0]


def dequantize_int8(q, scale, shape) -> jnp.ndarray:
    blocks = q.astype(jnp.float32) * scale[:, None]
    return blocks.reshape(shape)


def pack_nibbles(u, *, even_high: bool) -> jnp.ndarray:
    """THE int4 nibble packer — one implementation shared by both wire
    formats so they can never silently diverge (the cross-format
    regression test in tests/test_pallas_kernels.py pins both layouts).

    `u`: unsigned nibble values in [0, 15], even last dim.  Two adjacent
    values pack into one byte; `even_high=True` puts the EVEN index in
    the high nibble (`comm/compress.pack_int4`'s offset-binary wire
    format), `even_high=False` puts it in the low nibble (this module's
    storage format)."""
    if u.shape[-1] % 2:
        raise ValueError(f"int4 packing needs an even trailing dim, got "
                         f"{u.shape[-1]}")
    u = u.astype(jnp.uint8)
    even = u[..., 0::2]
    odd = u[..., 1::2]
    return ((even << 4) | odd) if even_high else (even | (odd << 4))


def unpack_nibbles(p, *, even_high: bool) -> jnp.ndarray:
    """Inverse of `pack_nibbles`: uint8 [..., n] -> values [..., 2n] in
    [0, 15] (uint8)."""
    hi = ((p >> 4) & 0xF).astype(jnp.uint8)
    lo = (p & 0xF).astype(jnp.uint8)
    even, odd = (hi, lo) if even_high else (lo, hi)
    out = jnp.stack([even, odd], axis=-1)
    return out.reshape(p.shape[:-1] + (2 * p.shape[-1],))


def quantize_int4(x, block_size: int = 64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-wise absmax int4, two nibbles packed per int8 (even index in
    the LOW nibble — the storage layout; `comm/compress.pack_int4` uses
    the transposed even-high wire layout, both via `pack_nibbles`)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block_size == 0 and block_size % 2 == 0
    blocks = flat.reshape(-1, block_size).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 7.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -7, 7).astype(jnp.int8) + 8
    packed = pack_nibbles(q, even_high=False)
    return packed, scale[:, 0]


def dequantize_int4(packed, scale, shape) -> jnp.ndarray:
    blocks = unpack_nibbles(packed, even_high=False).astype(jnp.int32) - 8
    return (blocks.astype(jnp.float32) * scale[:, None]).reshape(shape)


def quantized_matmul_int8(x, wq, wscale, w_shape) -> jnp.ndarray:
    """Weight-only int8 matmul: dequantize-on-the-fly (XLA fuses the
    dequant into the matmul epilogue's operand feed)."""
    w = dequantize_int8(wq, wscale, w_shape).astype(x.dtype)
    return x @ w
