"""Rotary position embeddings (reference: hetu/impl/kernel/rotary.cu +
python/hetu/models/llama/llama_model.py:10 RotaryEmbedding).

Supports packed varlen batches via per-token position ids (the TPU analog of
the reference's cu_seqlens-aware fused rotary): the data pipeline emits
position ids that restart at each packed-sequence boundary, so one gather
replaces the cu_seqlens offset logic.
"""
from typing import Optional

import jax
import jax.numpy as jnp


def build_rope_cache(max_len: int, head_dim: int, base: float = 10000.0,
                     dtype=jnp.float32):
    """Precompute cos/sin tables [max_len, head_dim//2]."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin, position_ids: Optional[jnp.ndarray] = None):
    """Apply RoPE. x: [..., seq, heads, head_dim]; cos/sin: [max_len, hd//2];
    position_ids: [..., seq] int32 (defaults to arange)."""
    seq = x.shape[-3]
    if position_ids is None:
        cos_t = cos[:seq]
        sin_t = sin[:seq]
        # [seq, 1, hd/2] broadcasting over heads
        cos_t = cos_t[:, None, :]
        sin_t = sin_t[:, None, :]
    else:
        cos_t = cos[position_ids][..., None, :]
        sin_t = sin[position_ids][..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos_t - xf2 * sin_t
    out2 = xf2 * cos_t + xf1 * sin_t
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def apply_rotary_qk(q, k, cos, sin, position_ids: Optional[jnp.ndarray] = None,
                    use_pallas: Optional[bool] = None):
    """Apply RoPE to q [b, s, nq, hd] AND k [b, s, nk, hd] in one fused
    Pallas pass (ops/pallas/rotary — the tables are gathered once and
    both tensors rotate in VMEM; the rotation's vjp is the same kernel
    with -sin).  Falls back to two `apply_rotary` calls — the exact seed
    composition — when the kernel is gated off or the shape gate
    rejects.  Returns (q_rotated, k_rotated)."""
    if use_pallas is None:
        from hetu_tpu.ops.pallas import resolve_route
        from hetu_tpu.ops.pallas import rotary as _pr
        use_pallas = resolve_route(
            "rotary", q.ndim == 4 and k.ndim == 4
            and _pr.compatible(q.shape, k.shape))
    if use_pallas:
        from hetu_tpu.ops.pallas.rotary import fused_rotary_qk
        b, s = q.shape[0], q.shape[1]
        d2 = cos.shape[-1]
        if position_ids is None:
            cos_t = jnp.broadcast_to(cos[:s][None], (b, s, d2))
            sin_t = jnp.broadcast_to(sin[:s][None], (b, s, d2))
        else:
            cos_t = jnp.broadcast_to(cos[position_ids], (b, s, d2))
            sin_t = jnp.broadcast_to(sin[position_ids], (b, s, d2))
        with jax.named_scope("pallas_rotary"):
            return fused_rotary_qk(q, k, cos_t.astype(jnp.float32),
                                   sin_t.astype(jnp.float32))
    return (apply_rotary(q, cos, sin, position_ids),
            apply_rotary(k, cos, sin, position_ids))
