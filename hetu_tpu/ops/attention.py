"""Attention ops.

`attention` is the reference composition (reference: hetu/graph/ops/Attention.cc)
— a pure-XLA softmax attention used for golden tests and small models.

`flash_attention` is the dispatcher for the fused path (reference:
hetu/impl/kernel/FlashAttention.cu wrapping flash-attn 2): on TPU it routes to
the Pallas flash kernel (hetu_tpu.ops.pallas.flash_attention) when shapes
permit, else falls back to the XLA composition — XLA's own fusion of this
pattern is already strong on TPU, so the fallback is safe, just more HBM
traffic for long sequences.
"""
from typing import Optional

import jax
import jax.numpy as jnp


def attention(q, k, v, *, causal: bool = True, bias: Optional[jnp.ndarray] = None,
              segment_ids: Optional[jnp.ndarray] = None, softmax_scale: Optional[float] = None,
              dropout_rate: float = 0.0, dropout_rng: Optional[jnp.ndarray] = None):
    """Softmax attention. q,k,v: [batch, seq, heads, head_dim] (kv heads may be
    fewer for GQA — broadcast here). Returns [batch, seq, heads, head_dim]."""
    orig_dtype = q.dtype
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # [b, h, sq, sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if bias is not None:
        scores = scores + bias
    neg = jnp.finfo(jnp.float32).min
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(mask[None, None], scores, neg)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(seg_mask[:, None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = 1.0 - dropout_rate
        mask2 = jax.random.bernoulli(dropout_rng, keep, probs.shape)
        probs = jnp.where(mask2, probs / keep, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(orig_dtype)


try:
    from hetu_tpu.ops.pallas.flash_attention import flash_attention as _pallas_fa
except ImportError:  # pallas kernel not built yet / not importable on CPU
    _pallas_fa = None


def _pallas_compatible(q, k) -> bool:
    """The auto path's shape gate.  Delegates to the kernel module's own
    `compatible` — which is implemented AS the entry validation
    (flash_attention.check_default_shapes), so the gate's verdict and
    what the kernel actually accepts can never silently diverge (the
    drift test in tests/test_pallas_kernels.py pins the contract)."""
    from hetu_tpu.ops.pallas.flash_attention import compatible
    return compatible(q.shape, k.shape)


def flash_attention(q, k, v, *, causal: bool = True,
                    segment_ids: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None):
    """Fused attention entry point. Routes to the Pallas TPU kernel when
    running on TPU with compatible shapes; XLA composition otherwise."""
    if use_pallas is None:
        # HETU_TPU_PALLAS=1/0 force-routes; "auto" keeps the shape gate
        # (reference: the HETU_PARALLEL_ATTN env family, GetExecEnvs);
        # HETU_TPU_PALLAS_KERNELS can exclude just this kernel
        from hetu_tpu.ops.pallas import kernel_enabled
        forced = kernel_enabled("flash")
        if forced is not None:
            use_pallas = forced
        else:
            use_pallas = (jax.default_backend() == "tpu"
                          and _pallas_fa is not None
                          and _pallas_compatible(q, k))
    if use_pallas:
        if _pallas_fa is None:
            raise RuntimeError("use_pallas=True but the Pallas kernel is unavailable")
        # named so obs.hlo_profile attributes the custom-call to its
        # kernel group (layer_table `.../pallas_flash_attention` rows)
        with jax.named_scope("pallas_flash_attention"):
            return _pallas_fa(q, k, v, causal=causal, segment_ids=segment_ids,
                              softmax_scale=softmax_scale)
    return attention(q, k, v, causal=causal, segment_ids=segment_ids,
                     softmax_scale=softmax_scale)
