"""Tensor/elementwise/reduction/view op surface.

The reference implements each of these as a C++/CUDA op pair
(reference: hetu/graph/ops/ — inventory in SURVEY.md §2.3: elementwise/unary,
arithmetics/linalg, shape/view, reductions).  On TPU they are jax.numpy
compositions that XLA fuses; this module provides the reference-named
functional surface so code written against the reference's op list ports
directly, and documents the 1:1 coverage for each inventory row.

All functions are jit-compatible and differentiate via jax autodiff — the
reference's per-op DoGradient is subsumed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# -- elementwise / unary (reference: Abs.cc, Ceil.cc, Exp.cc, ...) ----------
abs = jnp.abs  # noqa: A001
ceil = jnp.ceil
exp = jnp.exp
floor = jnp.floor
log = jnp.log
opposite = jnp.negative
pow = jnp.power  # noqa: A001
reciprocal = jnp.reciprocal
round = jnp.round  # noqa: A001
sqrt = jnp.sqrt
rsqrt = lax.rsqrt
sin = jnp.sin
cos = jnp.cos
tanh = jnp.tanh
sigmoid = jax.nn.sigmoid


def bool_(x):
    return x.astype(jnp.bool_)


where = jnp.where


def masked_fill(x, mask, value):
    """reference: Maskedfill.cc"""
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


clamp = jnp.clip


def range_mask(x, lo, hi):
    """reference: RangeMask kernel — 1 where lo <= x <= hi."""
    return ((x >= lo) & (x <= hi)).astype(x.dtype)


# -- arithmetics / linalg (reference: Arithmetics.cc, matmul.cc, ...) -------
add = jnp.add
sub = jnp.subtract
mul = jnp.multiply
div = jnp.divide
matmul = jnp.matmul
bmm = jnp.matmul          # BatchMatMul.cc — jnp.matmul batches leading dims


def linear(x, w, b=None):
    """reference: Linear.cc (x@w + b)."""
    y = x @ w
    return y + b if b is not None else y


dot = jnp.dot
outer = jnp.outer
einsum = jnp.einsum       # reference: Einsum.cc (~1.9k LoC) -> one call
norm = jnp.linalg.norm

# reductions (reference: Reduce.cc/ReduceX.cu: sum/mean/max/min/prod)
reduce_sum = jnp.sum
reduce_mean = jnp.mean
reduce_max = jnp.max
reduce_min = jnp.min
reduce_prod = jnp.prod

# -- shape / view (reference: Views.h, Reshape.cc, ...) ---------------------
reshape = jnp.reshape
transpose = jnp.transpose


def slice(x, begin, size):  # noqa: A001
    """reference: Slice.cc (begin/size semantics)."""
    return lax.dynamic_slice(x, begin, size)


split = jnp.split
concat = jnp.concatenate
pad = jnp.pad
repeat = jnp.repeat
roll = jnp.roll
gather = jnp.take_along_axis


def index_add(x, dim, index, src):
    """reference: IndexAdd.cc — x[..., index_i, ...] += src[..., i, ...]."""
    moved = jnp.moveaxis(x, dim, 0)
    moved_src = jnp.moveaxis(src, dim, 0)
    out = moved.at[index].add(moved_src)
    return jnp.moveaxis(out, 0, dim)


diagonal = jnp.diagonal
triu = jnp.triu
tril = jnp.tril
arange = jnp.arange


def onehot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


eye = jnp.eye


def interpolate(x, scale: int):
    """reference: Interpolate.cc — nearest-neighbor upsample (NHWC)."""
    return jnp.repeat(jnp.repeat(x, scale, axis=1), scale, axis=2)


broadcast_to = jnp.broadcast_to


def contiguous(x):
    """reference: Contiguous.cc — a no-op under XLA (layouts are compiler-
    managed); kept for API parity."""
    return x


def embedding_lookup(table, ids):
    """reference: EmbeddingLookup.cc"""
    return jnp.take(table, ids, axis=0)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def cumsum(x, axis=0):
    return jnp.cumsum(x, axis=axis)
