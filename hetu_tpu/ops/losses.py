"""Loss ops (reference: hetu/graph/ops/{SoftmaxCrossEntropy,
SoftmaxCrossEntropySparse,VocabParallelCrossEntropyLoss,NLLLoss,KLDivLoss,
MSELoss,BinaryCrossEntropy}.cc).

`vocab_parallel_cross_entropy` is the TP-sharded vocab CE: logits arrive
sharded on the vocab dim across the `tp` mesh axis and the max/denominator/
target-logit terms are combined with psums — the same three-collective scheme
as the reference's VocabParallelCrossEntropyLoss, expressed with lax collectives
inside shard_map (or left to GSPMD in gspmd mode via the plain sparse CE).
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def softmax_cross_entropy(logits, labels_onehot, reduction: str = "mean"):
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    loss = jnp.sum(labels_onehot * (logz - logits), axis=-1)
    return _reduce(loss, reduction)


def softmax_cross_entropy_sparse(logits, labels, ignore_index: int = -100,
                                 reduction: str = "mean"):
    """Sparse-label CE with ignored positions (the LM loss)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    safe_labels = jnp.where(labels == ignore_index, 0, labels)
    target = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    loss = logz - target
    mask = (labels != ignore_index).astype(jnp.float32)
    loss = loss * mask
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)
    return _reduce(loss, reduction)


def vocab_parallel_cross_entropy(local_logits, labels, vocab_start: int,
                                 vocab_size_local: int, axis: str = "tp",
                                 ignore_index: int = -100):
    """CE over vocab-sharded logits inside a shard_map region.

    local_logits: [tokens, vocab/tp] this shard's logits.
    labels: [tokens] global vocab ids (replicated across tp).
    Three collectives over `axis`: max, sum-exp, target-logit — mirroring the
    reference kernel's allreduce(max)/allreduce(denom) scheme.
    """
    x = local_logits.astype(jnp.float32)
    gmax = lax.pmax(jnp.max(x, axis=-1), axis)
    sumexp = jnp.sum(jnp.exp(x - gmax[..., None]), axis=-1)
    denom = lax.psum(sumexp, axis)
    logz = jnp.log(denom) + gmax

    in_range = (labels >= vocab_start) & (labels < vocab_start + vocab_size_local)
    local_idx = jnp.clip(labels - vocab_start, 0, vocab_size_local - 1)
    tgt = jnp.take_along_axis(x, local_idx[..., None], axis=-1)[..., 0]
    tgt = jnp.where(in_range, tgt, 0.0)
    target = lax.psum(tgt, axis)

    mask = (labels != ignore_index).astype(jnp.float32)
    loss = (logz - target) * mask
    return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)


def mse_loss(pred, target, reduction: str = "mean"):
    return _reduce(jnp.square(pred.astype(jnp.float32) - target.astype(jnp.float32)),
                   reduction)


def nll_loss(log_probs, labels, reduction: str = "mean"):
    loss = -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    return _reduce(loss, reduction)


def kl_div_loss(log_pred, target, reduction: str = "mean"):
    loss = target * (jnp.log(jnp.maximum(target, 1e-20)) - log_pred)
    return _reduce(jnp.sum(loss, axis=-1), reduction)


def binary_cross_entropy(pred, target, eps: float = 1e-7, reduction: str = "mean"):
    p = jnp.clip(pred.astype(jnp.float32), eps, 1.0 - eps)
    loss = -(target * jnp.log(p) + (1.0 - target) * jnp.log1p(-p))
    return _reduce(loss, reduction)


def _reduce(loss, reduction: str):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
