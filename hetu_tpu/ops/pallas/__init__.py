"""Hand-written Pallas TPU kernels for the hot ops
(reference: hetu/impl/kernel/*.cu — the ~10% of kernels XLA fusion does not
already cover; SURVEY.md §2.5 item 2)."""
