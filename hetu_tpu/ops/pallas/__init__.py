"""Hand-written Pallas TPU kernels for the hot ops
(reference: hetu/impl/kernel/*.cu — the ~10% of kernels XLA fusion does not
already cover; SURVEY.md §2.5 item 2).

The fused-kernel layer (docs/kernels.md):

  * flash_attention  — online-softmax attention (FlashAttention.cu)
  * fused_norm       — residual-add + RMSNorm / LayerNorm, one pass
                       (FusedLayerNorm/RMSNorm.cu)
  * swiglu           — silu(gate) * up combine (SwiGLU.cu)
  * rotary           — RoPE applied to q AND k in one kernel (rotary.cu)
  * quant            — blockwise int8/int4 quantize/dequantize feeding the
                       compressed collectives (quantization.cu, EQuARX)
  * paged_attention  — decode attention directly over the serving KV
                       pool's page tables (gather-free decode)
  * paged_verify     — the multi-query sibling: k+1 speculative query
                       positions per slot attend the same pages in one
                       launch (spec-decode verification)
  * sample           — fused last-layer epilogue: lm_head matmul +
                       temperature/top-k/top-p filter + Gumbel draw per
                       row without materializing [rows, vocab] logits
  * adam             — fused AdamW moment + parameter update, one launch
                       per flat parameter leaf (FusedAdam.cu)

Every kernel follows the flash-attention pattern: a shape gate that
EXACTLY mirrors the kernel's own entry validation (`compatible()` /
ValueError — the drift tests in tests/test_pallas_kernels.py pin the two
together), an XLA fallback the dispatcher in `hetu_tpu/ops` routes to
when the gate rejects or the flag says off, `interpret=_interpret()` on
the CPU test mesh, and a custom_vjp backward so training paths get the
fused bytes too.

Routing: `HETU_TPU_PALLAS` (auto/1/0) gates the WHOLE layer the way it
always gated flash attention; `HETU_TPU_PALLAS_KERNELS` restricts which
kernels participate (comma list / all / none) so one kernel can be
bisected out without losing the rest.
"""
from __future__ import annotations

from typing import FrozenSet, Optional

#: every routable kernel name (the HETU_TPU_PALLAS_KERNELS vocabulary)
KERNEL_NAMES = ("flash", "norm", "swiglu", "rotary", "quant", "paged_attn",
                "paged_verify", "sample", "adam")


def _interpret() -> bool:
    """CPU (the virtual test mesh) runs kernels in interpret mode — one
    definition shared by every kernel module."""
    import jax
    return jax.default_backend() == "cpu"


def _selected_kernels() -> FrozenSet[str]:
    from hetu_tpu.utils import flags
    raw = flags.str_flag("HETU_TPU_PALLAS_KERNELS").strip()
    if raw in ("", "all"):
        return frozenset(KERNEL_NAMES)
    if raw == "none":
        return frozenset()
    names = frozenset(t.strip() for t in raw.split(",") if t.strip())
    unknown = names - frozenset(KERNEL_NAMES)
    if unknown:
        raise ValueError(
            f"HETU_TPU_PALLAS_KERNELS names unknown kernels {sorted(unknown)}; "
            f"known: {list(KERNEL_NAMES)} (or 'all'/'none')")
    return names


def kernel_enabled(name: str) -> Optional[bool]:
    """Resolve the flag surface for one kernel: False = off (use the XLA
    fallback), True = forced on (the kernel's own validation raises on
    unsupported shapes — loud, per the flash-attention contract), None =
    auto (TPU backend + the kernel's shape gate decide)."""
    if name not in KERNEL_NAMES:
        raise ValueError(f"unknown pallas kernel {name!r}; "
                         f"known: {list(KERNEL_NAMES)}")
    from hetu_tpu.utils import flags
    mode = flags.str_flag("HETU_TPU_PALLAS")
    if mode == "0":
        return False
    if name not in _selected_kernels():
        return False
    if mode == "1":
        return True
    return None


def resolve_route(name: str, compatible: bool) -> bool:
    """The one auto-routing rule (mirrors ops.attention.flash_attention):
    forced flags win; auto takes the kernel only on a TPU backend with a
    passing shape gate."""
    en = kernel_enabled(name)
    if en is not None:
        return en
    import jax
    return jax.default_backend() == "tpu" and compatible
