"""Fused residual-add + RMSNorm / LayerNorm Pallas kernels.

Rebuild of the reference's fused norm kernels (reference:
hetu/impl/kernel/RMSNorm.cu, FusedLayerNorm.cu — residual-in, norm-out in
one pass with fp32 accumulators).  The XLA composition is a multi-pass
chain (add -> upcast -> square -> mean -> scale -> weight-mul -> downcast),
each pass a round trip of the [tokens, hidden] activation through HBM;
this kernel reads x and h once and writes the normed output AND the new
residual stream once (`ops/pallas/traffic.py` prices the two analytically
— the bench `detail.kernels` record).

Forward returns BOTH outputs because the pre-norm transformer needs both:

    s = x + h          # the residual stream the block returns
    y = norm(s) * w    # what feeds the next matmul

The backward is a custom_vjp running a second fused kernel: it receives
cotangents for y AND s (the residual stream is consumed downstream too),
recomputes the row statistics from the saved s (cheaper than saving
inv/mean: one fused read instead of extra HBM residents), and emits
dx (= dh) plus per-block partial dw/db rows that are summed outside.

Shape contract (`compatible` mirrors the entry validation EXACTLY — the
drift test pins them): hidden (the normed axis) must be lane-aligned
(% 128) and the flattened token count must tile into sublanes (% 8).
Rows per grid step are sized to keep each VMEM resident near ~0.5 MB.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

#: per-buffer VMEM budget (bytes, f32) used to pick the row-block size
_VMEM_ROW_BUDGET = 512 * 1024


def _check_shapes(x_shape, h_shape, w_shape) -> Tuple[int, int]:
    """Entry validation — raises ValueError exactly when `compatible`
    says False (the drift-test contract).  Returns (tokens, hidden)."""
    if tuple(x_shape) != tuple(h_shape):
        raise ValueError(f"residual/branch shapes differ: {x_shape} vs "
                         f"{h_shape}")
    if len(x_shape) < 2:
        raise ValueError(f"need at least [tokens, hidden], got {x_shape}")
    hidden = x_shape[-1]
    if tuple(w_shape) != (hidden,):
        raise ValueError(f"weight shape {w_shape} != ({hidden},)")
    tokens = 1
    for d in x_shape[:-1]:
        tokens *= d
    if hidden % 128:
        raise ValueError(f"hidden {hidden} is not lane-aligned (% 128); "
                         f"the XLA fallback handles this shape")
    if tokens % 8:
        raise ValueError(f"token count {tokens} does not tile into "
                         f"sublanes (% 8); the XLA fallback handles it")
    return tokens, hidden


def compatible(x_shape, h_shape=None, w_shape=None) -> bool:
    """The dispatcher's shape gate — implemented AS the entry validation
    so gate and kernel can never drift."""
    h_shape = x_shape if h_shape is None else h_shape
    w_shape = (x_shape[-1],) if w_shape is None else w_shape
    try:
        _check_shapes(x_shape, h_shape, w_shape)
        return True
    except ValueError:
        return False


def _fit_rows(tokens: int, hidden: int) -> int:
    """Largest divisor of `tokens` that is a multiple of 8 and keeps one
    f32 [rows, hidden] buffer near the VMEM budget."""
    cap = max(8, _VMEM_ROW_BUDGET // max(hidden * 4, 1))
    r = min(tokens, cap - cap % 8 or 8)
    while tokens % r or r % 8:
        r -= 1
    return max(r, 8)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, h_ref, w_ref, b_ref, y_ref, s_ref, *, eps, kind,
                has_bias):
    x = x_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    s = x + h
    if kind == "rms":
        var = jnp.mean(s * s, axis=-1, keepdims=True)
        y = s * jax.lax.rsqrt(var + eps)
    else:
        mu = jnp.mean(s, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
        y = (s - mu) * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32)
    if has_bias:
        y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    s_ref[...] = s.astype(s_ref.dtype)


def _bwd_kernel(s_ref, w_ref, dy_ref, dr_ref, dx_ref, dw_ref, db_ref, *,
                eps, kind):
    s = s_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if kind == "rms":
        inv = jax.lax.rsqrt(jnp.mean(s * s, axis=-1, keepdims=True) + eps)
        xhat = s * inv
        g = dy * w
        ds = inv * (g - xhat * jnp.mean(g * xhat, axis=-1, keepdims=True))
    else:
        mu = jnp.mean(s, axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(
            jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True) + eps)
        xhat = (s - mu) * inv
        g = dy * w
        ds = inv * (g - jnp.mean(g, axis=-1, keepdims=True)
                    - xhat * jnp.mean(g * xhat, axis=-1, keepdims=True))
    ds = ds + dr_ref[...].astype(jnp.float32)
    dx_ref[...] = ds.astype(dx_ref.dtype)
    dw_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    # written even for the bias-free RMS variant (discarded outside):
    # an output block a kernel MIGHT not write is undefined on TPU
    db_ref[...] = jnp.sum(dy, axis=0, keepdims=True)


def _call_fwd(x2, h2, w2, b2, *, eps, kind, has_bias, rows, hidden):
    n = x2.shape[0] // rows
    kern = functools.partial(_fwd_kernel, eps=eps, kind=kind,
                             has_bias=has_bias)
    row_spec = pl.BlockSpec((rows, hidden), lambda i: (i, 0))
    w_spec = pl.BlockSpec((1, hidden), lambda i: (0, 0))
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[row_spec, row_spec, w_spec, w_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(x2.shape, x2.dtype),
                   jax.ShapeDtypeStruct(x2.shape, x2.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(x2, h2, w2, b2)


def _call_bwd(s2, w2, dy2, dr2, *, eps, kind, rows, hidden):
    n = s2.shape[0] // rows
    kern = functools.partial(_bwd_kernel, eps=eps, kind=kind)
    row_spec = pl.BlockSpec((rows, hidden), lambda i: (i, 0))
    w_spec = pl.BlockSpec((1, hidden), lambda i: (0, 0))
    part_spec = pl.BlockSpec((1, hidden), lambda i: (i, 0))
    dx, dw_parts, db_parts = pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[row_spec, w_spec, row_spec, row_spec],
        out_specs=[row_spec, part_spec, part_spec],
        out_shape=[jax.ShapeDtypeStruct(s2.shape, s2.dtype),
                   jax.ShapeDtypeStruct((n, hidden), jnp.float32),
                   jax.ShapeDtypeStruct((n, hidden), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(s2, w2, dy2, dr2)
    return dx, dw_parts.sum(axis=0), db_parts.sum(axis=0)


# ---------------------------------------------------------------------------
# public API (custom VJP)
# ---------------------------------------------------------------------------

def _fused(x, h, weight, bias, *, eps, kind):
    shape = x.shape
    hidden = shape[-1]
    has_bias = bias is not None
    tokens, hidden = _check_shapes(shape, h.shape, weight.shape)
    rows = _fit_rows(tokens, hidden)
    x2 = x.reshape(tokens, hidden)
    h2 = h.reshape(tokens, hidden)
    w2 = weight.reshape(1, hidden)
    b2 = (bias.reshape(1, hidden) if has_bias
          else jnp.zeros((1, hidden), weight.dtype))
    y2, s2 = _call_fwd(x2, h2, w2, b2, eps=eps, kind=kind,
                       has_bias=has_bias, rows=rows, hidden=hidden)
    return y2.reshape(shape), s2.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_vjp(x, h, weight, bias, eps, kind, has_bias):
    return _fused(x, h, weight, bias, eps=eps, kind=kind)


def _fused_fwd(x, h, weight, bias, eps, kind, has_bias):
    y, s = _fused(x, h, weight, bias, eps=eps, kind=kind)
    return (y, s), (s, weight)


def _fused_bwd(eps, kind, has_bias, res, cts):
    s, weight = res
    dy, dr = cts
    shape = s.shape
    hidden = shape[-1]
    tokens = s.size // hidden
    rows = _fit_rows(tokens, hidden)
    dx2, dw, db = _call_bwd(
        s.reshape(tokens, hidden), weight.reshape(1, hidden),
        dy.reshape(tokens, hidden), dr.reshape(tokens, hidden),
        eps=eps, kind=kind, rows=rows, hidden=hidden)
    dx = dx2.reshape(shape)
    # dx and dh are the SAME cotangent: s = x + h
    return (dx, dx, dw.astype(weight.dtype),
            db.astype(weight.dtype) if has_bias else None)


_fused_vjp.defvjp(_fused_fwd, _fused_bwd)


def fused_residual_rmsnorm(x, h, weight, eps: float = 1e-5):
    """One fused pass: s = x + h; y = rms_norm(s) * weight.  Returns
    (y, s).  Raises ValueError on shapes outside the gate (`compatible`)
    — dispatchers fall back to the XLA composition instead."""
    return _fused_vjp(x, h, weight, None, eps, "rms", False)


def fused_residual_layernorm(x, h, weight, bias, eps: float = 1e-5):
    """One fused pass: s = x + h; y = layer_norm(s) * weight + bias.
    Returns (y, s).  `bias` may be None (scale-only LayerNorm)."""
    return _fused_vjp(x, h, weight, bias, eps, "ln", bias is not None)
