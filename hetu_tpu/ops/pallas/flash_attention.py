"""Flash attention for TPU in Pallas.

Rebuild of the reference's fused attention
(reference: hetu/impl/kernel/FlashAttention.cu:150 run_mha_fwd wrapping the
vendored flash-attn 2; varlen/cu_seqlens handled by the kernel there).
TPU-first design decisions:

- online-softmax forward with float32 accumulators in VMEM scratch; the grid
  is (batch, q_heads, q_blocks, k_blocks) with the k dim innermost —
  sequential on a TensorCore, so scratch carries running (m, l, acc) across
  k blocks exactly like flash-attn's inner loop.
- packed varlen batches are masked by **segment ids**, the static-shape
  equivalent of cu_seqlens; causality is masked by **global positions**, which
  are explicit inputs so ring-attention context parallelism (chunks owned by
  other cp ranks, head+tail symmetric split) reuses this same kernel for every
  ring step (reference: ParallelAttention.cc ExecFlashAttn :660).
- GQA folds the kv-head broadcast into the k/v BlockSpec index maps (no
  materialized repeat); dk/dv come back per q-head and are group-summed
  outside the kernel.
- forward also emits LSE so the ring's online-softmax merge
  (reference ExecCorr :606) can combine partial attentions.
- backward = two Pallas kernels (dq over k-blocks; dkv over q-blocks) using
  the saved LSE + delta trick from flash-attn 2.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    # CPU (the virtual test mesh) runs kernels in interpret mode
    return jax.default_backend() == "cpu"


DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _diag_clamp_k(block_q: int, block_k: int, skip: bool):
    """Index map clamp: skipped above-diagonal iterations re-fetch the
    diagonal k block so Mosaic elides the duplicate DMA."""
    if not skip:
        return lambda qi, ki: ki
    return lambda qi, ki: jnp.minimum(ki, (qi * block_q + block_q - 1)
                                      // block_k)


def _diag_clamp_q(block_q: int, block_k: int, skip: bool):
    """Transpose clamp for the dkv kernel's (ki, qi) grid."""
    if not skip:
        return lambda ki, qi: qi
    return lambda ki, qi: jnp.maximum(qi, ki * block_k // block_q)


def _mask(s, q_pos, k_pos, q_seg, k_seg, causal):
    """Combined causal+segment mask for one (Bq, Bk) score tile."""
    m = None
    if causal:
        m = q_pos[:, None] >= k_pos[None, :]
    if q_seg is not None:
        seg = q_seg[:, None] == k_seg[None, :]
        m = seg if m is None else (m & seg)
    if m is not None:
        s = jnp.where(m, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr, *, scale, causal,
                use_seg, nk, block_q, block_k, skip_blocks):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # contiguous-causal block skip: block fully above the diagonal
    live = (ki * block_k <= qi * block_q + block_q - 1) if skip_blocks else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [Bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [Bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)

        m_prev = m_scr[:]                               # [Bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked row: m_new == NEG_INF and exp(s - m_new) would be 1;
        # shift the reference point so p underflows to 0 instead
        m_exp = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - m_exp)                          # [Bq, Bk]
        corr = jnp.exp(m_prev - m_new)                  # [Bq, 1]
        l_new = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)             # [Bk, d]
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == nk - 1)
    def _fin():
        l = l_scr[:]
        # rows with no visible key (l==0) output 0, lse = -inf-ish
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = (m_scr[:] + jnp.log(l_safe))[:, 0]
        lse_ref[0, 0, 0] = jnp.where(l[:, 0] == 0.0, NEG_INF, lse)


def _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, *, scale, causal,
         block_q, block_k, skip_blocks=False, debug=False):
    """q: [b, hq, sq, d]; k/v: [b, hkv, sk, d]; positions/segments: [b, s].
    Returns (o [b,hq,sq,d], lse [b,hq,sq])."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must divide by blocks "
                         f"({block_q},{block_k})")
    nq, nk = sq // block_q, sk // block_k
    use_seg = q_seg is not None
    if not use_seg:
        q_seg = jnp.zeros((b, sq), jnp.int32)
        k_seg = jnp.zeros((b, sk), jnp.int32)

    grid = (b, hq, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, use_seg=use_seg, nk=nk,
        block_q=block_q, block_k=block_k,
        skip_blocks=skip_blocks and causal)

    q_pos = q_pos.reshape(b, 1, sq)
    k_pos = k_pos.reshape(b, 1, sk)
    q_seg = q_seg.reshape(b, 1, sq)
    k_seg = k_seg.reshape(b, 1, sk)

    kidx = _diag_clamp_k(block_q, block_k, skip_blocks and causal)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, kidx(qi, ki))),
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, kidx(qi, ki))),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group, kidx(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group, kidx(qi, ki), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        debug=debug,
        interpret=_interpret(),
    )(q_pos, k_pos, q_seg, k_seg, q, k, v)
    return o, lse.reshape(b, hq, sq)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref,
                   v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
                   scale, causal, use_seg, nk, block_q, block_k, skip_blocks):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (ki * block_k <= qi * block_q + block_q - 1) if skip_blocks else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]                 # [Bq,1]
        lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)   # masked-row guard
        delta = delta_ref[0, 0, 0][:, None]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)
        p = jnp.exp(s - lse)                            # [Bq, Bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                           # [Bq, Bk]
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _fin():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref,
                    v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr, *, scale, causal, use_seg, nq, block_q,
                    block_k, skip_blocks):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # skip q blocks entirely above the diagonal (q ends before k begins)
    live = (qi * block_q + block_q - 1 >= ki * block_k) if skip_blocks else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]
        lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)   # masked-row guard
        delta = delta_ref[0, 0, 0][:, None]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)
        p = jnp.exp(s - lse)                            # [Bq, Bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _fin():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, q_pos, k_pos, q_seg, k_seg, *, scale, causal,
         block_q, block_k, skip_blocks=False, delta=None):
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must divide by blocks "
                         f"({block_q},{block_k})")
    nq, nk = sq // block_q, sk // block_k
    use_seg = q_seg is not None
    if not use_seg:
        q_seg = jnp.zeros((b, sq), jnp.int32)
        k_seg = jnp.zeros((b, sk), jnp.int32)

    if delta is None:  # loop-invariant for ring callers — pass it in
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    q_pos = q_pos.reshape(b, 1, sq)
    k_pos = k_pos.reshape(b, 1, sk)
    q_seg = q_seg.reshape(b, 1, sq)
    k_seg = k_seg.reshape(b, 1, sk)
    lse4 = lse.reshape(b, hq, 1, sq)
    delta4 = delta.reshape(b, hq, 1, sq)

    kidx_b = _diag_clamp_k(block_q, block_k, skip_blocks and causal)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          use_seg=use_seg, nk=nk, block_q=block_q,
                          block_k=block_k, skip_blocks=skip_blocks and causal),
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, kidx_b(qi, ki))),
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, kidx_b(qi, ki))),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group,
                                                 kidx_b(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group,
                                                 kidx_b(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q_pos, k_pos, q_seg, k_seg, q, k, v, do, lse4, delta4)

    # dk/dv per Q HEAD (grid over k blocks, inner loop over q blocks), then
    # group-summed to kv heads outside.
    qidx_b = _diag_clamp_q(block_q, block_k, skip_blocks and causal)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          use_seg=use_seg, nq=nq, block_q=block_q,
                          block_k=block_k, skip_blocks=skip_blocks and causal),
        grid=(b, hq, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, ki, qi: (bi, 0, qidx_b(ki, qi))),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki, qi: (bi, 0, ki)),
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, ki, qi: (bi, 0, qidx_b(ki, qi))),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki, qi: (bi, 0, ki)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qidx_b(ki, qi), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi // group, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi // group, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qidx_b(ki, qi), 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, ki, qi: (bi, hi, 0, qidx_b(ki, qi))),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, ki, qi: (bi, hi, 0, qidx_b(ki, qi))),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q_pos, k_pos, q_seg, k_seg, q, k, v, do, lse4, delta4)

    if group > 1:
        dk = dk.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, sk, d).sum(axis=2)
    # fp32 out — single-device callers cast once; the ring accumulates fp32
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom VJP)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash(q, k, v, q_pos, k_pos, q_seg, k_seg, scale, causal, block_q,
           block_k, skip_blocks):
    o, _ = _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale=scale,
                causal=causal, block_q=block_q, block_k=block_k,
                skip_blocks=skip_blocks)
    return o


def _flash_fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale, causal, block_q,
               block_k, skip_blocks):
    o, lse = _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  skip_blocks=skip_blocks)
    return o, (q, k, v, o, lse, q_pos, k_pos, q_seg, k_seg)


def _flash_bwd(scale, causal, block_q, block_k, skip_blocks, res, do):
    q, k, v, o, lse, q_pos, k_pos, q_seg, k_seg = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, q_pos, k_pos, q_seg, k_seg,
                      scale=scale, causal=causal, block_q=block_q,
                      block_k=block_k, skip_blocks=skip_blocks)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None, None)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    segment_ids: Optional[jnp.ndarray] = None,
                    kv_segment_ids: Optional[jnp.ndarray] = None,
                    q_positions: Optional[jnp.ndarray] = None,
                    kv_positions: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] (kv heads may
    divide q heads — GQA). segment_ids: [batch, seq] packed-batch ids
    (0 = pad); positions: [batch, seq] global positions for causal masking.
    Defaults: kv = arange(sk); q = arange(sq) + (sk - sq), i.e. BOTTOM-RIGHT
    causal alignment for sq != sk (the HF convention) — pass explicit
    positions under CP or for other alignments.  Returns [b, s, hq, d]."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must divide by blocks "
                         f"({block_q},{block_k}); pad via the bucket ladder")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # contiguous positions on both sides -> blocks above the diagonal can be
    # statically skipped (the causal 2x)
    skip_blocks = (causal and q_positions is None and kv_positions is None
                   and sq == sk)
    # [b, s, h, d] -> [b, h, s, d]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if q_positions is None:
        # bottom-right causal alignment for sq != sk (queries are the LAST
        # sq positions — the HF / reference-attention convention)
        q_positions = jnp.broadcast_to(
            jnp.arange(sq, dtype=jnp.int32) + (sk - sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    o = _flash(qt, kt, vt, q_positions.astype(jnp.int32),
               kv_positions.astype(jnp.int32),
               segment_ids.astype(jnp.int32) if segment_ids is not None else None,
               kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None else None,
               scale, causal, block_q, block_k, skip_blocks)
    return o.transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             segment_ids=None, kv_segment_ids=None,
                             q_positions=None, kv_positions=None,
                             softmax_scale: Optional[float] = None,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K) -> Tuple:
    """Forward-only variant returning (out [b,s,h,d], lse [b,h,s]) for the
    ring-attention merge. Differentiation is handled by the ring layer."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if q_positions is None:
        q_positions = jnp.broadcast_to(
            jnp.arange(sq, dtype=jnp.int32) + (sk - sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    o, lse = _fwd(qt, kt, vt, q_positions.astype(jnp.int32),
                  kv_positions.astype(jnp.int32),
                  segment_ids.astype(jnp.int32) if segment_ids is not None else None,
                  kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None else None,
                  scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    return o.transpose(0, 2, 1, 3), lse
