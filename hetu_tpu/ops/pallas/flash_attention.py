"""Flash attention for TPU in Pallas.

Rebuild of the reference's fused attention
(reference: hetu/impl/kernel/FlashAttention.cu:150 run_mha_fwd wrapping the
vendored flash-attn 2; varlen/cu_seqlens handled by the kernel there).
TPU-first design decisions:

- online-softmax forward with float32 accumulators in VMEM scratch; the grid
  is (batch, q_heads, pair) where `pair` walks a **compressed list of live
  (q-block, k-block) tiles** — causally-dead tiles are never scheduled, the
  TPU analog of flash-attn 2's causal-skip launch geometry (reference:
  hetu/impl/kernel/FlashAttention.cu:150 + third_party/flash_attn). The
  live-pair tables ride in as scalar-prefetch operands (the splash-attention
  technique), so ANY static block mask — contiguous causal, ring-step
  offsets, SYM split quadrants (ParallelAttention.cc:212 GenerateAttnInfo) —
  compresses the same way, forward and backward alike.
- packed varlen batches are masked by **segment ids**, the static-shape
  equivalent of cu_seqlens; causality is masked by **global positions**, which
  are explicit inputs so ring-attention context parallelism (chunks owned by
  other cp ranks, head+tail symmetric split) reuses this same kernel for every
  ring step (reference: ParallelAttention.cc ExecFlashAttn :660).
- GQA folds the kv-head broadcast into the k/v BlockSpec index maps (no
  materialized repeat); dk/dv come back per q-head and are group-summed
  outside the kernel.
- forward also emits LSE so the ring's online-softmax merge
  (reference ExecCorr :606) can combine partial attentions.
- backward = two Pallas kernels (dq over k-blocks; dkv over q-blocks) using
  the saved LSE + delta trick from flash-attn 2; both run on compressed
  triangular grids.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    # CPU (the virtual test mesh) runs kernels in interpret mode
    return jax.default_backend() == "cpu"


# swept on v5e at b8/s2048/h12/d128 (tools_bench_attn.py, 2026-07): f+b
# 1024/1024 7.05ms < 1024/512 7.50 < 512/512 7.92 — bigger tiles amortize
# per-tile VPU/DMA overhead; causal skip granularity loss is smaller than
# the win. VMEM: the fp32 score tile is 4MB, well within budget.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


# ---------------------------------------------------------------------------
# static block masks + compressed pair tables
# ---------------------------------------------------------------------------

BlockMask = Tuple[Tuple[bool, ...], ...]  # hashable [nq][nk] live-tile grid


def causal_block_mask(sq: int, sk: int, block_q: int, block_k: int,
                      q_offset: Optional[int] = None,
                      k_offset: int = 0) -> BlockMask:
    """Live-tile grid for contiguous causal attention: tile (qi, ki) is live
    iff its best-case query position can see its earliest key position.
    `q_offset`/`k_offset` are the global positions of element 0 on each side
    (default: bottom-right alignment, q_offset = sk - sq + k_offset) — this is
    how ring steps express "my queries vs. a rotated KV chunk"
    (reference: ParallelAttention.cc:212 GenerateAttnInfo mask kinds)."""
    nq, nk = sq // block_q, sk // block_k
    if q_offset is None:
        q_offset = sk - sq + k_offset
    rows = []
    for qi in range(nq):
        q_max = q_offset + qi * block_q + block_q - 1
        rows.append(tuple(k_offset + ki * block_k <= q_max
                          for ki in range(nk)))
    return tuple(rows)


def full_block_mask(sq: int, sk: int, block_q: int, block_k: int) -> BlockMask:
    return tuple((True,) * (sk // block_k) for _ in range(sq // block_q))


def block_mask_live_frac(mask: BlockMask) -> float:
    """Fraction of tiles scheduled (diagnostics / cost models)."""
    flat = [x for row in mask for x in row]
    return sum(flat) / max(1, len(flat))


def _pair_tables(mask: BlockMask):
    """Row-major compressed enumeration of live tiles.

    Returns int32 arrays (row, col, first, last, valid) of length T. Rows
    with zero live tiles get one dummy (row, 0) pair with valid=0 so their
    output block is still initialized (to the "attends to nothing" value)
    and written; the kernels skip the compute body for valid=0."""
    rows, cols, first, last, valid = [], [], [], [], []
    for r, row in enumerate(mask):
        live = [c for c, ok in enumerate(row) if ok]
        ok = 1 if live else 0
        live = live or [0]
        for j, c in enumerate(live):
            rows.append(r)
            cols.append(c)
            first.append(1 if j == 0 else 0)
            last.append(1 if j == len(live) - 1 else 0)
            valid.append(ok)
    return (np.asarray(rows, np.int32), np.asarray(cols, np.int32),
            np.asarray(first, np.int32), np.asarray(last, np.int32),
            np.asarray(valid, np.int32))


def _check_mask(mask: BlockMask, nq: int, nk: int):
    if len(mask) != nq or any(len(row) != nk for row in mask):
        raise ValueError(
            f"block_mask shape ({len(mask)},{len(mask[0]) if mask else 0}) "
            f"does not match the ({nq},{nk}) block grid — rebuild it with "
            f"the actual (possibly clamped) block sizes")


def fit_block(requested: int, s: int) -> int:
    """Largest block <= requested that divides s: steps down the
    128-aligned ladder first, then any divisor — the ONE block-picking rule
    for the single-device kernel and the ring (hetu_tpu.parallel.
    ring_attention uses this as _pick_block), so both entry points get the
    same tile geometry."""
    b = min(requested, s)
    while s % b:
        b = b - 128 if b > 128 else b - 1
        if b <= 0:
            raise ValueError(f"cannot block seq len {s}")
    return b


def _transpose_mask(mask: BlockMask) -> BlockMask:
    return tuple(zip(*mask))


def check_default_shapes(sq: int, sk: int, d: int,
                         block_q: int = DEFAULT_BLOCK_Q,
                         block_k: int = DEFAULT_BLOCK_K):
    """The public entry's shape validation under the DEFAULT block
    geometry — raises ValueError exactly when `compatible` says False
    (the drift-test contract; tests/test_pallas_kernels.py).  Returns
    the fitted (block_q, block_k)."""
    bq0, bk0 = min(block_q, sq), min(block_k, sk)
    bq = fit_block(block_q, sq)
    bk = fit_block(block_k, sk)
    if (bq != bq0 and bq < 128) or (bk != bk0 and bk < 128):
        raise ValueError(f"seq lens ({sq},{sk}) fit no lane-aligned block "
                         f"ladder (best: q={bq}, k={bk}); pad via "
                         f"the bucket ladder or pass block_q/block_k "
                         f"explicitly")
    if d % 128:
        raise ValueError(f"head dim {d} is not lane-aligned (% 128); "
                         f"pass block_q/block_k explicitly to opt out of "
                         f"the default geometry")
    return bq, bk


def compatible(q_shape, k_shape) -> bool:
    """Will the public entry accept these [b, s, h, d] shapes under the
    DEFAULT block geometry?  Implemented AS the entry validation so the
    auto-route gate (`ops.attention._pallas_compatible`) can never drift
    from what the kernel accepts."""
    try:
        check_default_shapes(q_shape[1], k_shape[1], q_shape[-1])
        return True
    except ValueError:
        return False


def _mask(s, q_pos, k_pos, q_seg, k_seg, causal):
    """Combined causal+segment mask for one (Bq, Bk) score tile."""
    m = None
    if causal:
        m = q_pos[:, None] >= k_pos[None, :]
    if q_seg is not None:
        seg = q_seg[:, None] == k_seg[None, :]
        m = seg if m is None else (m & seg)
    if m is not None:
        s = jnp.where(m, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(qi_ref, ki_ref, first_ref, last_ref, valid_ref,
                qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr, *, scale, causal,
                use_seg):
    t = pl.program_id(2)

    @pl.when(first_ref[t] == 1)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(valid_ref[t] == 1)  # dummy tiles of all-dead rows: init+fin only
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [Bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [Bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)

        m_prev = m_scr[:]                               # [Bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked row: m_new == NEG_INF and exp(s - m_new) would be 1;
        # shift the reference point so p underflows to 0 instead
        m_exp = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - m_exp)                          # [Bq, Bk]
        corr = jnp.exp(m_prev - m_new)                  # [Bq, 1]
        l_new = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)             # [Bk, d]
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(last_ref[t] == 1)
    def _fin():
        l = l_scr[:]
        # rows with no visible key (l==0) output 0, lse = -inf-ish
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = (m_scr[:] + jnp.log(l_safe))[:, 0]
        lse_ref[0, 0, 0] = jnp.where(l[:, 0] == 0.0, NEG_INF, lse)


def _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, *, scale, causal,
         block_q, block_k, block_mask: Optional[BlockMask] = None,
         debug=False):
    """q: [b, hq, sq, d]; k/v: [b, hkv, sk, d]; positions/segments: [b, s].
    `block_mask` is a static live-tile grid; dead tiles are never scheduled.
    Returns (o [b,hq,sq,d], lse [b,hq,sq])."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must divide by blocks "
                         f"({block_q},{block_k})")
    nq, nk = sq // block_q, sk // block_k
    use_seg = q_seg is not None
    if not use_seg:
        q_seg = jnp.zeros((b, sq), jnp.int32)
        k_seg = jnp.zeros((b, sk), jnp.int32)
    if block_mask is None:
        block_mask = full_block_mask(sq, sk, block_q, block_k)
    _check_mask(block_mask, nq, nk)
    qi_m, ki_m, first, last, valid = _pair_tables(block_mask)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, use_seg=use_seg)

    q_pos = q_pos.reshape(b, 1, sq)
    k_pos = k_pos.reshape(b, 1, sk)
    q_seg = q_seg.reshape(b, 1, sq)
    k_seg = k_seg.reshape(b, 1, sk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, hq, len(qi_m)),
        in_specs=[
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, qm, km, *_:
                         (bi, hi // group, km[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, qm, km, *_:
                         (bi, hi // group, km[t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, 0, qm[t])),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, 1, sq), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        debug=debug,
        interpret=_interpret(),
    )(qi_m, ki_m, first, last, valid, q_pos, k_pos, q_seg, k_seg, q, k, v)
    return o, lse.reshape(b, hq, sq)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(qi_ref, ki_ref, first_ref, last_ref, valid_ref,
                   qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref,
                   v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
                   scale, causal, use_seg):
    t = pl.program_id(2)

    @pl.when(first_ref[t] == 1)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(valid_ref[t] == 1)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]                 # [Bq,1]
        lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)   # masked-row guard
        delta = delta_ref[0, 0, 0][:, None]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)
        p = jnp.exp(s - lse)                            # [Bq, Bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                           # [Bq, Bk]
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last_ref[t] == 1)
    def _fin():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(ki_ref, qi_ref, first_ref, last_ref, valid_ref,
                    qpos_ref, kpos_ref, qseg_ref, kseg_ref, q_ref, k_ref,
                    v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr, *, scale, causal, use_seg):
    t = pl.program_id(2)

    @pl.when(first_ref[t] == 1)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(valid_ref[t] == 1)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]
        lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)   # masked-row guard
        delta = delta_ref[0, 0, 0][:, None]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qpos_ref[0, 0]
        k_pos = kpos_ref[0, 0]
        q_seg = qseg_ref[0, 0] if use_seg else None
        k_seg = kseg_ref[0, 0] if use_seg else None
        s = _mask(s, q_pos, k_pos, q_seg, k_seg, causal)
        p = jnp.exp(s - lse)                            # [Bq, Bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last_ref[t] == 1)
    def _fin():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, q_pos, k_pos, q_seg, k_seg, *, scale, causal,
         block_q, block_k, block_mask: Optional[BlockMask] = None,
         delta=None):
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = hq // hkv
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must divide by blocks "
                         f"({block_q},{block_k})")
    use_seg = q_seg is not None
    if not use_seg:
        q_seg = jnp.zeros((b, sq), jnp.int32)
        k_seg = jnp.zeros((b, sk), jnp.int32)
    if block_mask is None:
        block_mask = full_block_mask(sq, sk, block_q, block_k)
    _check_mask(block_mask, sq // block_q, sk // block_k)

    if delta is None:  # loop-invariant for ring callers — pass it in
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    q_pos = q_pos.reshape(b, 1, sq)
    k_pos = k_pos.reshape(b, 1, sk)
    q_seg = q_seg.reshape(b, 1, sq)
    k_seg = k_seg.reshape(b, 1, sk)
    lse4 = lse.reshape(b, hq, 1, sq)
    delta4 = delta.reshape(b, hq, 1, sq)

    # dq: rows = q blocks, inner walk over that row's live k blocks
    qi_m, ki_m, first, last, valid = _pair_tables(block_mask)
    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, hq, len(qi_m)),
        in_specs=[
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, qm, km, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, qm, km, *_:
                         (bi, hi // group, km[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, qm, km, *_:
                         (bi, hi // group, km[t], 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, 0, qm[t])),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, t, qm, km, *_: (bi, hi, 0, qm[t])),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, t, qm, km, *_:
                               (bi, hi, qm[t], 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          use_seg=use_seg),
        grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qi_m, ki_m, first, last, valid,
      q_pos, k_pos, q_seg, k_seg, q, k, v, do, lse4, delta4)

    # dk/dv per Q HEAD (rows = k blocks, inner walk over live q blocks), then
    # group-summed to kv heads outside.
    ki_t, qi_t, first_t, last_t, valid_t = _pair_tables(
        _transpose_mask(block_mask))
    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, hq, len(ki_t)),
        in_specs=[
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, km, qm, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, km, qm, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, t, km, qm, *_: (bi, 0, qm[t])),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, t, km, qm, *_: (bi, 0, km[t])),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, km, qm, *_:
                         (bi, hi // group, km[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, km, qm, *_:
                         (bi, hi // group, km[t], 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, qm[t], 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, 0, qm[t])),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, 0, qm[t])),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, km[t], 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, t, km, qm, *_: (bi, hi, km[t], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          use_seg=use_seg),
        grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(ki_t, qi_t, first_t, last_t, valid_t,
      q_pos, k_pos, q_seg, k_seg, q, k, v, do, lse4, delta4)

    if group > 1:
        dk = dk.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, sk, d).sum(axis=2)
    # fp32 out — single-device callers cast once; the ring accumulates fp32
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom VJP)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash(q, k, v, q_pos, k_pos, q_seg, k_seg, scale, causal, block_q,
           block_k, block_mask):
    o, _ = _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale=scale,
                causal=causal, block_q=block_q, block_k=block_k,
                block_mask=block_mask)
    return o


def _flash_fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale, causal, block_q,
               block_k, block_mask):
    o, lse = _fwd(q, k, v, q_pos, k_pos, q_seg, k_seg, scale=scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  block_mask=block_mask)
    return o, (q, k, v, o, lse, q_pos, k_pos, q_seg, k_seg)


def _flash_bwd(scale, causal, block_q, block_k, block_mask, res, do):
    q, k, v, o, lse, q_pos, k_pos, q_seg, k_seg = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, q_pos, k_pos, q_seg, k_seg,
                      scale=scale, causal=causal, block_q=block_q,
                      block_k=block_k, block_mask=block_mask)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None, None)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    segment_ids: Optional[jnp.ndarray] = None,
                    kv_segment_ids: Optional[jnp.ndarray] = None,
                    q_positions: Optional[jnp.ndarray] = None,
                    kv_positions: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    block_mask: Optional[BlockMask] = None):
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] (kv heads may
    divide q heads — GQA). segment_ids: [batch, seq] packed-batch ids
    (0 = pad); positions: [batch, seq] global positions for causal masking.
    Defaults: kv = arange(sk); q = arange(sq) + (sk - sq), i.e. BOTTOM-RIGHT
    causal alignment for sq != sk (the HF convention) — pass explicit
    positions under CP or for other alignments. `block_mask` (static
    [nq][nk] bool grid) overrides the scheduled-tile set; by default causal
    attention with contiguous positions schedules only at-or-below-diagonal
    tiles. Returns [b, s, hq, d]."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    default_blocks = block_q == DEFAULT_BLOCK_Q and block_k == DEFAULT_BLOCK_K
    if default_blocks:
        # under the DEFAULT ladder, a shrink below lane alignment (or an
        # unaligned head dim) means the shape fits no reasonable tile —
        # reject via the shared validation (`check_default_shapes`, the
        # same predicate the auto-route gate evaluates).  An EXPLICIT
        # caller block choice is honored at whatever divisor fit_block
        # lands on (the caller opted out of the default geometry).
        block_q, block_k = check_default_shapes(sq, sk, d)
    else:
        block_q = fit_block(block_q, sq)
        block_k = fit_block(block_k, sk)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # contiguous positions on both sides -> tiles above the diagonal are
    # never scheduled (the causal 2x), fwd AND bwd
    if block_mask is None and causal and q_positions is None \
            and kv_positions is None:
        block_mask = causal_block_mask(sq, sk, block_q, block_k)
    # [b, s, h, d] -> [b, h, s, d]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if q_positions is None:
        # bottom-right causal alignment for sq != sk (queries are the LAST
        # sq positions — the HF / reference-attention convention)
        q_positions = jnp.broadcast_to(
            jnp.arange(sq, dtype=jnp.int32) + (sk - sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    o = _flash(qt, kt, vt, q_positions.astype(jnp.int32),
               kv_positions.astype(jnp.int32),
               segment_ids.astype(jnp.int32) if segment_ids is not None else None,
               kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None else None,
               scale, causal, block_q, block_k, block_mask)
    return o.transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             segment_ids=None, kv_segment_ids=None,
                             q_positions=None, kv_positions=None,
                             softmax_scale: Optional[float] = None,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K,
                             block_mask: Optional[BlockMask] = None) -> Tuple:
    """Forward-only variant returning (out [b,s,h,d], lse [b,h,s]) for the
    ring-attention merge. Differentiation is handled by the ring layer."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    bq0, bk0 = min(block_q, sq), min(block_k, sk)
    block_q = fit_block(block_q, sq)
    block_k = fit_block(block_k, sk)
    if (block_q != bq0 and block_q < 128) or (block_k != bk0 and block_k < 128):
        raise ValueError(f"seq lens ({sq},{sk}) fit no lane-aligned block "
                         f"ladder; pad via the bucket ladder")
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    if block_mask is None and causal and q_positions is None \
            and kv_positions is None:
        block_mask = causal_block_mask(sq, sk, block_q, block_k)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if q_positions is None:
        q_positions = jnp.broadcast_to(
            jnp.arange(sq, dtype=jnp.int32) + (sk - sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    o, lse = _fwd(qt, kt, vt, q_positions.astype(jnp.int32),
                  kv_positions.astype(jnp.int32),
                  segment_ids.astype(jnp.int32) if segment_ids is not None else None,
                  kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None else None,
                  scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                  block_mask=block_mask)
    return o.transpose(0, 2, 1, 3), lse
