"""Paged-attention decode Pallas kernel: attend directly over the serving
KV pool's page tables (gather-free decode).

The serving engine's decode step (PR 7 follow-up, closed here) used to
GATHER every slot's pages into a dense [S, max_len, n_kv, hd] view per
layer before attending — three passes over the cache bytes (gather read,
dense write, attention read), most of them over DEAD tail positions.
This kernel walks each slot's page list via scalar-prefetched block
index maps (the splash-attention technique the flash kernel already
uses for its live-pair tables): grid (slot, page_slot), with the K/V
BlockSpec index maps reading `table[s, p]` so each grid step DMAs ONE
page straight from the pool.  Pages past the slot's live length are
scheduled but compute-skipped (`pl.when`); the null page (id 0) that
inactive slots point at is masked the same way the dense path masks it
(position mask over the global key index).

Online-softmax accumulation across a slot's pages mirrors the flash
forward; GQA folds grouped q heads against the pool's kv heads via an
in-VMEM reshape (no materialized repeat).  Decode is forward-only — no
vjp (the training path keeps flash attention).

int8 pages (``HETU_TPU_KV_QUANT=int8``, the PR 9 "exact-fp pages only"
gap closed): the kernel takes the pool's per-head-vector f32 absmax
scales as two extra page-indexed operands and dequantizes each page
IN-VMEM (``k * scale``) right after the DMA — the HBM read is the int8
payload (+ the small scale plane), ~3.9x fewer cache bytes per decode
step than fp32 pages (ops/pallas/traffic.paged_attn_traffic prices it;
`detail.kernels` records the row).  The token K/V scattered pre-kernel
quantize through the SAME blockwise primitives the gather path uses
(comm/compress -> ops/pallas/quant when routed), so pool contents are
bit-identical across the two decode programs.

int4 pages (``HETU_TPU_KV_QUANT=int4``) push the same trick to nibble
storage: the pool holds uint8 payloads of HALF the head dim packed via
`ops/quantization.pack_nibbles` (even index = LOW nibble, values offset
by +8) plus the same per-head-vector f32 scale plane; the kernel unpacks
and dequantizes in-VMEM (``(nibble - 8) * scale``), ~7.5x fewer cache
bytes than fp32 pages at hd=128.

`paged_verify` is the multi-query sibling (spec-decode verification):
q carries C = k+1 query positions per slot, all attending the slot's
pages in ONE launch with per-position causal masks (query i sees keys
at global positions <= positions[s] + i).  Same page walk, same online
softmax with C*nq accumulator rows, same none/int8/int4 page modes —
it replaces the gather program `verify_step_slots` used to dispatch
(three passes over the cache bytes) with one pass over the quantized
pool.

Shape contract (drift-tested against `compatible`/`verify_compatible`):
hd % 128, q heads divide by kv heads, table/positions/q agree on the
slot count, scales present iff quant, pool head dim halved for int4."""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

NEG_INF = -1e30


def _check_pool(q_heads_hd, pool_shape, table_shape, pos_shape, S, *,
                quant: str) -> Tuple[int, int, int]:
    nq, hd = q_heads_hd
    if quant not in ("none", "int8", "int4"):
        raise ValueError(f"paged-attention page mode {quant!r} "
                         "unsupported; known: ('none', 'int8', 'int4')")
    P, ps, n_kv, hd_p = pool_shape
    hd_stored = hd // 2 if quant == "int4" else hd
    if hd_p != hd_stored:
        raise ValueError(f"head dim mismatch: q {hd} expects pool "
                         f"{hd_stored} ({quant} pages), got {hd_p}")
    if nq % n_kv:
        raise ValueError(f"q heads {nq} must divide by kv heads {n_kv}")
    if len(table_shape) != 2 or table_shape[0] != S:
        raise ValueError(f"table {table_shape} must be [S={S}, max_pages]")
    if tuple(pos_shape) != (S,):
        raise ValueError(f"positions {pos_shape} must be [S={S}]")
    if hd % 128:
        raise ValueError(f"head dim {hd} is not lane-aligned (% 128); "
                         f"the gather fallback handles it")
    return P, ps, n_kv


def _check_shapes(q_shape, pool_shape, table_shape, pos_shape, *,
                  quant: str = "none"
                  ) -> Tuple[int, int, int, int, int, int]:
    if len(q_shape) != 3 or len(pool_shape) != 4:
        raise ValueError(f"expected q [S, nq, hd] and pool [P, ps, n_kv, "
                         f"hd], got {q_shape} / {pool_shape}")
    S, nq, hd = q_shape
    P, ps, n_kv = _check_pool((nq, hd), pool_shape, table_shape,
                              pos_shape, S, quant=quant)
    return S, nq, hd, P, ps, n_kv


def _check_shapes_verify(q_shape, pool_shape, table_shape, pos_shape, *,
                         quant: str = "none"
                         ) -> Tuple[int, int, int, int, int, int, int]:
    if len(q_shape) != 4 or len(pool_shape) != 4:
        raise ValueError(f"expected q [S, C, nq, hd] and pool [P, ps, "
                         f"n_kv, hd], got {q_shape} / {pool_shape}")
    S, C, nq, hd = q_shape
    if C < 1:
        raise ValueError(f"verify needs at least one query position, "
                         f"got C={C}")
    P, ps, n_kv = _check_pool((nq, hd), pool_shape, table_shape,
                              pos_shape, S, quant=quant)
    return S, C, nq, hd, P, ps, n_kv


def compatible(q_shape, pool_shape, table_shape, pos_shape, *,
               quant: str = "none") -> bool:
    try:
        _check_shapes(q_shape, pool_shape, table_shape, pos_shape,
                      quant=quant)
        return True
    except ValueError:
        return False


def verify_compatible(q_shape, pool_shape, table_shape, pos_shape, *,
                      quant: str = "none") -> bool:
    try:
        _check_shapes_verify(q_shape, pool_shape, table_shape, pos_shape,
                             quant=quant)
        return True
    except ValueError:
        return False


def _load_page(page_ref, scale_ref, *, quant, ps, n_kv, hd):
    """DMA'd page block -> dequantized f32 [ps, n_kv, hd] in VMEM."""
    x = page_ref[0]
    if quant == "none":
        return x.astype(jnp.float32)
    if quant == "int4":
        # unpack the nibble payload [ps, n_kv, hd//2] (even index = LOW
        # nibble, ops/quantization.pack_nibbles layout, +8 offset)
        p8 = x.astype(jnp.uint8)
        lo = (p8 & 0xF).astype(jnp.int32) - 8
        hi = (p8 >> 4).astype(jnp.int32) - 8
        x = jnp.stack((lo, hi), axis=-1).reshape(ps, n_kv, hd)
    x = x.astype(jnp.float32)
    # one f32 absmax scale per head-vector (the kv_pool blockwise layout)
    return x * scale_ref[0].astype(jnp.float32)[..., None]


def _kernel(*refs, scale, ps, n_kv, group, mp, quant):
    if quant != "none":
        (table_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (table_ref, pos_ref, q_ref, k_ref, v_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    s_idx = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[s_idx]

    # page p holds global positions [p*ps, (p+1)*ps); skip the compute
    # body for wholly-future pages (they are scheduled — the grid is
    # static — but move no math; their DMA reads the null page)
    @pl.when(p * ps <= pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [nq, hd]
        nq, hd = q.shape
        k = _load_page(k_ref, ks_ref, quant=quant, ps=ps, n_kv=n_kv, hd=hd)
        v = _load_page(v_ref, vs_ref, quant=quant, ps=ps, n_kv=n_kv, hd=hd)
        qg = q.reshape(n_kv, group, hd)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [n_kv, g, ps]
        kpos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps), 2)
        s = jnp.where(kpos <= pos, s, NEG_INF)
        sf = s.reshape(nq, ps)

        m_prev = m_scr[:]                               # [nq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(sf, axis=1, keepdims=True))
        p_ = jnp.exp(sf - m_new)                        # [nq, ps]
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p_, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_.reshape(n_kv, group, ps), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # [n_kv, g, hd]
        acc_scr[:] = acc_scr[:] * corr + pv.reshape(nq, hd)
        m_scr[:] = m_new

    @pl.when(p == mp - 1)
    def _fin():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _verify_kernel(*refs, scale, C, ps, n_kv, group, mp, quant):
    """Multi-query form: the slot's q block carries C = k+1 positions;
    accumulator rows are laid out (n_kv, C, group) so the grouped-GQA
    contraction stays a single batched dot per page."""
    if quant != "none":
        (table_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (table_ref, pos_ref, q_ref, k_ref, v_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    s_idx = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[s_idx]

    # the LAST query position (pos + C - 1) decides which pages hold any
    # visible keys; wholly-future pages move no math
    @pl.when(p * ps <= pos + (C - 1))
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [C, nq, hd]
        nq, hd = q.shape[1], q.shape[2]
        k = _load_page(k_ref, ks_ref, quant=quant, ps=ps, n_kv=n_kv, hd=hd)
        v = _load_page(v_ref, vs_ref, quant=quant, ps=ps, n_kv=n_kv, hd=hd)
        rows = n_kv * C * group
        qg = q.reshape(C, n_kv, group, hd).transpose(1, 0, 2, 3) \
              .reshape(n_kv, C * group, hd)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [n_kv, C*g, ps]
        # per-position causal mask: query i sees keys at global
        # positions <= pos + i
        ci = jax.lax.broadcasted_iota(jnp.int32, (1, C, 1, ps), 1)
        kp = p * ps + jax.lax.broadcasted_iota(jnp.int32, (1, C, 1, ps), 3)
        s = jnp.where(kp <= pos + ci, s.reshape(n_kv, C, group, ps),
                      NEG_INF)
        sf = s.reshape(rows, ps)

        m_prev = m_scr[:]                               # [rows, 1]
        m_new = jnp.maximum(m_prev, jnp.max(sf, axis=1, keepdims=True))
        p_ = jnp.exp(sf - m_new)                        # [rows, ps]
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p_, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_.reshape(n_kv, C * group, ps), v,
            (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # [n_kv, C*g, hd]
        acc_scr[:] = acc_scr[:] * corr + pv.reshape(rows, hd)
        m_scr[:] = m_new

    @pl.when(p == mp - 1)
    def _fin():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        hd = o_ref.shape[3]
        o = (acc_scr[:] / l_safe).reshape(n_kv, C, group, hd) \
            .transpose(1, 0, 2, 3).reshape(C, n_kv * group, hd)
        o_ref[0] = o.astype(o_ref.dtype)


def _resolve_quant(quant, k_scale, v_scale):
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if quant is None:
        quant = "int8" if k_scale is not None else "none"
    if (quant != "none") != (k_scale is not None):
        raise ValueError(f"page mode {quant!r} needs scales iff "
                         "quantized (int8/int4)")
    return quant


def paged_attention(q, k_pool, v_pool, table, positions, *,
                    softmax_scale: Optional[float] = None,
                    k_scale=None, v_scale=None, quant=None):
    """Decode attention over paged KV.  q: [S, nq, hd] (one token per
    slot); k_pool/v_pool: [P, page_size, n_kv, hd] (page 0 = the null
    page); table: [S, max_pages] int32 page ids; positions: [S] int32 —
    slot s attends over global positions <= positions[s].  int8 pools
    pass their per-head-vector f32 scales [P, page_size, n_kv] as
    k_scale/v_scale and dequantize in-kernel; int4 pools additionally
    pass ``quant="int4"`` (uint8 nibble payloads, pool head dim hd//2).
    Returns [S, nq, hd].  Raises ValueError on shapes outside
    `compatible` (the dense-gather fallback in models/generation
    handles those)."""
    quant = _resolve_quant(quant, k_scale, v_scale)
    S, nq, hd, P, ps, n_kv = _check_shapes(
        q.shape, k_pool.shape, table.shape, positions.shape, quant=quant)
    if quant != "none" and tuple(k_scale.shape) != (P, ps, n_kv):
        raise ValueError(f"scales {k_scale.shape} must be "
                         f"[P={P}, ps={ps}, n_kv={n_kv}]")
    mp = table.shape[1]
    group = nq // n_kv
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    hd_p = k_pool.shape[-1]

    page_spec = pl.BlockSpec((1, ps, n_kv, hd_p),
                             lambda s, p, tab, pos: (tab[s, p], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, nq, hd), lambda s, p, tab, pos: (s, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quant != "none":
        scale_spec = pl.BlockSpec(
            (1, ps, n_kv), lambda s, p, tab, pos: (tab[s, p], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nq, hd),
                               lambda s, p, tab, pos: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nq, 1), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
            pltpu.VMEM((nq, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, scale=scale, ps=ps, n_kv=n_kv,
                          group=group, mp=mp, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nq, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(table.astype(jnp.int32), positions.astype(jnp.int32), *operands)


def paged_verify(q, k_pool, v_pool, table, positions, *,
                 softmax_scale: Optional[float] = None,
                 k_scale=None, v_scale=None, quant=None):
    """Multi-query verify attention over paged KV (spec decoding).
    q: [S, C, nq, hd] — slot s's C = k+1 query positions sit at global
    positions positions[s]..positions[s]+C-1, each attending causally
    over the slot's pages; pools/table/scales exactly as
    `paged_attention`.  Returns [S, C, nq, hd].  Raises ValueError on
    shapes outside `verify_compatible` (the gather verify program in
    models/generation handles those)."""
    quant = _resolve_quant(quant, k_scale, v_scale)
    S, C, nq, hd, P, ps, n_kv = _check_shapes_verify(
        q.shape, k_pool.shape, table.shape, positions.shape, quant=quant)
    if quant != "none" and tuple(k_scale.shape) != (P, ps, n_kv):
        raise ValueError(f"scales {k_scale.shape} must be "
                         f"[P={P}, ps={ps}, n_kv={n_kv}]")
    mp = table.shape[1]
    group = nq // n_kv
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    hd_p = k_pool.shape[-1]
    rows = n_kv * C * group

    page_spec = pl.BlockSpec((1, ps, n_kv, hd_p),
                             lambda s, p, tab, pos: (tab[s, p], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, C, nq, hd), lambda s, p, tab, pos: (s, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quant != "none":
        scale_spec = pl.BlockSpec(
            (1, ps, n_kv), lambda s, p, tab, pos: (tab[s, p], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, C, nq, hd),
                               lambda s, p, tab, pos: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_verify_kernel, scale=scale, C=C, ps=ps,
                          n_kv=n_kv, group=group, mp=mp, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, nq, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(table.astype(jnp.int32), positions.astype(jnp.int32), *operands)
