"""Fused AdamW update Pallas kernel (the optimizer's HBM diet.

Rebuild of the reference's fused Adam (reference:
hetu/impl/kernel/Optimizers.cu — one kernel reads p/g/m/v and writes
p'/m'/v').  The XLA lowering of `optim/optimizer.AdamW.update` is a
per-leaf chain of elementwise ops; XLA fuses most of it, but the
observatory's traffic model (ops/pallas/traffic.py) still charges the
chain its materialized intermediates (mhat, vhat, the decay product),
and the fused kernel pins the floor: read p+g+m+v once, write
p'+m'+v' once, nothing else.

The math is EXACTLY the optimizer's (f32 master arithmetic, bias
corrections c1/c2 computed OUTSIDE and passed as traced scalars with
the lr, so schedules stay host-side closures): m' = b1*m + (1-b1)*g;
v' = b2*v + (1-b2)*g^2; p' = p - lr*((m'/c1)/(sqrt(v'/c2)+eps) +
wd*p).  b1/b2/eps/wd are static (they pick the compiled kernel, like
every other hyperparameter-shaped knob).

Shape contract (drift-tested against `compatible`): the four leaf
buffers share one shape whose element count is lane-aligned (% 128);
ragged leaves (biases, norm gains) keep the XLA path."""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

#: leaf rows (of 128 lanes) handled per grid step
_ROWS = 256


def _check_shapes(p_shape, g_shape, m_shape, v_shape) -> int:
    shapes = (tuple(p_shape), tuple(g_shape), tuple(m_shape),
              tuple(v_shape))
    if len(set(shapes)) != 1:
        raise ValueError(f"p/g/m/v shapes must match, got {shapes}")
    n = 1
    for d in p_shape:
        n *= int(d)
    if n == 0 or n % 128:
        raise ValueError(f"leaf of {n} elements is not lane-aligned "
                         f"(% 128); the XLA update handles it")
    return n


def compatible(p_shape, g_shape=None, m_shape=None, v_shape=None) -> bool:
    g_shape = p_shape if g_shape is None else g_shape
    m_shape = p_shape if m_shape is None else m_shape
    v_shape = p_shape if v_shape is None else v_shape
    try:
        _check_shapes(p_shape, g_shape, m_shape, v_shape)
        return True
    except ValueError:
        return False


def _fit_rows(nb: int) -> int:
    r = min(nb, _ROWS)
    while nb % r:
        r -= 1
    return r


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                 np_ref, nm_ref, nv_ref, *, b1, b2, eps, wd):
    lr = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * jnp.square(g)
    mhat = m / c1
    vhat = v / c2
    pf = p_ref[...].astype(jnp.float32)
    newp = pf - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * pf)
    np_ref[...] = newp.astype(np_ref.dtype)
    nm_ref[...] = m
    nv_ref[...] = v


def adam_update(p, g, m, v, lr, c1, c2, *, b1: float, b2: float,
                eps: float, weight_decay: float
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One leaf's fused AdamW step -> (new_p, new_m, new_v).  lr/c1/c2
    are traced f32 scalars (step-dependent); b1/b2/eps/weight_decay are
    static.  Raises ValueError on shapes outside `compatible`."""
    n = _check_shapes(p.shape, g.shape, m.shape, v.shape)
    nb = n // 128
    rows = _fit_rows(nb)
    sc = jnp.stack([jnp.asarray(lr, jnp.float32),
                    jnp.asarray(c1, jnp.float32),
                    jnp.asarray(c2, jnp.float32)]).reshape(1, 3)
    blk = pl.BlockSpec((rows, 128), lambda i: (i, 0))
    sc_blk = pl.BlockSpec((1, 3), lambda i: (0, 0))
    newp, newm, newv = pl.pallas_call(
        functools.partial(_adam_kernel, b1=float(b1), b2=float(b2),
                          eps=float(eps), wd=float(weight_decay)),
        grid=(nb // rows,),
        in_specs=[blk, blk, blk, blk, sc_blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((nb, 128), p.dtype),
                   jax.ShapeDtypeStruct((nb, 128), jnp.float32),
                   jax.ShapeDtypeStruct((nb, 128), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(p.reshape(nb, 128), g.reshape(nb, 128),
      m.astype(jnp.float32).reshape(nb, 128),
      v.astype(jnp.float32).reshape(nb, 128), sc)
    return (newp.reshape(p.shape), newm.reshape(p.shape),
            newv.reshape(p.shape))
