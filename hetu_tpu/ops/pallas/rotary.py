"""Fused rotary-embedding Pallas kernel: RoPE applied to q AND k in one
pass (reference: hetu/impl/kernel/rotary.cu — the fused varlen rotary).

The XLA composition (`ops.rotary.apply_rotary` called once for q, once
for k) gathers the cos/sin tables twice and round-trips each half-split
product through HBM; this kernel reads the per-position cos/sin rows
ONCE and rotates both tensors in VMEM.  The rotation is linear, so the
custom-vjp backward is the SAME kernel with the sin table negated
(rotation by -theta) — no residuals beyond the tables.

Layout: q [b, s, nq, hd], k [b, s, nk, hd]; cos/sin arrive PRE-GATHERED
per (batch, position) as [b, s, hd//2] (the dispatcher in `ops.rotary`
does the position_ids lookup — one tiny gather feeding one fused pass).

Shape contract (drift-tested against `compatible`): hd must be even and
lane-aligned (% 128); b/s/heads are free (s is row-blocked to a VMEM
budget)."""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

_VMEM_SEQ_BUDGET = 512 * 1024


def _check_shapes(q_shape, k_shape) -> Tuple[int, int, int, int, int]:
    if len(q_shape) != 4 or len(k_shape) != 4:
        raise ValueError(f"expected [b, s, heads, hd], got {q_shape} / "
                         f"{k_shape}")
    b, s, nq, hd = q_shape
    if k_shape[0] != b or k_shape[1] != s or k_shape[3] != hd:
        raise ValueError(f"q/k disagree outside the head dim: {q_shape} "
                         f"vs {k_shape}")
    if hd % 2:
        raise ValueError(f"head dim {hd} must be even for the half-split "
                         f"rotation")
    if hd % 128:
        raise ValueError(f"head dim {hd} is not lane-aligned (% 128); "
                         f"the XLA fallback handles it")
    return b, s, nq, k_shape[2], hd


def compatible(q_shape, k_shape) -> bool:
    try:
        _check_shapes(q_shape, k_shape)
        return True
    except ValueError:
        return False


def _fit_seq(s: int, width: int) -> int:
    """Largest divisor of s keeping one f32 [S, width] buffer in budget."""
    cap = max(1, _VMEM_SEQ_BUDGET // max(width * 4, 1))
    r = min(s, cap)
    while s % r:
        r -= 1
    return r


def _kernel(cos_ref, sin_ref, q_ref, k_ref, qo_ref, ko_ref, *, d2):
    cos = cos_ref[0][:, None, :]                       # [S, 1, hd/2]
    sin = sin_ref[0][:, None, :]

    def rot(x):
        xf = x.astype(jnp.float32)
        x1 = xf[..., :d2]
        x2 = xf[..., d2:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)

    qo_ref[...] = rot(q_ref[0])[None].astype(qo_ref.dtype)
    ko_ref[...] = rot(k_ref[0])[None].astype(ko_ref.dtype)


def _apply(q, k, cos_t, sin_t):
    b, s, nq, nk, hd = _check_shapes(q.shape, k.shape)
    d2 = hd // 2
    S = _fit_seq(s, max(nq, nk) * hd)
    kern = functools.partial(_kernel, d2=d2)
    cs_spec = pl.BlockSpec((1, S, d2), lambda bi, si: (bi, si, 0))
    q_spec = pl.BlockSpec((1, S, nq, hd), lambda bi, si: (bi, si, 0, 0))
    k_spec = pl.BlockSpec((1, S, nk, hd), lambda bi, si: (bi, si, 0, 0))
    return pl.pallas_call(
        kern,
        grid=(b, s // S),
        in_specs=[cs_spec, cs_spec, q_spec, k_spec],
        out_specs=[q_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(cos_t, sin_t, q, k)


@jax.custom_vjp
def _rotary_qk(q, k, cos_t, sin_t):
    return _apply(q, k, cos_t, sin_t)


def _rotary_fwd(q, k, cos_t, sin_t):
    return _apply(q, k, cos_t, sin_t), (cos_t, sin_t)


def _rotary_bwd(res, cts):
    cos_t, sin_t = res
    dqo, dko = cts
    # rotation is orthogonal: the vjp rotates the cotangents by -theta
    dq, dk = _apply(dqo, dko, cos_t, -sin_t)
    return dq, dk, None, None


_rotary_qk.defvjp(_rotary_fwd, _rotary_bwd)


def fused_rotary_qk(q, k, cos_t, sin_t):
    """Rotate q [b,s,nq,hd] and k [b,s,nk,hd] by the pre-gathered
    per-position tables cos_t/sin_t [b,s,hd//2] in one fused pass.
    Raises ValueError on shapes outside `compatible`."""
    return _rotary_qk(q, k, cos_t, sin_t)
