"""Fused lm_head + filter + sample Pallas kernel (the decode epilogue).

The serving sampler (serving/sampling.py) used to materialize the full
``[rows, vocab]`` logits in HBM — lm_head matmul write, then a
sort-based top-k/top-p filter chain reading and writing the whole vocab
plane several times, then the categorical draw.  For speculative
verification that plane is ``[slots, k+1, vocab]`` per step, and every
byte of it is consumed exactly once.  This kernel takes the LAST-LAYER
HIDDEN rows instead: one grid step per row does the lm_head slice
matmul in-VMEM, applies temperature / top-k / top-p exactly as
``serving/sampling.filtered_logits`` does, adds Gumbel noise from a
counter-based hash of the row's (seed, absolute_position) fold_in key,
and writes back ONE int32 token — the vocab plane never touches HBM.

Determinism contract: the per-row key WORDS are
``jax.random.key_data(fold_in(jax.random.key(seed), position))`` — the
exact derivation the engine always used — and `hash_uniform` /
`gumbel` below are pure jnp, shared verbatim by the XLA fallback in
``serving/sampling.sample_tokens``.  Kernel and fallback therefore draw
the SAME noise and pick the SAME token for the same (seed, position);
rows with temperature 0 take the plain argmax of the unfiltered logits
(greedy stays greedy).

Filter equivalence without a sort: top-k's kth value and the nucleus
cutoff are found by 32-step bisection over the MONOTONE uint32 image of
the f32 logits (sign-flip bitcast), which converges to the EXACT values
the sort-based filter reads off — including the duplicate-value
semantics (a kept value keeps all its duplicates).

Shape contract (drift-tested against `compatible`): hidden [R, H] with
H % 128 == 0, head w [H, V] with V % 128 == 0, and H*V small enough
that the head slice fits VMEM (realistic full vocabularies fall back to
the XLA path; the fused win targets the draft/verify models)."""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

#: the filter mask value (matches serving/sampling and generate())
_NEG = -1e30

#: head-slice VMEM budget: H * V f32 elements must fit comfortably
_MAX_W_ELEMS = 2 * 1024 * 1024


def hash_uniform(w0, w1, idx, lane: int = 0):
    """Counter-based uniform draws in (0, 1): a murmur3-style finalizer
    over (key word pair, counter index, stream lane).  Pure jnp — the
    SAME ops run in-kernel and in the XLA fallback, so both paths draw
    identical noise for identical (seed, position) keys.  `lane` picks
    an independent stream (the stochastic accept/resample draws in
    serving/spec_decode use lanes 1 and 2)."""
    w0 = w0.astype(jnp.uint32)
    w1 = w1.astype(jnp.uint32)
    x = w0 ^ (idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)) \
        ^ jnp.uint32((lane * 0x85EBCA77) & 0xFFFFFFFF)
    x = x + w1
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # 24-bit mantissa uniform, centered off 0 and 1 (log-safe)
    return (x >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24)) \
        + jnp.float32(0.5 / (1 << 24))


def gumbel(w0, w1, idx, lane: int = 0):
    """Gumbel(0, 1) noise from `hash_uniform`; argmax(logits + gumbel)
    is an exact categorical draw."""
    return -jnp.log(-jnp.log(hash_uniform(w0, w1, idx, lane)))


def _check_shapes(hidden_shape, w_shape) -> Tuple[int, int, int]:
    if len(hidden_shape) != 2 or len(w_shape) != 2:
        raise ValueError(f"expected hidden [R, H] and head [H, V], got "
                         f"{hidden_shape} / {w_shape}")
    R, H = hidden_shape
    H_w, V = w_shape
    if H_w != H:
        raise ValueError(f"hidden dim mismatch: hidden {H} vs head {H_w}")
    if H % 128 or V % 128:
        raise ValueError(f"hidden {H} and vocab {V} must be lane-aligned "
                         f"(% 128); the XLA sampler handles the rest")
    if H * V > _MAX_W_ELEMS:
        raise ValueError(f"head slice {H}x{V} exceeds the VMEM budget "
                         f"({_MAX_W_ELEMS} elems); the XLA sampler "
                         f"handles it")
    return R, H, V


def compatible(hidden_shape, w_shape) -> bool:
    try:
        _check_shapes(hidden_shape, w_shape)
        return True
    except ValueError:
        return False


def _sort_key(x):
    """f32 -> uint32, strictly monotone (the radix-sort trick): bisection
    over this image terminates on EXACT logit values in 32 steps."""
    b = jax.lax.bitcast_convert_type(x, jnp.int32)
    flip = b.astype(jnp.uint32) ^ jnp.uint32(0x80000000)
    inv = (~b).astype(jnp.uint32)
    return jnp.where(b >= 0, flip, inv)


def _first_argmax(x, iota, V):
    """First index attaining the max — jnp.argmax's tie rule."""
    m = jnp.max(x)
    return jnp.min(jnp.where(x == m, iota, V)).astype(jnp.int32)


def _kth_largest_key(keys, k_eff):
    """Largest uint32 threshold t with count(keys >= t) >= k_eff — the
    key of the k-th largest logit (duplicates counted like the sort)."""
    lo = jnp.min(keys)
    hi = jnp.max(keys)

    def body(_, c):
        lo, hi = c
        mid = lo + ((hi - lo + jnp.uint32(1)) >> 1)
        ok = jnp.sum((keys >= mid).astype(jnp.int32)) >= k_eff
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - jnp.uint32(1))

    lo, _ = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo


def _nucleus_key(keys, e, z, top_p):
    """Smallest uint32 threshold t whose strictly-greater kept mass
    sum(e[keys > t]) / z drops below top_p — the value-duplicate-exact
    form of filtered_logits' sorted-cumsum cutoff."""
    lo = jnp.min(keys)
    hi = jnp.max(keys)

    def body(_, c):
        lo, hi = c
        mid = lo + ((hi - lo) >> 1)
        s_gt = jnp.sum(jnp.where(keys > mid, e, 0.0))
        q = s_gt / z < top_p
        return jnp.where(q, lo, mid + jnp.uint32(1)), jnp.where(q, mid, hi)

    _, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return hi


def _sample_kernel(h_ref, w_ref, wd_ref, t_ref, k_ref, p_ref, o_ref, *, V):
    h = h_ref[...].astype(jnp.float32)                   # [1, H]
    w = w_ref[...].astype(jnp.float32)                   # [H, V]
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32)  # [1, V]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, V), 1)
    greedy = _first_argmax(logits, iota, V)

    temp = t_ref[0, 0]
    safe_t = jnp.where(temp > 0, temp, 1.0)
    scaled = logits / safe_t
    # +0.0 canonicalizes -0.0 so the uint32 image is monotone over ==
    keys = _sort_key(scaled + 0.0)

    k_in = k_ref[0, 0]
    k_eff = jnp.minimum(jnp.where(k_in > 0, k_in, V), V)
    kth_key = _kth_largest_key(keys, k_eff)
    keep = keys >= kth_key
    filt = jnp.where(keep, scaled, _NEG)

    top_p = p_ref[0, 0]
    p_on = (top_p > 0.0) & (top_p < 1.0)
    m_f = jnp.max(scaled)                     # top-1 is always kept
    e = jnp.where(keep, jnp.exp(scaled - m_f), 0.0)
    z = jnp.sum(e)
    t_star = _nucleus_key(keys, e, z, top_p)
    filt = jnp.where(p_on & (keys < t_star), _NEG, filt)

    g = gumbel(wd_ref[0, 0], wd_ref[0, 1], iota)
    sampled = _first_argmax(filt + g, iota, V)
    o_ref[0, 0] = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)


def fused_sample(hidden, w, key_words, temps, top_ks, top_ps):
    """hidden [R, H] + head w [H, V] -> sampled tokens [R] int32 in one
    launch (no [R, V] logits in HBM).  key_words: [R, 2] uint32 — the
    raw data of each row's fold_in(key(seed), position) key; temps /
    top_ks / top_ps: [R] per-row sampling params (temp 0 = greedy row).
    Raises ValueError on shapes outside `compatible`."""
    R, H, V = _check_shapes(hidden.shape, w.shape)
    if tuple(key_words.shape) != (R, 2):
        raise ValueError(f"key_words {key_words.shape} must be [R={R}, 2]")
    for name, arr in (("temps", temps), ("top_ks", top_ks),
                      ("top_ps", top_ps)):
        if tuple(arr.shape) != (R,):
            raise ValueError(f"{name} {arr.shape} must be [R={R}]")
    row = pl.BlockSpec((1, H), lambda r: (r, 0))
    head = pl.BlockSpec((H, V), lambda r: (0, 0))
    words = pl.BlockSpec((1, 2), lambda r: (r, 0))
    scalar = pl.BlockSpec((1, 1), lambda r: (r, 0))
    out = pl.pallas_call(
        functools.partial(_sample_kernel, V=V),
        grid=(R,),
        in_specs=[row, head, words, scalar, scalar, scalar],
        out_specs=pl.BlockSpec((1, 1), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(hidden, w, key_words.astype(jnp.uint32),
      temps.astype(jnp.float32).reshape(R, 1),
      top_ks.astype(jnp.int32).reshape(R, 1),
      top_ps.astype(jnp.float32).reshape(R, 1))
    return out[:, 0]
