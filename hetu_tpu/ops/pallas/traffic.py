"""Analytic HBM-traffic model for the fused-kernel layer.

Every Pallas kernel in this package earns its place by cutting HBM round
trips, not FLOPs — so its win is provable WITHOUT hardware by counting
the bytes each path moves (the comm/wire.py pattern: the TPU tunnel has
been down since bench round 3 and every perf claim must be analytic).

For each kernel this module prices two paths:

  * ``unfused``: the XLA op chain the dispatcher falls back to, counted
    op by op — each elementwise op reads its operands and writes its
    result to HBM, reductions read their operand and write the (small)
    reduced row.  Activations move at the compute dtype (`elem_bytes`);
    the seed norm/rotary implementations upcast to float32, so their
    intermediates move at 4 bytes — exactly what the fallback code does.
    XLA's fuser would collapse SOME of these round trips; the op-chain
    count is the reproducible upper bound the docs table and the
    `detail.kernels` BENCH record use, and the chain is listed per op so
    the model is auditable (docs/kernels.md).
  * ``fused``: the Pallas kernel — one read of each input, one write of
    each output, statistics live in VMEM.

`reduction` = unfused / fused is the headline byte cut per kernel
(`tools_bench_kernels.py` prints it; the acceptance gate pins
residual+RMSNorm >= 3x at the bench config's bf16 activations).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: bytes of a float32 intermediate (the upcast the seed fallbacks do)
_F32 = 4.0

Chain = List[Tuple[str, float, float]]     # (op, read_bytes, write_bytes)


def _report(kernel: str, chain: Chain, fused_read: float,
            fused_write: float) -> Dict[str, Any]:
    ur = sum(r for _, r, _ in chain)
    uw = sum(w for _, _, w in chain)
    fused = fused_read + fused_write
    unfused = ur + uw
    return {
        "kernel": kernel,
        "unfused_bytes": unfused,
        "unfused_read_bytes": ur,
        "unfused_write_bytes": uw,
        "fused_bytes": fused,
        "fused_read_bytes": fused_read,
        "fused_write_bytes": fused_write,
        "reduction": unfused / fused if fused else float("inf"),
        "chain": [{"op": op, "read": r, "write": w}
                  for op, r, w in chain],
    }


def norm_traffic(tokens: int, hidden: int, *, elem_bytes: float = 2.0,
                 kind: str = "rms") -> Dict[str, Any]:
    """Fused residual-add + RMSNorm/LayerNorm vs the seed chain
    (`x + h` -> `ops.norms.rms_norm`): the fallback adds in the compute
    dtype, then upcasts and runs the stats/normalize/weight chain in
    float32 (ops/norms.py)."""
    n = float(tokens) * hidden
    e = float(elem_bytes)
    t = float(tokens) * _F32               # one f32 scalar per row
    chain: Chain = [
        ("residual_add", 2 * e * n, e * n),
        ("upcast_f32", e * n, _F32 * n) if e != _F32 else
        ("upcast_f32", 0.0, 0.0),
    ]
    if kind == "ln":
        chain += [("mean_reduce", _F32 * n, t),
                  ("center", _F32 * n + t, _F32 * n)]
    chain += [
        ("square", _F32 * n, _F32 * n),
        ("var_reduce", _F32 * n, t),
        ("rsqrt_scale", _F32 * n + t, _F32 * n),
        ("weight_mul", _F32 * n + _F32 * hidden, _F32 * n),
    ]
    if kind == "ln":
        chain.append(("bias_add", _F32 * n + _F32 * hidden, _F32 * n))
    chain.append(("downcast", _F32 * n, e * n) if e != _F32 else
                 ("downcast", 0.0, 0.0))
    # fused: read x and h once, write y AND the residual stream s once
    return _report(f"norm[{kind}]" if kind != "rms" else "norm",
                   chain, 2 * e * n, 2 * e * n)


def swiglu_traffic(tokens: int, inner: int, *,
                   elem_bytes: float = 2.0) -> Dict[str, Any]:
    """silu(gate) * up: the fallback chain stays in the compute dtype
    (ops.activations.silu is jax.nn.silu on the input dtype)."""
    n = float(tokens) * inner
    e = float(elem_bytes)
    chain: Chain = [
        ("sigmoid", e * n, e * n),
        ("gate_mul", 2 * e * n, e * n),
        ("up_mul", 2 * e * n, e * n),
    ]
    return _report("swiglu", chain, 2 * e * n, e * n)


def rotary_traffic(batch: int, seq: int, q_heads: int, kv_heads: int,
                   head_dim: int, *, elem_bytes: float = 2.0
                   ) -> Dict[str, Any]:
    """RoPE on q AND k: the fallback is two `ops.rotary.apply_rotary`
    calls, each upcasting to f32, forming the four half-products, the
    two sub/adds, the concat, and the downcast — and each gathering the
    cos/sin tables separately."""
    e = float(elem_bytes)
    tables = 2.0 * batch * seq * (head_dim // 2) * _F32     # cos + sin

    def one_call(heads: int) -> Chain:
        n = float(batch) * seq * heads * head_dim
        return [
            ("upcast_f32", e * n, _F32 * n),
            ("half_products", 2 * _F32 * n + tables, 2 * _F32 * n),
            ("sub_add", 2 * _F32 * n, _F32 * n),
            ("concat", _F32 * n, _F32 * n),
            ("downcast", _F32 * n, e * n),
        ]

    chain = ([("q_" + op, r, w) for op, r, w in one_call(q_heads)]
             + [("k_" + op, r, w) for op, r, w in one_call(kv_heads)])
    nq = float(batch) * seq * q_heads * head_dim
    nk = float(batch) * seq * kv_heads * head_dim
    # fused: q + k + the tables read once, q + k written once
    return _report("rotary", chain,
                   e * (nq + nk) + tables, e * (nq + nk))


def quant_traffic(n: int, block_size: int, *, bits: int = 8
                  ) -> Dict[str, Any]:
    """Blockwise quantize feeding the compressed collectives: the
    fallback chain is abs -> blockmax -> div -> round -> clip -> cast
    over the f32 flat buffer (comm/compress.quantize_blockwise)."""
    nf = float(n)
    scales = nf / block_size * _F32
    chain: Chain = [
        ("abs", _F32 * nf, _F32 * nf),
        ("blockmax_reduce", _F32 * nf, scales),
        ("div", _F32 * nf + scales, _F32 * nf),
        ("round", _F32 * nf, _F32 * nf),
        ("clip", _F32 * nf, _F32 * nf),
        ("cast_int8", _F32 * nf, 1.0 * nf),
    ]
    return _report("quant", chain, _F32 * nf, 1.0 * nf + scales)


def flash_traffic(batch: int, seq: int, heads: int, head_dim: int, *,
                  elem_bytes: float = 2.0) -> Dict[str, Any]:
    """Flash attention vs the dense composition: the dense path
    materializes the [b, h, s, s] score matrix in f32 twice (scores,
    softmax) and reads it back for the p@v contraction."""
    e = float(elem_bytes)
    s2 = float(batch) * heads * seq * seq
    io = float(batch) * seq * heads * head_dim
    chain: Chain = [
        ("qk_scores", 2 * e * io, _F32 * s2),
        ("softmax", 2 * _F32 * s2, _F32 * s2),     # max/denom + normalize
        ("pv", _F32 * s2 + e * io, e * io),
    ]
    # fused: q, k, v read once; out + the per-row lse written
    lse = float(batch) * heads * seq * _F32
    return _report("flash", chain, 3 * e * io, e * io + lse)


def paged_attn_traffic(slots: int, max_pages: int, page_size: int,
                       kv_heads: int, head_dim: int, *,
                       elem_bytes: float = 4.0,
                       quant: str = "none") -> Dict[str, Any]:
    """Paged decode vs the gather path: the fallback gathers every
    slot's pages into a dense [S, max_len] view (read pool, write
    dense) and the attention reads the dense view back — three passes
    over the cache bytes.  The kernel DMAs each scheduled page once.

    ``quant="int8"`` prices the int8-page mode (serving/kv_pool.py:
    1 byte/elem + one f32 scale per head-vector): the kernel's read is
    the quantized payload, while the gather fallback additionally
    materializes the DEQUANTIZED dense view at the compute width — the
    in-kernel dequantize earns its keep on top of the payload cut.
    ``quant="int4"`` halves the payload again (two values per byte,
    same per-head-vector f32 scale)."""
    elems = 2.0 * slots * max_pages * page_size * kv_heads * head_dim
    e = float(elem_bytes)
    if quant in ("int8", "int4"):
        cache_q = elems * _kv_payload_bytes(quant, head_dim)
        chain: Chain = [
            ("gather_pages", cache_q, elems * e),   # dequantized dense
            ("attend_dense", elems * e, 0.0),
        ]
        return _report(f"paged_attn_{quant}", chain, cache_q, 0.0)
    cache = elems * e
    chain = [
        ("gather_pages", cache, cache),
        ("attend_dense", cache, 0.0),
    ]
    return _report("paged_attn", chain, cache, 0.0)


def _kv_payload_bytes(quant: str, head_dim: int) -> float:
    """Quantized-page bytes per cache ELEMENT (payload + the f32
    per-head-vector scale amortized over head_dim) — mirrors
    serving/kv_pool.kv_bytes_per_token."""
    payload = 0.5 if quant == "int4" else 1.0
    return payload + _F32 / head_dim


def paged_verify_traffic(slots: int, k: int, max_pages: int,
                         page_size: int, kv_heads: int, head_dim: int, *,
                         elem_bytes: float = 4.0,
                         quant: str = "none") -> Dict[str, Any]:
    """Multi-query verify decode (ops/pallas/paged_attention.paged_verify)
    vs the gather path: the fallback gathers every slot's pages into a
    dense [S, max_len] view and attends the k+1 query positions against
    it — the SAME three passes over the cache bytes as single-query
    decode (the dense view doesn't get cheaper because more queries read
    it).  The kernel DMAs each scheduled page once and shares it across
    all k+1 query positions in VMEM, so its cache read is IDENTICAL to
    the single-token kernel's: the verify step's extra queries ride
    free.  Quantized pages ("int8"/"int4") keep the payload cut on top;
    the gather fallback still materializes the dequantized dense view at
    the compute width."""
    if k < 1:
        raise ValueError(f"spec_k must be >= 1, got {k}")
    elems = 2.0 * slots * max_pages * page_size * kv_heads * head_dim
    e = float(elem_bytes)
    # the k+1 query/output vectors are noise next to the cache bytes but
    # the model counts them (auditable, not rounded away)
    qio = float(slots) * (k + 1) * kv_heads * head_dim * e
    if quant in ("int8", "int4"):
        cache_q = elems * _kv_payload_bytes(quant, head_dim)
        chain: Chain = [
            ("gather_pages", cache_q, elems * e),
            ("attend_dense", elems * e + qio, qio),
        ]
        return _report(f"paged_verify_{quant}", chain, cache_q + qio, qio)
    cache = elems * e
    chain = [
        ("gather_pages", cache, cache),
        ("attend_dense", cache + qio, qio),
    ]
    return _report("paged_verify", chain, cache + qio, qio)


def sample_traffic(rows: int, hidden: int, vocab: int, *,
                   elem_bytes: float = 2.0) -> Dict[str, Any]:
    """Fused sampling epilogue (ops/pallas/sample.py) vs the unfused
    verify tail: lm_head matmul materializing the [rows, vocab] f32
    logit grid in HBM, then the filter chain over it (temperature scale,
    the top-k/top-p sort + masks of serving/sampling.filtered_logits),
    the Gumbel add and the argmax.  The kernel streams vocab tiles
    through VMEM — hidden and the lm_head weight are read once, only
    the [rows] token ids ever hit HBM."""
    e = float(elem_bytes)
    nv = float(rows) * vocab
    h_in = float(rows) * hidden * e
    w = float(hidden) * vocab * e
    toks = float(rows) * _F32
    chain: Chain = [
        ("lm_head_matmul", h_in + w, _F32 * nv),
        ("temp_scale", _F32 * nv, _F32 * nv),
        ("topk_sort", _F32 * nv, _F32 * nv),
        ("topk_mask", 2 * _F32 * nv, _F32 * nv),
        ("softmax_cumsum", _F32 * nv, _F32 * nv),
        ("topp_mask", 2 * _F32 * nv, _F32 * nv),
        ("gumbel_add", _F32 * nv, _F32 * nv),
        ("argmax", _F32 * nv, toks),
    ]
    return _report("sample", chain, h_in + w, toks)


def adam_traffic(n_params: int, *, param_bytes: float = 4.0
                 ) -> Dict[str, Any]:
    """Fused AdamW update (ops/pallas/adam.py) vs the XLA op chain of
    optim/optimizer.AdamW.update: per step the chain materializes the
    two moment updates, the bias-corrected mhat/vhat, the denominator
    and the final update — each a params-sized f32 round trip.  The
    kernel reads p/g/m/v once and writes p'/m'/v' once."""
    n = float(n_params)
    pb = float(param_bytes)
    chain: Chain = [
        ("m_update", 2 * _F32 * n, _F32 * n),        # b1*m + (1-b1)*g
        ("v_update", 2 * _F32 * n, _F32 * n),        # b2*v + (1-b2)*g^2
        ("mhat", _F32 * n, _F32 * n),
        ("vhat", _F32 * n, _F32 * n),
        ("denom", _F32 * n, _F32 * n),               # sqrt(vhat) + eps
        ("update", 2 * _F32 * n + pb * n, pb * n),   # mhat/denom + wd*p
    ]
    return _report("adam", chain,
                   pb * n + 3 * _F32 * n,            # p + g + m + v
                   pb * n + 2 * _F32 * n)            # p' + m' + v'


def fused_verify_chain(slots: int, k: int, max_pages: int, page_size: int,
                       kv_heads: int, head_dim: int, hidden: int,
                       vocab: int, *, num_layers: int = 1,
                       elem_bytes: float = 2.0,
                       quant: str = "int8") -> Dict[str, Any]:
    """The WHOLE fused verify step vs the gather path: per layer the
    multi-query cache read (paged_verify vs gather+dense attend), plus
    ONE sampling epilogue over the [slots*(k+1)] verify rows (fused
    in-VMEM sample vs HBM logits + filter chain).  This is the number
    the acceptance gate pins: >= 2x fewer HBM bytes than the gather
    path at k=4 (docs/kernels.md)."""
    pv = paged_verify_traffic(slots, k, max_pages, page_size, kv_heads,
                              head_dim, elem_bytes=elem_bytes, quant=quant)
    sm = sample_traffic(slots * (k + 1), hidden, vocab,
                        elem_bytes=elem_bytes)
    gather = pv["unfused_bytes"] * num_layers + sm["unfused_bytes"]
    fused = pv["fused_bytes"] * num_layers + sm["fused_bytes"]
    return {
        "kernel": "fused_verify_chain",
        "k": k, "slots": slots, "num_layers": num_layers, "quant": quant,
        "gather_bytes": gather,
        "fused_bytes": fused,
        "reduction": gather / fused if fused else float("inf"),
        "paged_verify": {kk: pv[kk] for kk in
                         ("unfused_bytes", "fused_bytes", "reduction")},
        "sample": {kk: sm[kk] for kk in
                   ("unfused_bytes", "fused_bytes", "reduction")},
    }


# ---------------------------------------------------------------------------
# model-level assembly (bench.py detail.kernels / tools_bench_kernels.py)
# ---------------------------------------------------------------------------

def kernel_traffic_report(*, batch: int, seq: int, hidden: int,
                          intermediate: int, num_layers: int,
                          q_heads: int, kv_heads: int, head_dim: int,
                          elem_bytes: float = 2.0,
                          norm_kind: str = "rms",
                          quant_elems: Optional[int] = None,
                          quant_block: int = 1024,
                          serve_slots: int = 8, serve_pages: int = 16,
                          serve_page_size: int = 16, spec_k: int = 4,
                          vocab: Optional[int] = None,
                          n_params: Optional[int] = None
                          ) -> Dict[str, Dict[str, Any]]:
    """Per-kernel fused-vs-unfused bytes for ONE forward pass of a
    transformer stack shaped like the arguments (per-step: every count
    multiplied by num_layers where the kernel runs per layer).  The
    quant entry prices one gradient-sync quantize over `quant_elems`
    (default: a [hidden, intermediate] matmul's worth per layer)."""
    tokens = batch * seq
    per_layer = {
        "norm": norm_traffic(tokens, hidden, elem_bytes=elem_bytes,
                             kind=norm_kind),
        "swiglu": swiglu_traffic(tokens, intermediate,
                                 elem_bytes=elem_bytes),
        "rotary": rotary_traffic(batch, seq, q_heads, kv_heads, head_dim,
                                 elem_bytes=elem_bytes),
        "flash": flash_traffic(batch, seq, q_heads, head_dim,
                               elem_bytes=elem_bytes),
    }
    out: Dict[str, Dict[str, Any]] = {}
    for name, rec in per_layer.items():
        scaled = dict(rec)
        # two residual+norm pairs per pre-norm block
        mult = num_layers * (2 if name == "norm" else 1)
        for k in ("unfused_bytes", "unfused_read_bytes",
                  "unfused_write_bytes", "fused_bytes",
                  "fused_read_bytes", "fused_write_bytes"):
            scaled[k] = rec[k] * mult
        scaled["per_step_multiplier"] = mult
        scaled.pop("chain", None)          # the CLI prints it on demand
        out[name] = scaled
    qn = quant_elems if quant_elems is not None else \
        num_layers * hidden * intermediate
    q = quant_traffic(qn, quant_block)
    q.pop("chain", None)
    q["per_step_multiplier"] = 1
    out["quant"] = q
    for quant in ("none", "int8", "int4"):
        p = paged_attn_traffic(serve_slots, serve_pages, serve_page_size,
                               kv_heads, head_dim, elem_bytes=elem_bytes,
                               quant=quant)
        for k in ("unfused_bytes", "unfused_read_bytes",
                  "unfused_write_bytes", "fused_bytes",
                  "fused_read_bytes", "fused_write_bytes"):
            p[k] = p[k] * num_layers
        p["per_step_multiplier"] = num_layers
        p.pop("chain", None)
        out[p["kernel"]] = p
    # the fused verify-and-sample decode path (spec decode at spec_k)
    pv = paged_verify_traffic(serve_slots, spec_k, serve_pages,
                              serve_page_size, kv_heads, head_dim,
                              elem_bytes=elem_bytes, quant="int8")
    for k in ("unfused_bytes", "unfused_read_bytes",
              "unfused_write_bytes", "fused_bytes",
              "fused_read_bytes", "fused_write_bytes"):
        pv[k] = pv[k] * num_layers
    pv["per_step_multiplier"] = num_layers
    pv.pop("chain", None)
    out["paged_verify"] = pv
    v = vocab if vocab is not None else 32 * hidden
    sm = sample_traffic(serve_slots * (spec_k + 1), hidden, v,
                        elem_bytes=elem_bytes)
    sm["per_step_multiplier"] = 1
    sm.pop("chain", None)
    out["sample"] = sm
    pn = n_params if n_params is not None else \
        num_layers * (4 * hidden * hidden + 3 * hidden * intermediate)
    ad = adam_traffic(pn)
    ad["per_step_multiplier"] = 1
    ad.pop("chain", None)
    out["adam"] = ad
    return out


def report_for_config(cfg, *, batch: int, seq: int,
                      elem_bytes: Optional[float] = None
                      ) -> Dict[str, Dict[str, Any]]:
    """`kernel_traffic_report` from a LLaMA/GPT-style config object."""
    if elem_bytes is None:
        import jax.numpy as jnp
        elem_bytes = float(jnp.dtype(cfg.compute_dtype).itemsize)
    kv = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
    kind = "rms" if hasattr(cfg, "rms_norm_eps") else "ln"
    n_params = cfg.num_params() if hasattr(cfg, "num_params") else None
    return kernel_traffic_report(
        batch=batch, seq=seq, hidden=cfg.hidden_size,
        intermediate=cfg.intermediate_size,
        num_layers=cfg.num_hidden_layers,
        q_heads=cfg.num_attention_heads, kv_heads=kv,
        head_dim=cfg.head_dim, elem_bytes=elem_bytes, norm_kind=kind,
        vocab=cfg.vocab_size, n_params=n_params)
