"""Fused SwiGLU combine Pallas kernel.

Rebuild of the reference's fused SwiGLU (reference:
hetu/impl/kernel/SwiGLU.cu): y = silu(gate) * up in ONE pass over the
[tokens, intermediate] pair, instead of the XLA chain (sigmoid ->
gate*sig -> *up) that round-trips the activation through HBM per op.
The backward is the fused derivative kernel:

    dgate = dy * up * sig * (1 + gate * (1 - sig))
    dup   = dy * gate * sig

computed from the SAVED (gate, up) pair — silu(gate) is recomputed in
VMEM rather than kept resident in HBM.

Shape contract (drift-tested against `compatible`): the last dim must be
lane-aligned (% 128) and the flattened leading dims must tile into
sublanes (% 8).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret
from hetu_tpu.ops.pallas.fused_norm import _fit_rows


def _check_shapes(g_shape, u_shape) -> Tuple[int, int]:
    if tuple(g_shape) != tuple(u_shape):
        raise ValueError(f"gate/up shapes differ: {g_shape} vs {u_shape}")
    if len(g_shape) < 2:
        raise ValueError(f"need at least [tokens, inner], got {g_shape}")
    inner = g_shape[-1]
    tokens = 1
    for d in g_shape[:-1]:
        tokens *= d
    if inner % 128:
        raise ValueError(f"inner dim {inner} is not lane-aligned (% 128)")
    if tokens % 8:
        raise ValueError(f"token count {tokens} does not tile into "
                         f"sublanes (% 8)")
    return tokens, inner


def compatible(g_shape, u_shape=None) -> bool:
    try:
        _check_shapes(g_shape, g_shape if u_shape is None else u_shape)
        return True
    except ValueError:
        return False


def _fwd_kernel(g_ref, u_ref, y_ref):
    g = g_ref[...].astype(jnp.float32)
    u = u_ref[...].astype(jnp.float32)
    y_ref[...] = (g * jax.nn.sigmoid(g) * u).astype(y_ref.dtype)


def _bwd_kernel(g_ref, u_ref, dy_ref, dg_ref, du_ref):
    g = g_ref[...].astype(jnp.float32)
    u = u_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    sig = jax.nn.sigmoid(g)
    dg_ref[...] = (dy * u * sig * (1.0 + g * (1.0 - sig))).astype(
        dg_ref.dtype)
    du_ref[...] = (dy * g * sig).astype(du_ref.dtype)


def _run(kern, inputs, out_shapes, rows, inner, n):
    spec = pl.BlockSpec((rows, inner), lambda i: (i, 0))
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[spec] * len(inputs),
        out_specs=[spec] * len(out_shapes) if len(out_shapes) > 1 else spec,
        out_shape=(out_shapes if len(out_shapes) > 1 else out_shapes[0]),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(*inputs)


@jax.custom_vjp
def _swiglu(gate, up):
    tokens, inner = _check_shapes(gate.shape, up.shape)
    rows = _fit_rows(tokens, inner)
    y = _run(_fwd_kernel,
             (gate.reshape(tokens, inner), up.reshape(tokens, inner)),
             [jax.ShapeDtypeStruct((tokens, inner), gate.dtype)],
             rows, inner, tokens // rows)
    return y.reshape(gate.shape)


def _swiglu_fwd(gate, up):
    return _swiglu(gate, up), (gate, up)


def _swiglu_bwd(res, dy):
    gate, up = res
    shape = gate.shape
    inner = shape[-1]
    tokens = gate.size // inner
    rows = _fit_rows(tokens, inner)
    dg, du = _run(_bwd_kernel,
                  (gate.reshape(tokens, inner), up.reshape(tokens, inner),
                   dy.reshape(tokens, inner)),
                  [jax.ShapeDtypeStruct((tokens, inner), gate.dtype),
                   jax.ShapeDtypeStruct((tokens, inner), up.dtype)],
                  rows, inner, tokens // rows)
    return dg.reshape(shape), du.reshape(shape)


_swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def fused_swiglu(gate, up):
    """silu(gate) * up in one fused pass (custom-vjp backward included).
    Raises ValueError on shapes outside `compatible` — dispatchers fall
    back to the XLA composition."""
    return _swiglu(gate, up)
