"""Fused blockwise quantize/dequantize Pallas kernels.

Rebuild of the reference's quantization kernels (reference:
hetu/graph/ops/Quantization.h backed by bitsandbytes CUDA kernels;
EQuARX, PAPERS.md, motivates fusing the quantize that feeds every
compressed collective).  `comm/compress.quantize_blockwise` is an XLA
chain (abs -> blockmax -> div -> round -> clip -> cast) that round-trips
the flat buffer through HBM per op; this kernel does one read of the
f32 buffer and one write of the int8 payload + per-block scales.  The
quantize-for-collectives step (DP grad sync, SP compress, ZeRO refresh,
KV pages) routes here via the dispatcher in `comm/compress`.

The int payload is BIT-IDENTICAL to the jnp path and the f32 scales
agree to 1 ulp (XLA may realize /qmax as multiply-by-reciprocal in one
of the two lowerings): same absmax/qmax scale,
same round-half-to-even, same 1e-12 scale floor, int4 values on the
same [-7, 7] grid (packing to nibbles stays in `comm/compress` —
byte-shuffling is free next to the collective itself).  Stochastic
rounding keeps the XLA path (it needs a threaded rng).

Shape contract (drift-tested against `compatible`): buffer length must
divide by block_size, and block_size must be lane-aligned (% 128)."""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hetu_tpu.ops.pallas import _interpret

#: quantize blocks (rows) handled per grid step
_ROWS = 256


def _check_shapes(n: int, block_size: int, bits: int = 8) -> int:
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    if block_size % 128:
        raise ValueError(f"block_size {block_size} is not lane-aligned "
                         f"(% 128); the XLA fallback handles it")
    if n % block_size:
        raise ValueError(f"buffer of {n} elements is not a multiple of "
                         f"block_size={block_size}; pad first")
    return n // block_size


def compatible(n: int, block_size: int, bits: int = 8) -> bool:
    try:
        _check_shapes(n, block_size, bits)
        return True
    except ValueError:
        return False


def _fit_rows(nb: int) -> int:
    r = min(nb, _ROWS)
    while nb % r:
        r -= 1
    return r


def _quant_kernel(x_ref, q_ref, s_ref, *, qmax):
    x = x_ref[...].astype(jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / qmax
    scale = jnp.maximum(scale, 1e-12)
    q_ref[...] = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(jnp.int8)
    s_ref[...] = scale


def _dequant_kernel(q_ref, s_ref, y_ref):
    y_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...]


def quantize_blockwise_pallas(x, block_size: int, *, bits: int = 8
                              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flat buffer -> (q int8 [n//bs, bs], scales f32 [n//bs]) in one
    fused pass (deterministic rounding only).  Raises ValueError on
    shapes outside `compatible`."""
    flat = x.reshape(-1).astype(jnp.float32)
    nb = _check_shapes(flat.shape[0], block_size, bits)
    qmax = 127.0 if bits == 8 else 7.0
    rows = _fit_rows(nb)
    blk = pl.BlockSpec((rows, block_size), lambda i: (i, 0))
    s_blk = pl.BlockSpec((rows, 1), lambda i: (i, 0))
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=(nb // rows,),
        in_specs=[blk],
        out_specs=[blk, s_blk],
        out_shape=[jax.ShapeDtypeStruct((nb, block_size), jnp.int8),
                   jax.ShapeDtypeStruct((nb, 1), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(flat.reshape(nb, block_size))
    return q, s[:, 0]


def dequantize_blockwise_pallas(q, scale) -> jnp.ndarray:
    """(q int8 [nb, bs], scales f32 [nb]) -> flat f32 [nb*bs] in one
    fused pass.  Raises ValueError on shapes outside `compatible`."""
    nb, bs = q.shape
    _check_shapes(nb * bs, bs)
    rows = _fit_rows(nb)
    blk = pl.BlockSpec((rows, bs), lambda i: (i, 0))
    s_blk = pl.BlockSpec((rows, 1), lambda i: (i, 0))
    y = pl.pallas_call(
        _dequant_kernel,
        grid=(nb // rows,),
        in_specs=[blk, s_blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((nb, bs), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(q, scale.reshape(nb, 1).astype(jnp.float32))
    return y.reshape(-1)
