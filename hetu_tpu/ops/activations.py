"""Activations (reference: hetu/graph/ops/{Gelu,Silu,SwiGLU,...}.cc).

Plain jax.numpy — XLA fuses these into adjacent matmuls on TPU, which is why
the reference's fused CUDA kernels (FusedUnary.cu, SwiGLU.cu) need no Pallas
counterpart for the epilogue case.
"""
import jax
import jax.numpy as jnp


def relu(x):
    return jnp.maximum(x, 0)


def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


def gelu(x, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return x * jax.nn.sigmoid(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def swiglu(gate, up, use_pallas=None):
    """SwiGLU combine (reference: ops/SwiGLU.cc): silu(gate) * up.

    Routes to the fused Pallas kernel (ops/pallas/swiglu — one pass,
    custom-vjp backward) under HETU_TPU_PALLAS; the jnp composition is
    the exact fallback."""
    if use_pallas is None:
        from hetu_tpu.ops.pallas import resolve_route
        from hetu_tpu.ops.pallas import swiglu as _sw
        use_pallas = resolve_route(
            "swiglu", _sw.compatible(gate.shape, up.shape))
    if use_pallas:
        from hetu_tpu.ops.pallas.swiglu import fused_swiglu
        with jax.named_scope("pallas_swiglu"):
            return fused_swiglu(gate, up)
    return silu(gate) * up


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x):
    return jax.nn.softplus(x)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def dropout(x, rate: float, rng=None, deterministic: bool = True):
    """Functional dropout (reference: hetu/graph/ops/Dropout.cc)."""
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)
