"""Activations (reference: hetu/graph/ops/{Gelu,Silu,SwiGLU,...}.cc).

Plain jax.numpy — XLA fuses these into adjacent matmuls on TPU, which is why
the reference's fused CUDA kernels (FusedUnary.cu, SwiGLU.cu) need no Pallas
counterpart for the epilogue case.
"""
import jax
import jax.numpy as jnp


def relu(x):
    return jnp.maximum(x, 0)


def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


def gelu(x, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return x * jax.nn.sigmoid(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def swiglu(gate, up):
    """SwiGLU combine (reference: ops/SwiGLU.cc): silu(gate) * up."""
    return silu(gate) * up


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x):
    return jax.nn.softplus(x)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def dropout(x, rate: float, rng=None, deterministic: bool = True):
    """Functional dropout (reference: hetu/graph/ops/Dropout.cc)."""
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)
