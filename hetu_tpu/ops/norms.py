"""Normalization ops (reference: hetu/impl/kernel/{RMSNorm,FusedLayerNorm}.cu).

Computed in float32 regardless of input dtype (the reference's fused kernels
accumulate in fp32), cast back to the input dtype at the end; XLA fuses the
whole body into one VPU loop so a Pallas kernel is only warranted when fusing
across op boundaries (see ops/pallas for the fused residual+norm variant).
"""
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)
