"""Normalization ops (reference: hetu/impl/kernel/{RMSNorm,FusedLayerNorm}.cu).

Computed in float32 regardless of input dtype (the reference's fused kernels
accumulate in fp32), cast back to the input dtype at the end; XLA fuses the
whole body into one VPU loop so a Pallas kernel is only warranted when fusing
across op boundaries — which is exactly what `residual_rms_norm` /
`residual_layer_norm` do: the residual-add + norm pair the transformer
blocks emit fuses into ONE pass (ops/pallas/fused_norm) behind the
HETU_TPU_PALLAS routing, with this module's composition as the fallback.
"""
from typing import Optional

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def residual_rms_norm(x, h, weight, eps: float = 1e-5,
                      use_pallas: Optional[bool] = None):
    """Fused residual-add + RMSNorm: returns (rms_norm(x + h) * weight,
    x + h) — the pre-norm block's pair, one Pallas pass when routed
    (HETU_TPU_PALLAS auto/1/0 + the `norm` kernel gate), the exact seed
    composition otherwise."""
    if use_pallas is None:
        from hetu_tpu.ops.pallas import resolve_route
        from hetu_tpu.ops.pallas import fused_norm as _fn
        use_pallas = resolve_route(
            "norm", _fn.compatible(x.shape, h.shape, weight.shape))
    if use_pallas:
        from hetu_tpu.ops.pallas.fused_norm import fused_residual_rmsnorm
        with jax.named_scope("pallas_residual_rmsnorm"):
            return fused_residual_rmsnorm(x, h, weight, eps)
    s = x + h
    return rms_norm(s, weight, eps), s


def residual_layer_norm(x, h, weight, bias, eps: float = 1e-5,
                        use_pallas: Optional[bool] = None):
    """Fused residual-add + LayerNorm: returns (layer_norm(x + h), x + h).
    Same routing contract as `residual_rms_norm`."""
    if use_pallas is None:
        from hetu_tpu.ops.pallas import resolve_route
        from hetu_tpu.ops.pallas import fused_norm as _fn
        use_pallas = resolve_route(
            "norm", _fn.compatible(x.shape, h.shape, weight.shape))
    if use_pallas:
        from hetu_tpu.ops.pallas.fused_norm import fused_residual_layernorm
        with jax.named_scope("pallas_residual_layernorm"):
            return fused_residual_layernorm(x, h, weight, bias, eps)
    s = x + h
    return layer_norm(s, weight, bias, eps), s
