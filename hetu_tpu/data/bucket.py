"""Bucketing, padding and sequence packing.

Rebuild of the reference data bucket (reference: python/hetu/data/bucket.py:8 —
pad_data :67, pack_data :86 greedy packing, generate_cp_pack_data :193
head/tail-symmetric CP split, cu_seqlens generation), adapted to XLA's
static-shape world: every batch is padded/packed to a length from a fixed
bucket ladder so the compiled-executable cache (plan pool) stays small.

TPU adaptations:
- cu_seqlens become per-token `position_ids` (restart at each packed sequence)
  and `segment_ids` (sequence index per token) — the Pallas flash kernel and
  the XLA attention both mask cross-sequence attention via segment_ids, which
  is the static-shape equivalent of varlen cu_seqlens.
- the CP split keeps the reference's head+tail symmetric assignment
  (rank r gets chunk r and chunk 2*cp-1-r of 2*cp chunks) so causal load is
  balanced across the ring, matching HETU_PARALLEL_ATTN_SPLIT=SYM.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


DEFAULT_BUCKET_SIZES = (256, 512, 1024, 2048, 4096, 8192, 16384, 32768)


def choose_bucket(length: int, buckets: Sequence[int] = DEFAULT_BUCKET_SIZES) -> int:
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]


@dataclasses.dataclass
class Bucket:
    """A batch of sequences padded/packed to one static length
    (reference: bucket.py:8 Bucket with pad_data/pack_data)."""

    max_seq_len: int
    pad_id: int = 0

    def __post_init__(self):
        self._seqs: List[np.ndarray] = []

    def add(self, ids: np.ndarray):
        self._seqs.append(np.asarray(ids, np.int32)[: self.max_seq_len])

    def __len__(self):
        return len(self._seqs)

    # -- padding mode (reference pad_data :67) ------------------------------
    def pad_batch(self) -> Dict[str, np.ndarray]:
        return pad_batch(self._seqs, self.max_seq_len, self.pad_id)

    # -- packing mode (reference pack_data :86) -----------------------------
    def pack_batch(self, num_packed: Optional[int] = None) -> Dict[str, np.ndarray]:
        return pack_sequences(self._seqs, self.max_seq_len, self.pad_id,
                              num_packed=num_packed)


def pad_batch(seqs: Sequence[np.ndarray], max_len: int, pad_id: int = 0) -> Dict[str, np.ndarray]:
    """Pad each sequence to max_len. labels = ids with pads masked to -100."""
    n = len(seqs)
    ids = np.full((n, max_len), pad_id, np.int32)
    labels = np.full((n, max_len), -100, np.int32)
    position_ids = np.zeros((n, max_len), np.int32)
    segment_ids = np.zeros((n, max_len), np.int32)
    for i, s in enumerate(seqs):
        L = min(len(s), max_len)
        ids[i, :L] = s[:L]
        labels[i, :L] = s[:L]
        position_ids[i, :L] = np.arange(L)
        segment_ids[i, :L] = 1
    return {"input_ids": ids, "labels": labels,
            "position_ids": position_ids, "segment_ids": segment_ids}


def pack_sequences(seqs: Sequence[np.ndarray], max_len: int, pad_id: int = 0,
                   num_packed: Optional[int] = None,
                   on_overflow: str = "warn") -> Dict[str, np.ndarray]:
    """Greedy first-fit packing into rows of length max_len
    (reference: bucket.py:86 pack_data).  Returns ids/labels/position_ids/
    segment_ids; segment 0 = padding, packed sequences are 1-indexed.

    When `num_packed` caps the row count, overflow rows are dropped;
    on_overflow: "warn" logs the loss, "error" raises, "silent" drops."""
    order = np.argsort([-len(s) for s in seqs], kind="stable")
    rows: List[List[np.ndarray]] = []
    used: List[int] = []
    for idx in order:
        s = seqs[idx]
        L = len(s)
        placed = False
        for r in range(len(rows)):
            if used[r] + L <= max_len:
                rows[r].append(s)
                used[r] += L
                placed = True
                break
        if not placed:
            rows.append([s])
            used.append(min(L, max_len))
    if num_packed is not None:
        while len(rows) < num_packed:
            rows.append([])
            used.append(0)
        if len(rows) > num_packed:
            dropped = sum(len(s) for row in rows[num_packed:] for s in row)
            if on_overflow == "error":
                raise ValueError(
                    f"packing overflow: {len(rows) - num_packed} rows "
                    f"({dropped} tokens) do not fit in num_packed={num_packed}")
            if on_overflow == "warn":
                from hetu_tpu.utils.logging import get_logger
                get_logger("data").warning(
                    f"packing dropped {dropped} tokens "
                    f"({len(rows) - num_packed} overflow rows)")
        rows = rows[:num_packed]

    n = len(rows)
    ids = np.full((n, max_len), pad_id, np.int32)
    labels = np.full((n, max_len), -100, np.int32)
    position_ids = np.zeros((n, max_len), np.int32)
    segment_ids = np.zeros((n, max_len), np.int32)
    for r, row in enumerate(rows):
        off = 0
        for j, s in enumerate(row):
            L = min(len(s), max_len - off)
            ids[r, off:off + L] = s[:L]
            labels[r, off:off + L] = s[:L]
            position_ids[r, off:off + L] = np.arange(L)
            segment_ids[r, off:off + L] = j + 1
            # first token of each sequence can't be predicted from the
            # previous sequence: mask its label
            labels[r, off] = -100
            off += L
    return {"input_ids": ids, "labels": labels,
            "position_ids": position_ids, "segment_ids": segment_ids}


def stripe_granularity(seq: int, cp: int):
    """The stripe split's block granularity: finest g = seq/(cp*m) giving
    every rank >= 2 blocks (m from cp down to 2), or None if none divides.
    ONE rule shared by the data split below and the ring's static step
    masks (parallel/ring_attention.ring_step_masks) — drift between the two
    would make the masks skip live tiles."""
    for m in range(cp, 1, -1):
        if seq % (cp * m) == 0:
            return seq // (cp * m)
    return None


def cp_split_batch(batch: Dict[str, np.ndarray], cp: int,
                   split: Optional[str] = None) -> List[Dict[str, np.ndarray]]:
    """Split a packed/padded batch along seq into per-CP-rank slices
    (reference: bucket.py:193 generate_cp_pack_data + the ring's
    HETU_PARALLEL_ATTN_SPLIT=NORMAL|STRIPE|SYM modes,
    ParallelAttention.cc:196-204):

      sym    — of 2*cp equal chunks, rank r owns chunks r and 2*cp-1-r
               (head+tail symmetric; balanced causal work)
      stripe — round-robin token-block striping (chunk i -> rank i % cp)
      normal — contiguous chunks (rank r owns chunk r; causal-imbalanced)

    Returns a list of cp dicts, each with seq_len = total/cp.  Causality
    under any split is preserved by the ring kernel's position-based masks
    (feed the original position_ids through)."""
    if split is None:
        # flag-driven default (reference: HETU_PARALLEL_ATTN_SPLIT_PATTERN)
        from hetu_tpu.utils import flags
        split = flags.str_flag("HETU_TPU_CP_SPLIT")
    seq = batch["input_ids"].shape[1]
    if split == "sym":
        assert seq % (2 * cp) == 0, f"seq {seq} must divide by 2*cp={2*cp}"
        chunk = seq // (2 * cp)
        owner = [(r * chunk, (2 * cp - 1 - r) * chunk) for r in range(cp)]
        idx = [np.concatenate([np.arange(lo, lo + chunk),
                               np.arange(hi, hi + chunk)])
               for lo, hi in owner]
    elif split == "stripe":
        assert seq % cp == 0, f"seq {seq} must divide by cp={cp}"
        g = stripe_granularity(seq, cp)
        if g is None:
            raise ValueError(
                f"stripe split needs seq ({seq}) divisible by cp*m for some "
                f"m >= 2 (cp={cp}); use split='sym' or 'normal'")
        blocks = [np.arange(i * g, (i + 1) * g) for i in range(seq // g)]
        idx = [np.concatenate(blocks[r::cp]) for r in range(cp)]
    elif split == "normal":
        assert seq % cp == 0, f"seq {seq} must divide by cp={cp}"
        chunk = seq // cp
        idx = [np.arange(r * chunk, (r + 1) * chunk) for r in range(cp)]
    else:
        raise ValueError(f"split must be sym|stripe|normal, got {split!r}")
    out = []
    for r in range(cp):
        out.append({k: v[:, idx[r]] for k, v in batch.items()})
    return out


def cp_split_uneven(batch: Dict[str, np.ndarray], lengths: Sequence[int],
                    align: int = 1, pad_id: int = 0) -> Dict[str, np.ndarray]:
    """Heterogeneous-CP split: ring rank r owns `lengths[r]` VALID tokens.

    The reference runs hetero CP rings whose members hold unequal seq shards
    (reference: hetu/graph/ops/ParallelAttention.cc:949-1050 hetero ring with
    per-rank valid lens).  XLA's even-sharding world realizes that as equal
    PHYSICAL shards with per-rank valid prefixes: each rank's region is
    padded to the common width s_max, pads carry segment 0 (masked from all
    valid tokens by the kernel's segment machinery) and label -100.

    Input batch: the usual padded/packed dict over a compact seq of
    sum(lengths) tokens.  Output: same dict re-laid-out to seq = cp*s_max so
    a plain cp sharding of the seq dim gives rank r exactly its tokens —
    run it through the normal ring path, no special casing.
    """
    cp = len(lengths)
    seq = batch["input_ids"].shape[1]
    if sum(lengths) != seq:
        raise ValueError(f"lengths {list(lengths)} must sum to seq {seq}")
    s_max = max(lengths)
    s_max = -(-s_max // align) * align
    starts = np.cumsum([0] + list(lengths[:-1]))
    out = {}
    for key, v in batch.items():
        fill = -100 if key == "labels" else (
            pad_id if key == "input_ids" else 0)
        arr = np.full((v.shape[0], cp * s_max), fill, v.dtype)
        for r, (st0, L) in enumerate(zip(starts, lengths)):
            arr[:, r * s_max:r * s_max + L] = v[:, st0:st0 + L]
        out[key] = arr
    return out


def merge_cp_uneven(batch: Dict[str, np.ndarray], lengths: Sequence[int]
                    ) -> Dict[str, np.ndarray]:
    """Inverse of cp_split_uneven: drop per-rank pads, re-compact the seq."""
    cp = len(lengths)
    s_max = batch["input_ids"].shape[1] // cp
    keep = np.concatenate([np.arange(r * s_max, r * s_max + L)
                           for r, L in enumerate(lengths)])
    return {k: v[:, keep] for k, v in batch.items()}


def cp_split_indices(seq: int, cp: int, split: str = "sym") -> List[np.ndarray]:
    """The global token indices each cp rank owns (for reassembly/tests)."""
    dummy = {"input_ids": np.arange(seq)[None, :]}
    return [s["input_ids"][0] for s in cp_split_batch(dummy, cp, split)]


def merge_cp_batch(shards: List[Dict[str, np.ndarray]],
                   split: str = "sym") -> Dict[str, np.ndarray]:
    """Inverse of cp_split_batch (for tests / unsharded eval)."""
    cp = len(shards)
    seq = sum(s["input_ids"].shape[1] for s in shards)
    idx = cp_split_indices(seq, cp, split)
    merged = {}
    for k in shards[0]:
        total = np.zeros((shards[0][k].shape[0], seq), shards[0][k].dtype)
        for r, sh in enumerate(shards):
            total[:, idx[r]] = sh[k]
        merged[k] = total
    return merged
