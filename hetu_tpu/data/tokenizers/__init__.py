"""In-tree tokenizer stack (reference: python/hetu/data/tokenizers/ — the
reference vendors GPT2-BPE, SentencePiece, tiktoken and an HF wrapper; this
package vendors a self-contained byte-level BPE (train/save/load, no
downloads) plus a thin HF delegate for pretrained vocabularies)."""
from hetu_tpu.data.tokenizers.bpe import ByteLevelBPETokenizer
from hetu_tpu.data.tokenizers.hf import HFTokenizer, build_tokenizer
from hetu_tpu.data.tokenizers.sp_model import SentencePieceTokenizer
from hetu_tpu.data.tokenizers.tiktoken_bpe import TikTokenizer

__all__ = ["ByteLevelBPETokenizer", "HFTokenizer", "build_tokenizer",
           "SentencePieceTokenizer", "TikTokenizer"]
