"""Tiktoken-format tokenizer (reference: python/hetu/data/tokenizers/
tiktoken_tokenizer.py).

The rank file (one `base64(token) rank` pair per line) loads WITHOUT the
tiktoken package, and the byte-pair merge itself is implemented here
(lowest-rank adjacent pair first — the tiktoken algorithm), so the tokenizer
is fully functional standalone; when the `tiktoken` package is importable
its compiled Encoding is used for the hot encode path instead.
"""
from __future__ import annotations

import base64
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Union

# llama3-style split pattern (the reference ships PATTERN_TIKTOKEN variants;
# any pattern string can be passed in)
PATTERN_DEFAULT = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}"
    r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)


def load_tiktoken_ranks(path: str) -> Dict[bytes, int]:
    """Parse a .tiktoken/.model rank file: `base64(token) rank` per line."""
    ranks: Dict[bytes, int] = {}
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            tok_b64, rank = line.split()
            ranks[base64.b64decode(tok_b64)] = int(rank)
    return ranks


def save_tiktoken_ranks(ranks: Dict[bytes, int], path: str):
    with open(path, "wb") as f:
        for tok, rank in sorted(ranks.items(), key=lambda kv: kv[1]):
            f.write(base64.b64encode(tok) + b" " + str(rank).encode() + b"\n")


def bpe_merge(piece: bytes, ranks: Dict[bytes, int]) -> List[int]:
    """Tiktoken's merge loop: repeatedly fuse the adjacent part pair with
    the LOWEST rank until none is mergeable; parts map to their rank ids."""
    parts = [piece[i:i + 1] for i in range(len(piece))]
    while len(parts) > 1:
        best_k, best_rank = -1, None
        for k in range(len(parts) - 1):
            r = ranks.get(parts[k] + parts[k + 1])
            if r is not None and (best_rank is None or r < best_rank):
                best_k, best_rank = k, r
        if best_k < 0:
            break
        parts[best_k:best_k + 2] = [parts[best_k] + parts[best_k + 1]]
    return [ranks[p] for p in parts]


class TikTokenizer:
    """Byte-level BPE over a tiktoken rank file + named special tokens."""

    def __init__(self, path: str, pattern: str = PATTERN_DEFAULT,
                 special_tokens: Optional[Sequence[str]] = None):
        self.ranks = load_tiktoken_ranks(path)
        self.pattern = pattern
        specials = list(special_tokens if special_tokens is not None
                        else ("<s>", "</s>", "<unk>"))
        # non-dense rank files exist (holes in the id space): special ids
        # must start past the MAX rank, not len(ranks), or they collide
        # with base ids and decode() silently prefers the base token
        base = (max(self.ranks.values()) + 1) if self.ranks else 0
        self.special_tokens = {t: base + i for i, t in enumerate(specials)}
        self._base = base
        self.bos_id = self.special_tokens.get("<s>")
        self.eos_id = self.special_tokens.get("</s>")
        self.pad_id = self.eos_id
        self._id_to_bytes = {r: t for t, r in self.ranks.items()}
        self._id_to_special = {i: t for t, i in self.special_tokens.items()}

        import regex
        self._pat = regex.compile(pattern)
        self._fast = None
        try:  # optional compiled path
            from tiktoken import Encoding
            self._fast = Encoding(
                name=Path(path).stem, pat_str=pattern,
                mergeable_ranks=self.ranks,
                special_tokens=self.special_tokens)
        except Exception:
            pass

    # -------------------------------------------------- encode / decode
    def _encode_ordinary(self, text: str) -> List[int]:
        if self._fast is not None:
            return self._fast.encode(text, disallowed_special=())
        ids: List[int] = []
        for m in self._pat.finditer(text):
            piece = m.group().encode("utf-8")
            r = self.ranks.get(piece)
            ids.extend([r] if r is not None else bpe_merge(piece, self.ranks))
        return ids

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        ids = self._encode_ordinary(text) if text else []
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        if add_eos and self.eos_id is not None:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Union[int, Sequence[int]]) -> str:
        if isinstance(ids, int):
            ids = [ids]
        buf = bytearray()
        for i in ids:
            b = self._id_to_bytes.get(i)
            if b is not None:
                buf.extend(b)
            elif i in self._id_to_special:
                buf.extend(self._id_to_special[i].encode("utf-8"))
        return buf.decode("utf-8", errors="replace")

    # -------------------------------------------------- vocab surface
    @property
    def vocab_size(self) -> int:
        # id-space size (embedding rows needed), not the token count —
        # the two differ when the rank file is non-dense
        return self._base + len(self.special_tokens)

    @property
    def base_vocab_size(self) -> int:
        # id-space size below the special tokens (== first special id);
        # > len(self.ranks) when the rank file is non-dense
        return self._base

    def token_to_id(self, token: Union[str, bytes]) -> Optional[int]:
        if isinstance(token, str):
            sid = self.special_tokens.get(token)
            if sid is not None:
                return sid
            token = token.encode("utf-8")
        return self.ranks.get(token)
