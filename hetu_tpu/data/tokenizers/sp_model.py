"""SentencePiece tokenizer with a pure-python .model loader.

The reference wraps the `sentencepiece` runtime (reference: python/hetu/data/
tokenizers/sentencepiece_tokenizer.py) — which is not available here, so this
module reads the `tokenizer.model` protobuf DIRECTLY (generic proto wire
parsing, no compiled schema) and implements both sentencepiece inference
algorithms in python:

  * unigram — Viterbi segmentation maximizing summed piece log-probs
  * bpe     — greedy best-score adjacent merge (sp stores merge priority as
              the piece score, so "highest score first" == training order)

plus the LLaMA-relevant details: ▁ whitespace escaping, add_dummy_prefix,
byte-fallback pieces (<0x00>..<0xFF>) for out-of-vocab characters, and
CONTROL pieces (bos/eos/pad) excluded from text matching.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

_WS = "▁"  # ▁

# sentencepiece_model.proto piece types
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _UNUSED, _BYTE = 1, 2, 3, 4, 5, 6


# ---------------------------------------------------------------------------
# minimal protobuf wire reader (enough for ModelProto)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _signed(v: int) -> int:
    """proto int32/int64 varints are two's complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's wire data.
    value: int for varint/fixed, bytes for length-delimited."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt} (field {fno})")
        yield fno, wt, v


def parse_model_proto(data: bytes):
    """ModelProto -> (pieces [(text, score, type)], trainer {..}, norm {..})."""
    pieces: List[Tuple[str, float, int]] = []
    trainer: Dict[str, int] = {}
    # proto defaults (sentencepiece_model.proto NormalizerSpec):
    # remove_extra_whitespaces defaults TRUE — models trained with
    # defaults omit the field entirely
    norm = {"add_dummy_prefix": True, "escape_whitespaces": True,
            "remove_extra_whitespaces": True}
    for fno, _, v in _fields(data):
        if fno == 1:  # repeated SentencePiece
            text, score, typ = "", 0.0, _NORMAL
            for pfno, pwt, pv in _fields(v):
                if pfno == 1:
                    text = pv.decode("utf-8")
                elif pfno == 2:
                    score = struct.unpack("<f", struct.pack("<I", pv))[0]
                elif pfno == 3:
                    typ = pv
            pieces.append((text, score, typ))
        elif fno == 2:  # TrainerSpec
            for tfno, twt, tv in _fields(v):
                if tfno == 3:    # model_type: 1=unigram 2=bpe
                    trainer["model_type"] = tv
                elif tfno == 35:  # byte_fallback
                    trainer["byte_fallback"] = bool(tv)
                elif tfno == 40:
                    trainer["unk_id"] = _signed(tv)
                elif tfno == 41:
                    trainer["bos_id"] = _signed(tv)
                elif tfno == 42:
                    trainer["eos_id"] = _signed(tv)
                elif tfno == 43:
                    trainer["pad_id"] = _signed(tv)
        elif fno == 3:  # NormalizerSpec
            for nfno, nwt, nv in _fields(v):
                if nfno == 1:
                    norm["name"] = nv.decode("utf-8")
                elif nfno == 3:
                    norm["add_dummy_prefix"] = bool(nv)
                elif nfno == 4:
                    norm["remove_extra_whitespaces"] = bool(nv)
                elif nfno == 5:
                    norm["escape_whitespaces"] = bool(nv)
    return pieces, trainer, norm


# ---------------------------------------------------------------------------
# writer (tests + in-tree model construction; also proves the reader against
# real wire format rather than a private fixture format)
# ---------------------------------------------------------------------------

def _varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(fno: int, payload: bytes) -> bytes:
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def write_model_proto(pieces: Sequence[Tuple[str, float, int]],
                      model_type: int = 1, *,
                      unk_id: int = 0, bos_id: int = 1, eos_id: int = 2,
                      pad_id: int = -1, add_dummy_prefix: bool = True,
                      byte_fallback: bool = False,
                      normalizer_name: str = "identity",
                      remove_extra_whitespaces: bool = False) -> bytes:
    out = b""
    for text, score, typ in pieces:
        p = _ld(1, text.encode("utf-8"))
        p += _varint((2 << 3) | 5) + struct.pack("<f", score)
        p += _varint((3 << 3) | 0) + _varint(typ)
        out += _ld(1, p)
    tr = _varint((3 << 3) | 0) + _varint(model_type)
    tr += _varint((35 << 3) | 0) + _varint(int(byte_fallback))
    for fno, vid in ((40, unk_id), (41, bos_id), (42, eos_id), (43, pad_id)):
        tr += _varint((fno << 3) | 0) + _varint(vid)
    out += _ld(2, tr)
    nm = _ld(1, normalizer_name.encode("utf-8"))
    nm += _varint((3 << 3) | 0) + _varint(int(add_dummy_prefix))
    nm += _varint((4 << 3) | 0) + _varint(int(remove_extra_whitespaces))
    nm += _varint((5 << 3) | 0) + _varint(1)
    out += _ld(3, nm)
    return out


# ---------------------------------------------------------------------------
# rule-name normalization (NormalizerSpec.name driven)
# ---------------------------------------------------------------------------

# The real sentencepiece runtime normalizes through the model's PRECOMPILED
# charsmap (a serialized double-array trie baked at training time from the
# named rule — builder.cc BuildNmtNfkcMap).  This module implements the
# NAMED rules directly with unicodedata instead of decoding the trie:
# identical for NFKC-representable mappings (the overwhelming majority —
# fullwidth forms, compatibility ligatures, composed accents), approximate
# for the handful of hand-curated NMT entries.  Documented divergence, not
# silent: models whose name is unknown raise.
_NMT_SPACE = {0x0009, 0x000A, 0x000D, 0x000B, 0x000C, 0x00A0, 0x1680,
              0x2028, 0x2029, 0x202F, 0x205F, 0x3000} | \
             set(range(0x2000, 0x200B))
_NMT_REMOVE = (set(range(0x0000, 0x0009)) | set(range(0x000E, 0x0020))
               | {0x007F, 0x008F, 0x009F, 0x00AD, 0xFEFF}
               | set(range(0x200B, 0x2010)) | set(range(0x202A, 0x202F))
               | set(range(0x2060, 0x2065)))


def _nmt_premap(text: str) -> str:
    out = []
    for ch in text:
        cp = ord(ch)
        if cp in _NMT_REMOVE:
            continue
        out.append(" " if cp in _NMT_SPACE else ch)
    return "".join(out)


#: NormalizerSpec rule names this module implements; anything else (e.g.
#: 'user_defined' custom-charsmap models, which sentencepiece supports)
#: falls back to identity AT LOAD TIME with a logged warning — a model
#: that loads must not start raising on its first encode().
KNOWN_RULES = ("identity", "", "nfkc", "nfkc_cf", "nmt_nfkc", "nmt_nfkc_cf")


def rule_normalize(name: str, text: str) -> str:
    """Apply the NormalizerSpec rule `name` (reference: the sentencepiece
    normalization_rule_name the library bakes into the charsmap)."""
    import unicodedata
    if name in ("identity", ""):
        return text
    if name in ("nfkc", "nfkc_cf", "nmt_nfkc", "nmt_nfkc_cf"):
        if name.startswith("nmt_"):
            text = _nmt_premap(text)
        text = unicodedata.normalize("NFKC", text)
        if name.endswith("_cf"):
            text = text.casefold()
        return text
    raise ValueError(f"unknown normalization rule {name!r} "
                     "(identity|nfkc|nfkc_cf|nmt_nfkc|nmt_nfkc_cf)")


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

class SentencePieceTokenizer:
    """encode/decode over a sentencepiece .model file, runtime-free."""

    def __init__(self, model_file: Optional[str] = None,
                 model_bytes: Optional[bytes] = None):
        if model_bytes is None:
            if model_file is None:
                raise ValueError("need model_file or model_bytes")
            with open(model_file, "rb") as f:
                model_bytes = f.read()
        pieces, trainer, norm = parse_model_proto(model_bytes)
        self.pieces = pieces
        self.model_type = trainer.get("model_type", 1)
        self.add_dummy_prefix = norm["add_dummy_prefix"]
        self.normalizer_name = norm.get("name", "identity")
        if self.normalizer_name not in KNOWN_RULES:
            # validate ONCE at load: unknown rules (custom precompiled
            # charsmaps) degrade to identity with a visible warning instead
            # of raising mid-encode()
            from hetu_tpu.utils.logging import get_logger
            get_logger("tokenizers.sp").warning(
                f"unknown normalization rule "
                f"{self.normalizer_name!r}; known rules are "
                f"{[r for r in KNOWN_RULES if r]} — falling back to "
                "identity (the model's precompiled charsmap is not "
                "applied)")
            self.normalizer_name = "identity"
        self.remove_extra_whitespaces = norm["remove_extra_whitespaces"]
        self.unk_id = trainer.get("unk_id", 0)
        self.bos_id = trainer.get("bos_id", 1)
        self.eos_id = trainer.get("eos_id", 2)
        self.pad_id = trainer.get("pad_id", -1)
        # text-matchable vocab: NORMAL + USER_DEFINED only
        self._vocab: Dict[str, Tuple[int, float]] = {}
        self._byte_ids: Dict[int, int] = {}   # byte value -> piece id
        for pid, (text, score, typ) in enumerate(pieces):
            if typ in (_NORMAL, _USER_DEFINED):
                self._vocab[text] = (pid, score)
            elif typ == _BYTE:
                self._byte_ids[int(text[1:-1], 16)] = pid  # "<0xAB>"
        self._max_len = max((len(t) for t in self._vocab), default=1)
        # no vocab piece carries ▁ past position 0 -> no merge can cross
        # a word boundary -> the BPE arena chunks exactly at each ▁
        # (LLaMA-style vocabs qualify; interior-▁ pieces fall back to the
        # whole-text arena)
        self._bpe_chunkable = not any(_WS in t[1:] for t in self._vocab)

    # -------------------------------------------------- helpers
    def _normalize(self, text: str) -> str:
        """NormalizerSpec order (normalizer.cc): charsmap rule ->
        whitespace squeeze -> ▁ escaping -> dummy prefix."""
        text = rule_normalize(self.normalizer_name, text)
        if self.remove_extra_whitespaces:
            import re
            text = re.sub(r" +", " ", text).strip(" ")
        text = text.replace(" ", _WS)
        if self.add_dummy_prefix and text and not text.startswith(_WS):
            text = _WS + text
        return text

    def _char_fallback(self, ch: str, out: List[int]):
        """OOV character -> byte pieces when present, else unk."""
        if self._byte_ids:
            for b in ch.encode("utf-8"):
                out.append(self._byte_ids.get(b, self.unk_id))
        else:
            out.append(self.unk_id)

    # -------------------------------------------------- unigram (Viterbi)
    def _encode_unigram(self, text: str) -> List[int]:
        n = len(text)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, int]]] = [None] * (n + 1)
        best[0] = 0.0
        unk_penalty = min(
            (s for _, (_, s) in self._vocab.items()), default=0.0) - 10.0
        for i in range(n):
            if best[i] == NEG:
                continue
            hi = min(n, i + self._max_len)
            for j in range(i + 1, hi + 1):
                hit = self._vocab.get(text[i:j])
                if hit is not None and best[i] + hit[1] > best[j]:
                    best[j] = best[i] + hit[1]
                    back[j] = (i, hit[0])
            # fallback edge: single char as unk/byte
            if best[i] + unk_penalty > best[i + 1]:
                best[i + 1] = best[i] + unk_penalty
                back[i + 1] = (i, -1)
        ids: List[int] = []
        j = n
        rev: List[Tuple[int, int, int]] = []   # (i, j, id|-1)
        while j > 0:
            i, pid = back[j]
            rev.append((i, j, pid))
            j = i
        for i, j, pid in reversed(rev):
            if pid >= 0:
                ids.append(pid)
            else:
                self._char_fallback(text[i:j], ids)
        return ids

    # -------------------------------------------------- bpe (score merges)
    def _encode_bpe(self, text: str) -> List[int]:
        """Best-score-first adjacent merges (ties leftmost — the greedy
        reference semantics; the sentencepiece library's symbol-pair
        agenda is the same scheme, bpe_model.cc).  Word-chunked when the
        vocab allows (corpus-speed path), heap-based lazy-invalidation
        merges within an arena — against the O(n^2) rescan the first
        version did."""
        if self._bpe_chunkable and len(text) > 64:
            ids: List[int] = []
            start = 0
            for k in range(1, len(text) + 1):
                if k == len(text) or text[k] == _WS:
                    ids.extend(self._merge_arena(text[start:k]))
                    start = k
            return ids
        return self._merge_arena(text)

    def _merge_arena(self, text: str) -> List[int]:
        import heapq

        units = list(text)
        n = len(units)
        if n <= 1:
            return self._bpe_emit(units)
        if n <= 16:
            # small arenas (typical ▁-chunked words): the plain greedy
            # rescan beats the heap's setup cost
            get = self._vocab.get
            while len(units) > 1:
                best_k, best_score = -1, None
                for k in range(len(units) - 1):
                    hit = get(units[k] + units[k + 1])
                    if hit is not None and (best_score is None
                                            or hit[1] > best_score):
                        best_k, best_score = k, hit[1]
                if best_k < 0:
                    break
                units[best_k:best_k + 2] = [units[best_k]
                                            + units[best_k + 1]]
            return self._bpe_emit(units)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(0, n - 1))
        alive = [True] * n
        heap: List[Tuple[float, int, str, str]] = []

        def push(k: int):
            j = nxt[k]
            if j < 0:
                return
            hit = self._vocab.get(units[k] + units[j])
            if hit is not None:
                # (-score, k): leftmost wins ties like the greedy scan
                heapq.heappush(heap, (-hit[1], k, units[k], units[j]))

        for k in range(n - 1):
            push(k)
        while heap:
            _, k, left, right = heapq.heappop(heap)
            if not alive[k] or units[k] != left:
                continue              # stale: k was merged away/changed
            j = nxt[k]
            if j < 0 or units[j] != right:
                continue              # stale: the right neighbor changed
            units[k] = left + right
            alive[j] = False
            nxt[k] = nxt[j]
            if nxt[j] >= 0:
                prv[nxt[j]] = k
            if prv[k] >= 0:
                push(prv[k])
            push(k)
        return self._bpe_emit([units[k] for k in range(n) if alive[k]])

    def _bpe_emit(self, units: Sequence[str]) -> List[int]:
        ids: List[int] = []
        for u in units:
            hit = self._vocab.get(u)
            if hit is not None:
                ids.append(hit[0])
            else:
                for ch in u:
                    self._char_fallback(ch, ids)
        return ids

    # -------------------------------------------------- public api
    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        if not text:
            ids = []
        else:
            t = self._normalize(text)
            ids = (self._encode_bpe(t) if self.model_type == 2
                   else self._encode_unigram(t))
        if add_bos and self.bos_id >= 0:
            ids = [self.bos_id] + ids
        if add_eos and self.eos_id >= 0:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[str] = []
        byte_buf = bytearray()

        def flush():
            if byte_buf:
                out.append(byte_buf.decode("utf-8", errors="replace"))
                byte_buf.clear()

        for pid in ids:
            if pid < 0 or pid >= len(self.pieces):
                continue
            text, _, typ = self.pieces[pid]
            if typ == _BYTE:
                byte_buf.append(int(text[1:-1], 16))
                continue
            flush()
            if typ in (_CONTROL, _UNKNOWN):
                continue
            out.append(text)
        flush()
        s = "".join(out).replace(_WS, " ")
        if self.add_dummy_prefix and s.startswith(" "):
            s = s[1:]
        return s

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)

    def id_to_piece(self, pid: int) -> str:
        return self.pieces[pid][0]

    def piece_to_id(self, piece: str) -> int:
        hit = self._vocab.get(piece)
        if hit is not None:
            return hit[0]
        for pid, (text, _, _) in enumerate(self.pieces):
            if text == piece:
                return pid
        return self.unk_id
