"""HF tokenizer delegate + factory (reference: python/hetu/data/tokenizers/
build_tokenizer.py — the reference exposes one build function over its
GPT2/SP/tiktoken/HF stacks; here the in-tree BPE is the no-dependency path
and transformers is the pretrained path, chosen explicitly)."""
from __future__ import annotations

from typing import List, Optional


class HFTokenizer:
    """Thin delegate to a transformers tokenizer — the EXPLICIT external
    dependency (round-1 review: the HF fallback used to be implicit)."""

    def __init__(self, name_or_path: str, **kw):
        try:
            from transformers import AutoTokenizer
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "HFTokenizer needs the `transformers` package; use the "
                "in-tree ByteLevelBPETokenizer for dependency-free runs"
            ) from e
        self._tok = AutoTokenizer.from_pretrained(name_or_path, **kw)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids) -> str:
        return self._tok.decode(ids)

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.convert_tokens_to_ids(token)


def build_tokenizer(kind: str, path: Optional[str] = None, **kw):
    """kind: "bpe" (in-tree byte-level BPE; path = saved vocab dir) |
    "hf" (pretrained via transformers; path = model name or dir) |
    "sp" (sentencepiece .model file, runtime-free loader) |
    "tiktoken" (tiktoken rank file)."""
    if kind == "bpe":
        from hetu_tpu.data.tokenizers.bpe import ByteLevelBPETokenizer
        if path is None:
            raise ValueError("bpe tokenizer needs path (saved vocab dir)")
        return ByteLevelBPETokenizer.load(path, **kw)
    if kind == "hf":
        if path is None:
            raise ValueError("hf tokenizer needs a model name or dir")
        return HFTokenizer(path, **kw)
    if kind == "sp":
        from hetu_tpu.data.tokenizers.sp_model import SentencePieceTokenizer
        if path is None:
            raise ValueError("sp tokenizer needs a .model file path")
        return SentencePieceTokenizer(path, **kw)
    if kind == "tiktoken":
        from hetu_tpu.data.tokenizers.tiktoken_bpe import TikTokenizer
        if path is None:
            raise ValueError("tiktoken tokenizer needs a rank file path")
        return TikTokenizer(path, **kw)
    raise ValueError(f"unknown tokenizer kind {kind!r} (bpe|hf|sp|tiktoken)")
