"""Byte-level BPE tokenizer, self-contained (train / save / load / encode /
decode — no network, no external tokenizer runtime).

Rebuild of the reference's vendored GPT2 BPE stack (reference: python/hetu/
data/tokenizers/ gpt2_tokenization.py semantics): byte-level pre-tokenization
(every byte representable, no <unk>), greedy merge application by learned
rank, optional special tokens.  File format matches the public GPT-2
convention — `vocab.json` (token -> id) + `merges.txt` (one merge pair per
line) — so pretrained GPT-2 vocabularies drop in unchanged.
"""
from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:                  # the GPT-2 split pattern needs unicode properties
    import regex as _re
    _PAT = _re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")
except ImportError:   # degraded but functional split
    import re as _re
    _PAT = _re.compile(r" ?\w+| ?[^\w\s]+|\s+")


def bytes_to_unicode() -> Dict[int, str]:
    """The reversible byte <-> printable-unicode table (public GPT-2
    convention): printable ASCII/latin bytes map to themselves, the rest to
    256+ offsets, so merges.txt stays human-readable and lossless."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_B2U = bytes_to_unicode()
_U2B = {u: b for b, u in _B2U.items()}


def _word_to_units(word: str) -> Tuple[str, ...]:
    return tuple(_B2U[b] for b in word.encode("utf-8"))


class ByteLevelBPETokenizer:
    """encode/decode with learned merges.

    vocab: unit-string -> id; merges: list of (a, b) in learned order."""

    def __init__(self, vocab: Dict[str, int],
                 merges: Sequence[Tuple[str, str]],
                 special_tokens: Optional[Sequence[str]] = None):
        self.vocab = dict(vocab)
        self.merges = list(merges)
        self.ranks = {tuple(m): i for i, m in enumerate(self.merges)}
        self.special_tokens = list(special_tokens or [])
        for tok in self.special_tokens:
            if tok not in self.vocab:
                self.vocab[tok] = len(self.vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self._cache: Dict[str, List[str]] = {}

    # -- core BPE -----------------------------------------------------------
    def _bpe(self, word: str) -> List[str]:
        if word in self._cache:
            return self._cache[word]
        units = list(_word_to_units(word))
        while len(units) > 1:
            cand = [(self.ranks.get((a, b)), i) for i, (a, b) in
                    enumerate(zip(units[:-1], units[1:]))]
            cand = [(r, i) for r, i in cand if r is not None]
            if not cand:
                break
            _, i = min(cand)
            units[i:i + 2] = [units[i] + units[i + 1]]
        self._cache[word] = units
        return units

    def encode(self, text: str) -> List[int]:
        out: List[int] = []
        for word in _PAT.findall(text):
            for unit in self._bpe(word):
                if unit in self.vocab:
                    out.append(self.vocab[unit])
                else:  # unseen unit: fall back to per-byte units
                    out.extend(self.vocab[u] for u in unit)
        return out

    def decode(self, ids: Iterable[int]) -> str:
        text = "".join(self.inv_vocab[i] for i in ids
                       if i in self.inv_vocab
                       and self.inv_vocab[i] not in self.special_tokens)
        data = bytes(_U2B[u] for u in text)
        return data.decode("utf-8", errors="replace")

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # -- training (reference trains offline; kept in-tree so tests and small
    # runs need no downloaded vocab) ---------------------------------------
    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 1024,
              special_tokens: Sequence[str] = ("<|endoftext|>",)
              ) -> "ByteLevelBPETokenizer":
        """Classic BPE: start from the 256 byte units, repeatedly merge the
        most frequent adjacent pair until vocab_size.

        Incremental: pair counts are adjusted only where a merge touches
        (with a pair->words index and a lazy max-heap for the argmax), so a
        merge costs O(occurrences), not O(corpus) — real vocab sizes train
        in seconds, not hours."""
        import heapq

        word_freq: Counter = Counter()
        for t in texts:
            word_freq.update(_PAT.findall(t))
        words = {w: list(_word_to_units(w)) for w in word_freq}

        pair_freq: Counter = Counter()
        pair_words: Dict[Tuple[str, str], set] = {}
        for w, units in words.items():
            f = word_freq[w]
            for p in zip(units[:-1], units[1:]):
                pair_freq[p] += f
                pair_words.setdefault(p, set()).add(w)

        heap = [(-c, p) for p, c in pair_freq.items()]
        heapq.heapify(heap)

        def bump(p, delta, w):
            pair_freq[p] += delta
            if delta > 0:
                pair_words.setdefault(p, set()).add(w)
                heapq.heappush(heap, (-pair_freq[p], p))

        vocab: Dict[str, int] = {u: i for i, u in
                                 enumerate(sorted(_B2U.values()))}
        merges: List[Tuple[str, str]] = []
        target = vocab_size - len(special_tokens)
        while len(vocab) < target and heap:
            # lazy heap: pop until the entry matches the live count
            neg, pair = heapq.heappop(heap)
            cnt = pair_freq.get(pair, 0)
            if -neg != cnt:
                if cnt > 0:
                    heapq.heappush(heap, (-cnt, pair))
                continue
            if cnt < 2:
                break
            a, b = pair
            ab = a + b
            merges.append(pair)
            vocab[ab] = len(vocab)
            # apply the merge only where it occurs, adjusting neighbors
            for w in pair_words.pop(pair, ()):
                units = words[w]
                f = word_freq[w]
                i = 0
                while i < len(units) - 1:
                    if units[i] != a or units[i + 1] != b:
                        i += 1
                        continue
                    if i > 0:
                        bump((units[i - 1], a), -f, w)
                        bump((units[i - 1], ab), f, w)
                    if i + 2 < len(units):
                        bump((b, units[i + 2]), -f, w)
                        bump((ab, units[i + 2]), f, w)
                    units[i:i + 2] = [ab]
            del pair_freq[pair]
        return cls(vocab, merges, special_tokens)

    # -- GPT-2 file format --------------------------------------------------
    def save(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "vocab.json"), "w") as f:
            json.dump(self.vocab, f, ensure_ascii=False)
        with open(os.path.join(directory, "merges.txt"), "w") as f:
            f.write("#version: 0.2\n")
            for a, b in self.merges:
                f.write(f"{a} {b}\n")

    @classmethod
    def load(cls, directory: str,
             special_tokens: Sequence[str] = ("<|endoftext|>",)
             ) -> "ByteLevelBPETokenizer":
        with open(os.path.join(directory, "vocab.json")) as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(os.path.join(directory, "merges.txt")) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        # pass requested specials through unchanged: __init__ appends any
        # that are missing from the vocab and keeps existing ids for the rest
        return cls(vocab, merges, list(special_tokens))
