"""Batch collation (reference: python/hetu/data/data_collator.py
DataCollatorForLanguageModel)."""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from hetu_tpu.data.bucket import pad_batch, pack_sequences


class DataCollatorForLanguageModel:
    """Collate tokenized sequences into fixed-shape LM batches.

    packing=False: one sequence per row, padded (reference pad_data).
    packing=True: greedy first-fit packing (reference pack_data).
    """

    def __init__(self, max_seq_len: int, pad_id: int = 0, packing: bool = False):
        self.max_seq_len = max_seq_len
        self.pad_id = pad_id
        self.packing = packing

    def __call__(self, seqs: Sequence[np.ndarray],
                 num_rows: int | None = None) -> Dict[str, np.ndarray]:
        if self.packing:
            return pack_sequences(seqs, self.max_seq_len, self.pad_id,
                                  num_packed=num_rows)
        return pad_batch(seqs, self.max_seq_len, self.pad_id)
